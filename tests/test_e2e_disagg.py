"""e2e: REAL disaggregated serving — a DisaggregatedSet launches prefill and
decode as separate OS processes; a prompt flows client -> prefill (KV cache
bundle) -> decode -> client with KV BYTES OVER TCP ONLY, the decode worker
discovering prefill's endpoint from the DS's revision-aware `-prv` service
record through the API server (VERDICT r3 #5; ref
service_manager.go:126-163). Result byte-identical to a single-engine
oracle (BASELINE config #5, the llm-d shape). Zero shared-filesystem
coupling: the only cross-process channels are the HTTP API and the KV
sockets."""

import socket
import sys
import time

import numpy as np
import pytest

from lws_tpu.api.disagg import (
    DisaggregatedRoleSpec,
    DisaggregatedSet,
    DisaggregatedSetSpec,
    LeaderWorkerSetTemplateSpec,
)
from lws_tpu.api.pod import Container, EnvVar, PodSpec, PodTemplateSpec
from lws_tpu.api.types import LeaderWorkerSetSpec, LeaderWorkerTemplate
from lws_tpu.client import RemoteClient
from lws_tpu.core.store import new_meta
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.server import ApiServer
from lws_tpu.serving import kv_transport as kt
from tests.test_e2e_local import make_backend

DECODE_STEPS = 6


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def role_spec(role: str, kv_port: int, api_url: str, extra_env: list | None = None,
              metrics_port: int | None = None):
    telemetry_env = (
        [EnvVar("LWS_TPU_METRICS_PORT", str(metrics_port))]
        if metrics_port is not None else []
    )
    return DisaggregatedRoleSpec(
        name=role,
        replicas=1,
        template=LeaderWorkerSetTemplateSpec(
            spec=LeaderWorkerSetSpec(
                leader_worker_template=LeaderWorkerTemplate(
                    size=1,
                    worker_template=PodTemplateSpec(
                        spec=PodSpec(
                            containers=[
                                Container(
                                    name=role,
                                    command=[
                                        sys.executable, "-m", "lws_tpu.serving.disagg_worker",
                                        role, "--transport", "tcp", "--steps", str(DECODE_STEPS),
                                    ],
                                    env=[
                                        EnvVar("JAX_PLATFORMS", "cpu"),
                                        # containerPort analog: the declared KV
                                        # endpoint port the service routes to.
                                        EnvVar("LWS_TPU_KV_PORT", str(kv_port)),
                                        EnvVar("LWS_TPU_API", api_url),
                                    ] + telemetry_env + list(extra_env or []),
                                )
                            ]
                        )
                    ),
                )
            )
        ),
    )


def _run_disagg_e2e(tmp_path, extra_env: list | None = None,
                    backend_env: dict | None = None,
                    expect_streamed: bool = False,
                    run_scenario: bool = False):
    from lws_tpu.core import trace as _trace

    _trace.TRACER.enabled = True
    _trace.TRACER.sample_rate = 1.0
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    api_url = f"http://127.0.0.1:{api.port}"
    prefill_port, decode_port = free_port(), free_port()
    prefill_metrics, decode_metrics = free_port(), free_port()

    ds = DisaggregatedSet(
        meta=new_meta("llmd"),
        spec=DisaggregatedSetSpec(
            roles=[
                role_spec("prefill", prefill_port, api_url, extra_env,
                          metrics_port=prefill_metrics),
                role_spec("decode", decode_port, api_url, extra_env,
                          metrics_port=decode_metrics),
            ]
        ),
    )
    backend = make_backend(cp, tmp_path, extra_env=backend_env)
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    client = RemoteClient(api_url)

    try:
        cp.create(ds)
        cp.run_until_stable()
        pods = sorted(p.meta.name for p in cp.store.list("Pod"))
        assert len(pods) == 2, pods  # one prefill, one decode leader

        # The client discovers BOTH endpoints exactly like the decode worker
        # does: through the -prv service records, via the HTTP API.
        deadline = time.time() + 150
        endpoints = {}
        while time.time() < deadline and len(endpoints) < 2:
            backend.poll_all()
            cp.run_until_stable()
            for role in ("prefill", "decode"):
                if role not in endpoints:
                    ep = kt.discover_role_endpoint(client, "default", "llmd", role)
                    if ep is not None:
                        endpoints[role] = ep
            time.sleep(0.3)
        assert len(endpoints) == 2, f"-prv endpoints never published: {endpoints}"

        # Trace spine, client leg: the request root span grafts onto the
        # DS's latest reconcile root — the resulting tree spans controller
        # reconcile -> admission -> prefill -> KV handoff -> decode across
        # three processes (the workers' subtrees ride back with the result).
        from lws_tpu.core import trace

        ds_reconciles = [
            s for s in trace.TRACER.spans()
            if s["name"] == "reconcile"
            and s["attrs"].get("controller") == "disaggregatedset"
            and s["attrs"].get("object") == "llmd"
        ]
        assert ds_reconciles, "no DS reconcile root spans recorded"
        reconcile_span = ds_reconciles[-1]

        # The pod goes Ready when its process is alive, which can precede the
        # worker binding its KV port (engine compile) — dial with retries,
        # exactly like a production client behind a service would.
        prompt = np.array([5, 9, 2, 11, 7], dtype=np.int32)
        request_span = trace.TRACER.span(
            "serve.request", parent={
                "trace_id": reconcile_span["trace_id"],
                "span_id": reconcile_span["span_id"],
            },
            role="client", request_id="req1",
        )
        with request_span:
            while time.time() < deadline:
                try:
                    kt.submit_prompt(
                        endpoints["prefill"], "req1", kt.arrays_to_bytes(prompt=prompt)
                    )
                    break
                except OSError:
                    time.sleep(0.5)
            else:
                pytest.fail("prefill endpoint never accepted the prompt")

        result = meta = None
        while time.time() < deadline:
            backend.poll_all()
            try:
                got = kt.pull_result(endpoints["decode"], "req1")
            except OSError:
                got = None
            if got is not None:
                meta, payload = got
                result = kt.bytes_to_arrays(payload)["tokens"]
                break
            time.sleep(0.5)
        assert result is not None, "no decode result over TCP"

        # Per-handoff cost breakdown rides back with the result (VERDICT r4
        # #5): prefill-side gather + decode-side deserialize/reshard/decode
        # timings and the wire byte count.
        handoff = meta.get("handoff")
        assert handoff is not None, meta
        for key in ("bundle_bytes", "prefill_s", "gather_s",
                    "deserialize_s", "reshard_s", "decode_s"):
            assert key in handoff, (key, handoff)
        # The reported wire size must cover the real pos-truncated K/V rows
        # (prompt-length tokens, every layer, K+V) — not just be positive.
        from lws_tpu.models.flagship import flagship_config, kv_row_bytes

        cfg = flagship_config("smoke", max_seq_len=32)
        assert handoff["bundle_bytes"] >= len(prompt) * kv_row_bytes(cfg), handoff
        if expect_streamed:
            # The streamed path really ran: chunk count matches the knob
            # (ceil(5 / 2) chunks for the 5-token prompt) on both the
            # prefill-side record and the decode-side stats merge.
            assert handoff.get("streamed") is True, handoff
            assert handoff.get("chunks") == 3, handoff
        else:
            assert "streamed" not in handoff, handoff

        # One CONNECTED span tree across three processes: controller
        # reconcile (control plane) -> client request -> prefill admission +
        # KV gather (prefill worker) -> deserialize/reshard/decode dispatch
        # (decode worker), reassembled from the records that rode back with
        # the result, and JSONL round-trippable.
        from lws_tpu.core.trace import Tracer, connected_tree

        remote_spans = meta.get("spans")
        assert remote_spans, meta
        tree = [reconcile_span, request_span.to_dict()] + list(remote_spans)
        assert connected_tree(tree), [
            (s["name"], s["trace_id"], s["parent_id"]) for s in tree
        ]
        names = {s["name"] for s in tree}
        assert {
            "reconcile", "serve.request", "serve.prefill", "kv.gather",
            "kv.deserialize", "kv.reshard", "serve.decode_dispatch",
        } <= names, names
        # The span subtree SUBSUMES the handoff record: every wire timing is
        # a span duration, and the gather span carries the pos/bytes attrs.
        gather = next(s for s in tree if s["name"] == "kv.gather")
        assert gather["attrs"]["bundle_bytes"] == handoff["bundle_bytes"]
        assert gather["attrs"]["pos"] == handoff["pos"]
        exported = str(tmp_path / "request_trace.jsonl")
        collector = Tracer()
        for s in tree:
            collector.record(s)
        assert collector.export_jsonl(exported) == len(tree)
        assert connected_tree(Tracer.read_jsonl(exported))

        # Live observability surface: /metrics renders parser-valid
        # Prometheus text including the new result-labeled reconcile
        # histogram and rollout gauge; /debug/traces serves recent spans.
        import urllib.request

        from tests.test_dns_metrics import parse_exposition

        with urllib.request.urlopen(f"{api_url}/metrics", timeout=10) as resp:
            fams = parse_exposition(resp.read().decode())
        assert fams["lws_reconcile_duration_seconds"]["type"] == "histogram"
        assert any(
            labels.get("result") == "success"
            for _, labels, _ in fams["lws_reconcile_duration_seconds"]["samples"]
        )
        assert fams["lws_rollout_progress"]["type"] == "gauge"
        with urllib.request.urlopen(
            f"{api_url}/debug/traces?limit=50", timeout=10
        ) as resp:
            import json as _json

            debug_spans = _json.loads(resp.read().decode())
        assert debug_spans and any(s["name"] == "reconcile" for s in debug_spans)

        # Loadgen scenario over the LIVE pair (ISSUE 11): a seeded
        # open-loop schedule with two workload classes drives the same
        # client path — the class labels ride the frame meta to BOTH
        # workers' SLO/goodput series, and the fixed seed reproduces the
        # request schedule end to end.
        if run_scenario:
            from lws_tpu import loadgen

            scen_spec = {
                "name": "e2e_mix", "horizon_s": 0.4, "max_len": 16,
                "vocab": 64,
                "arrivals": {"process": "poisson", "rate_rps": 10.0},
                "classes": [
                    {"name": "premium", "weight": 0.5,
                     "prompt_len": {"kind": "uniform", "lo": 4, "hi": 6},
                     "output_len": 2,
                     "targets": {"ttft_s": 30.0, "itl_s": 30.0,
                                 "queue_wait_s": 30.0}},
                    {"name": "chat", "weight": 0.5,
                     "prompt_len": {"kind": "uniform", "lo": 4, "hi": 6},
                     "output_len": 2,
                     "targets": {"ttft_s": 30.0, "itl_s": 30.0,
                                 "queue_wait_s": 30.0}},
                ],
            }
            schedule = loadgen.build_schedule(scen_spec, seed=5)
            # Acceptance: a fixed seed reproduces an identical schedule.
            assert loadgen.schedule_digest(schedule) == \
                loadgen.schedule_digest(loadgen.build_schedule(scen_spec, seed=5))
            assert {r.klass for r in schedule} == {"premium", "chat"}
            scen_result = loadgen.run_schedule(
                schedule,
                loadgen.DisaggTarget(endpoints["prefill"], endpoints["decode"]),
                max_wall_s=90.0,
            )
            scen_report = loadgen.summarize(
                scen_result, loadgen.class_targets(scen_spec),
                scen_spec["horizon_s"], "e2e_mix", 5,
            )
            assert scen_report["all"]["completed"] == len(schedule), scen_report
            # The decode worker's --steps decides tokens per request.
            assert scen_report["all"]["tokens"] == \
                len(schedule) * (DECODE_STEPS + 1), scen_report
            frame = loadgen.render_report(scen_report)
            assert "premium" in frame and "chat" in frame, frame

        # Fleet telemetry plane (ISSUE 4): the control plane scrapes BOTH
        # worker processes' /metrics (addresses from pod records, ports from
        # the pod-declared LWS_TPU_METRICS_PORT) and serves ONE merged
        # exposition with instance/role/revision labels. The workers' SLO
        # histograms ride in — TTFT from the prefill leg, ITL from the
        # decode leg — with trace exemplars on the bucket lines.
        fleet = fleet_text = None
        # OpenMetrics negotiation: exemplars ride only for clients that ask
        # (a classic Prometheus text parser rejects the suffix).
        fleet_req = urllib.request.Request(
            f"{api_url}/metrics/fleet",
            headers={"Accept": "application/openmetrics-text"},
        )
        # Control-plane registries ride the fleet view too, and the API
        # server's dry-run recommender publishes role-labelled gauges
        # (`serving_scale_recommendation{role=...}`) from the control-plane
        # instance — the worker-pod assertions below must not count them.
        while time.time() < deadline:
            with urllib.request.urlopen(fleet_req, timeout=10) as resp:
                fleet_text = resp.read().decode()
            fleet = parse_exposition(fleet_text)
            roles = {
                labels.get("role")
                for fam in fleet.values()
                for _, labels, _ in fam["samples"]
                if labels.get("instance") != "control-plane"
            }
            if {"prefill", "decode"} <= roles:
                break
            time.sleep(1.1)  # collector cache TTL is 1s
        by_role = {}
        for fam in fleet.values():
            for _, labels, _ in fam["samples"]:
                if labels.get("role") \
                        and labels.get("instance") != "control-plane":
                    by_role.setdefault(labels["role"], set()).add(labels["instance"])
        assert {"prefill", "decode"} <= set(by_role), by_role
        assert by_role["prefill"].isdisjoint(by_role["decode"])  # distinct pods
        assert all(len(v) == 1 for v in by_role.values()), by_role
        # Prefill leg recorded TTFT (+ the socket queue wait), decode ITL.
        assert any(
            labels.get("role") == "prefill" and labels.get("engine") == "disagg"
            and name.endswith("_count") and value > 0
            for name, labels, value in fleet["serving_ttft_seconds"]["samples"]
        ), fleet["serving_ttft_seconds"]["samples"]
        assert any(
            labels.get("role") == "decode" and labels.get("engine") == "disagg"
            and name.endswith("_count") and value > 0
            for name, labels, value in fleet["serving_itl_seconds"]["samples"]
        ), fleet["serving_itl_seconds"]["samples"]
        if not expect_streamed:
            # Monolithic-path journey regression: finish() must run AFTER
            # kv.gather closes, or the gather leg never joins req1's vault
            # journey on the prefill worker (the streamed path is covered
            # by the forensic block below). req1 is healthy, so it rides
            # the slowest-K healthy retention class.
            import urllib.error as _urlerr

            mono = mono_leg = None
            mono_deadline = time.time() + 60
            while time.time() < mono_deadline:
                try:
                    with urllib.request.urlopen(
                        f"{api_url}/debug/request/req1", timeout=10
                    ) as resp:
                        mono = _json.loads(resp.read().decode())
                except _urlerr.HTTPError:
                    mono = None
                if mono is not None:
                    mono_leg = next(
                        (leg for leg in mono.get("legs", [])
                         if leg["labels"].get("role") == "prefill"
                         and leg["journey"].get("completed")), None)
                    if mono_leg is not None:
                        break
                time.sleep(0.5)
            assert mono_leg is not None, "prefill leg journey never joined"
            mono_gather = {
                s.get("instance") for s in mono["spans"]
                if s["name"] == "kv.gather"
            }
            assert mono_gather & by_role["prefill"], [
                (s["name"], s.get("instance")) for s in mono["spans"]
            ]
        if run_scenario:
            # ISSUE 11 acceptance: the goodput ledger and class-granular
            # attainment ride the MERGED fleet exposition during a live
            # disagg scenario run — both workload classes, both workers.
            goodput = fleet.get("serving_goodput_tokens_total", {})
            klasses = {
                labels.get("klass")
                for _, labels, value in goodput.get("samples", [])
                if labels.get("engine") == "disagg" and value > 0
            }
            assert {"premium", "chat"} <= klasses, goodput.get("samples")
            assert any(
                labels.get("engine") == "disagg" and labels.get("klass")
                for _, labels, _ in
                fleet.get("serving_slo_attainment", {}).get("samples", [])
            ), fleet.get("serving_slo_attainment", {}).get("samples")
            assert any(
                labels.get("engine") == "disagg"
                and labels.get("klass") in ("premium", "chat") and value > 0
                for _, labels, value in
                fleet.get("serving_tokens_total", {}).get("samples", [])
            ), fleet.get("serving_tokens_total", {}).get("samples")
        # Exemplars survive scrape + merge: a breach bucket links to a trace.
        assert 'trace_id="' in fleet_text
        # The control plane's own registries merged in under their instance.
        assert any(
            labels.get("instance") == "control-plane"
            for _, labels, _ in fleet["lws_reconcile_total"]["samples"]
        )

        # The exemplar RESOLVES: pull the prefill TTFT exemplar's trace id
        # out of the merged text and find its span tree in the emitting
        # worker's own /debug/traces — the fleet-surface -> trace-backend
        # round trip an operator walks after an SLO breach.
        from lws_tpu.core.metrics import parse_exposition as parse_prod

        prod_fams = parse_prod(fleet_text)
        exemplar_ids = {
            ex.split('trace_id="')[1].split('"')[0]
            for name, labels, _, ex in prod_fams["serving_ttft_seconds"]["samples"]
            if labels.get("role") == "prefill" and 'trace_id="' in ex
        }
        assert exemplar_ids, prod_fams["serving_ttft_seconds"]["samples"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{prefill_metrics}/debug/traces?limit=512",
            timeout=10,
        ) as resp:
            worker_spans = _json.loads(resp.read().decode())
        known = {s["trace_id"] for s in worker_spans}
        assert exemplar_ids & known, (exemplar_ids, known)

        # `lws-tpu top` renders the operator view from this exact surface:
        # both worker instances appear as rows of the fleet table.
        from lws_tpu.cli import render_top

        frame = render_top(prod_fams)
        assert frame.startswith("FLEET"), frame
        for instance in by_role["prefill"] | by_role["decode"]:
            assert instance in frame, frame

        # ISSUE 13: request-journey forensics across the three REAL
        # processes. Arm a one-shot receive-side stream tear on the DECODE
        # worker (the at-least-once retry leg), then send one request of
        # the env-targeted "forensic" class (TTFT budget = 1 microsecond,
        # so the prefill leg ALWAYS breaches and the tail vault ALWAYS
        # retains it). Assertions: one connected fleet-joined tree, the KV
        # chunk timeline, the torn-stream/requeue retry events, breach
        # exemplar -> retained journey resolution, and an `lws-tpu
        # explain` render whose verdict names the breaching phase.
        if run_scenario:
            import urllib.error

            arm_tear = _json.dumps(
                {"arm": {"kv.stream.recv_chunk": "drop:1"}}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{decode_metrics}/debug/faults",
                data=arm_tear, headers={"Content-Type": "application/json"},
            ), timeout=10) as resp:
                assert resp.status == 200
            tail_prompt = np.array([7, 3, 9, 1, 4], dtype=np.int32)
            # A fresh ROOT trace (req1 already proved reconcile grafting):
            # the forensic request owns its trace id, so the SLO exemplar's
            # trace id below resolves unambiguously to THIS journey.
            tail_span = trace.TRACER.span(
                "serve.request", role="client", request_id="req-tail",
            )
            with tail_span:
                kt.submit_prompt(
                    endpoints["prefill"], "req-tail",
                    kt.arrays_to_bytes(prompt=tail_prompt), klass="forensic",
                )
            tail_result = None
            # Fresh budget: the test-global deadline is mostly spent by now
            # (startup + fleet waits + the scenario run above).
            tail_deadline = time.time() + 60
            while time.time() < tail_deadline and tail_result is None:
                backend.poll_all()
                try:
                    got_tail = kt.pull_result(endpoints["decode"], "req-tail")
                except OSError:
                    got_tail = None
                if got_tail is not None:
                    tail_result = kt.bytes_to_arrays(got_tail[1])["tokens"]
                    break
                time.sleep(0.5)
            assert tail_result is not None, \
                "req-tail never completed across the torn-stream retry"

            # The fleet-joined journey by REQUEST id: ONE connected tree
            # across client + prefill + decode, with the wire chunk
            # timeline and the retry leg's events.
            joined = None
            journey_deadline = time.time() + 60
            while time.time() < journey_deadline:
                try:
                    with urllib.request.urlopen(
                        f"{api_url}/debug/request/req-tail", timeout=10
                    ) as resp:
                        joined = _json.loads(resp.read().decode())
                except urllib.error.HTTPError:
                    joined = None
                if joined is not None and joined.get("connected") and \
                        "retried" in (joined.get("flags") or []):
                    break
                time.sleep(0.5)
            assert joined is not None, "fleet join never found req-tail"
            assert joined["connected"] is True, [
                (s["name"], s.get("instance"), s["trace_id"], s["parent_id"])
                for s in joined["spans"]
            ]
            leg_instances = {s.get("instance") for s in joined["spans"]}
            assert by_role["prefill"] <= leg_instances, leg_instances
            assert by_role["decode"] <= leg_instances, leg_instances
            names = {s["name"] for s in joined["spans"]}
            assert {"serve.request", "serve.prefill", "kv.gather",
                    "kv.deserialize", "serve.decode_dispatch"} <= names, names
            # Tail retention verdicts: breached (forensic TTFT budget) AND
            # retried (the armed stream tear).
            assert "breached" in joined["flags"], joined["flags"]
            assert "retried" in joined["flags"], joined["flags"]
            kinds = {e["kind"] for e in joined["events"]}
            assert kinds & {"kv_stream_torn", "kv_requeue"}, kinds
            # The KV chunk timeline rode the journey: 3 stream chunks
            # (ceil(5 tokens / chunk=2)) with arrival stamps, plus the
            # produce-side twin from the prefill leg.
            chunks = joined["annotations"].get("chunks")
            assert chunks is not None and len(chunks) == 3, chunks
            assert all("t_s" in c and c["bytes"] > 0 for c in chunks), chunks
            assert len(joined["annotations"].get("chunks_produced", [])) == 3
            # The prefill leg's timeline carries the phase values + the
            # forensic targets the verdict grades against.
            prefill_leg = next(
                leg for leg in joined["legs"]
                if leg["labels"].get("role") == "prefill"
            )
            tlv = prefill_leg["journey"]["timeline"]
            assert tlv["ttft_s"] > tlv["targets"]["ttft_s"], tlv

            # The breach exemplar RESOLVES to the retained journey: pull a
            # forensic-class TTFT exemplar trace id off the merged fleet
            # exposition and ask the fleet-joined endpoint for it — the
            # span ring may wrap, the vault must not.
            forensic_ids = set()
            while time.time() < journey_deadline and not forensic_ids:
                with urllib.request.urlopen(fleet_req, timeout=10) as resp:
                    tail_text = resp.read().decode()
                tfams = parse_prod(tail_text)
                forensic_ids = {
                    ex.split('trace_id="')[1].split('"')[0]
                    for name, labels, _, ex in
                    tfams.get("serving_ttft_seconds", {}).get("samples", [])
                    if labels.get("klass") == "forensic"
                    and 'trace_id="' in ex
                }
                if not forensic_ids:
                    time.sleep(1.1)  # collector cache TTL is 1s
            assert forensic_ids, "forensic TTFT exemplar never scraped"
            resolved = None
            for ex_tid in forensic_ids:
                try:
                    with urllib.request.urlopen(
                        f"{api_url}/debug/request/{ex_tid}", timeout=10
                    ) as resp:
                        cand = _json.loads(resp.read().decode())
                except urllib.error.HTTPError:
                    continue
                if "breached" in (cand.get("flags") or []):
                    resolved = cand
                    break
            assert resolved is not None, \
                "breach exemplar did not resolve to a retained journey"
            assert any(
                leg["journey"].get("id") == "req-tail"
                for leg in resolved["legs"]
            ), resolved["legs"]

            # `lws-tpu explain` renders the whole story: cross-process
            # waterfall + wire chunks + retry events + a verdict naming
            # the phase (ttft) that blew the budget.
            import io as _io
            from contextlib import redirect_stdout

            from lws_tpu import cli as climod

            buf = _io.StringIO()
            with redirect_stdout(buf):
                rc = climod.main([
                    "explain", "req-tail",
                    "--server", f"127.0.0.1:{api.port}",
                ])
            assert rc == 0
            explain_frame = buf.getvalue()
            assert "WATERFALL" in explain_frame, explain_frame
            assert "wire chunks: 3" in explain_frame, explain_frame
            assert "VERDICT: BREACHED" in explain_frame, explain_frame
            assert "ttft" in explain_frame, explain_frame
            # The index surface lists it among the breached worst.
            buf = _io.StringIO()
            with redirect_stdout(buf):
                rc = climod.main([
                    "explain", "--breached",
                    "--server", f"127.0.0.1:{api.port}",
                ])
            assert rc == 0
            assert "req-tail" in buf.getvalue(), buf.getvalue()

            # ISSUE 20: device-runtime forensics across the live pair.
            # The decode worker paid its first-call compile for the
            # bundle-decode dispatch under the `disagg.decode` compile
            # site while serving req1 (the process's first bundle), so
            # the record carries that request id. Three surfaces must
            # agree: the worker's own /debug/compile ledger, the fleet
            # compile fold on the control plane, and req1's fleet-joined
            # journey (slowest-K retention keeps the healthy first
            # request; the compile annotation rode VAULT.annotate from
            # the site teardown).
            dev_deadline = time.time() + 60
            worker_compile = None
            while time.time() < dev_deadline:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{decode_metrics}/debug/compile",
                    timeout=10,
                ) as resp:
                    worker_compile = _json.loads(resp.read().decode())
                if any(
                    r.get("executable") == "disagg.decode"
                    for r in worker_compile.get("records", [])
                ):
                    break
                time.sleep(0.5)
            assert worker_compile is not None
            assert worker_compile.get("armed") is True, worker_compile
            dec_records = [
                r for r in worker_compile.get("records", [])
                if r.get("executable") == "disagg.decode"
            ]
            assert dec_records, worker_compile
            assert dec_records[0]["kind"] == "first", dec_records
            assert dec_records[0]["request_id"] == "req1", dec_records
            assert dec_records[0]["seconds"] > 0, dec_records

            # The same record rides the fleet fold: per-executable sums
            # plus how many instances compiled it, with the decode
            # worker present among the scraped instances.
            with urllib.request.urlopen(
                f"{api_url}/debug/compile/fleet?limit=64", timeout=10
            ) as resp:
                fleet_compile = _json.loads(resp.read().decode())
            folded = fleet_compile["executables"].get("disagg.decode")
            assert folded is not None, fleet_compile["executables"]
            assert folded["first"] >= 1, folded
            assert folded["instances"] >= 1, folded
            fold_instances = {
                i["labels"].get("instance")
                for i in fleet_compile.get("instances", [])
            }
            assert by_role["decode"] & fold_instances, fold_instances

            # Compile-blame journey: the fleet join merges the decode
            # leg's `compiles` annotation to the top level, naming the
            # executable and the seconds the request spent compiling.
            dev_joined = None
            while time.time() < dev_deadline:
                try:
                    with urllib.request.urlopen(
                        f"{api_url}/debug/request/req1", timeout=10
                    ) as resp:
                        dev_joined = _json.loads(resp.read().decode())
                except urllib.error.HTTPError:
                    dev_joined = None
                if dev_joined is not None and \
                        (dev_joined.get("annotations") or {}).get("compiles"):
                    break
                time.sleep(0.5)
            assert dev_joined is not None, "fleet join never found req1"
            blamed = (dev_joined.get("annotations") or {}).get("compiles")
            assert blamed, dev_joined.get("annotations")
            assert any(
                c.get("executable") == "disagg.decode"
                and c.get("seconds", 0) > 0
                for c in blamed
            ), blamed

        # ISSUE 12 satellite: counter resets + series retirement across a
        # REAL worker restart, as seen by the history plane. Sample the
        # merged fleet exposition into a HistoryRing, kill the prefill
        # worker through its own fault surface (exit at the next handoff),
        # let the restart policy bring up a fresh process whose counters
        # restart near zero, and sample again: no counter-backed window
        # may yield a negative rate, and the dead process's retired
        # class-labelled attainment series (PR 11's clear_gauge contract)
        # must stay retired through the sampling cadence and the fleet
        # cache TTL — frozen history, never current again.
        if run_scenario:
            from lws_tpu.obs import rate as history_rate
            from lws_tpu.obs.history import HistoryRing

            ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
            ring.ingest(fleet_text, now=0.0)
            prefill_instance = next(iter(by_role["prefill"]))

            def _ttft_count(fams):
                acc = 0.0
                for name, labels, value, _ in \
                        fams.get("serving_ttft_seconds", {}).get("samples", []):
                    if name == "serving_ttft_seconds_count" \
                            and labels.get("instance") == prefill_instance:
                        acc += value
                return acc

            def _prefill_wire_bytes(fams):
                for name, labels, value, _ in \
                        fams.get("serving_kv_transfer_bytes_total", {}).get("samples", []):
                    if name == "serving_kv_transfer_bytes_total" \
                            and labels.get("instance") == prefill_instance \
                            and labels.get("role") == "prefill":
                        return labels, value
                return None, None

            pre_count = _ttft_count(prod_fams)
            assert pre_count > 1, prod_fams.get("serving_ttft_seconds")
            _, pre_wire = _prefill_wire_bytes(prod_fams)
            assert pre_wire, "prefill send leg never metered its wire bytes"
            retired_keys = [
                (name, tuple(sorted(labels.items())))
                for name, labels, _, _pts, _ in ring.series("serving_slo_attainment")
                if labels.get("instance") == prefill_instance
                and labels.get("klass")
            ]
            assert retired_keys, "scenario left no class-labelled attainment"

            arm = _json.dumps(
                {"arm": {"disagg.prefill.handoff": "exit:1"}}
            ).encode()
            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{prefill_metrics}/debug/faults", data=arm,
                headers={"Content-Type": "application/json"},
            ), timeout=10) as resp:
                assert resp.status == 200
            # The next prompt kills prefill mid-handoff; the at-least-once
            # resubmit contract delivers it through the restarted
            # replacement (same pod name -> same instance label).
            restart_deadline = time.time() + 120
            prompt2 = np.array([3, 1, 4, 1, 5], dtype=np.int32)
            result2 = None
            while time.time() < restart_deadline and result2 is None:
                backend.poll_all()
                cp.run_until_stable()
                try:
                    kt.submit_prompt(endpoints["prefill"], "req-reset",
                                     kt.arrays_to_bytes(prompt=prompt2))
                except OSError:
                    time.sleep(0.5)  # replacement still compiling/binding
                    continue
                poll_until = time.time() + 6
                while time.time() < poll_until:
                    backend.poll_all()
                    try:
                        got2 = kt.pull_result(endpoints["decode"], "req-reset")
                    except OSError:
                        got2 = None
                    if got2 is not None:
                        result2 = kt.bytes_to_arrays(got2[1])["tokens"]
                        break
                    time.sleep(0.5)
            assert result2 is not None, "request never completed across restart"

            # Wait out the fleet cache TTL until the scrape shows the
            # REPLACEMENT (its ttft count restarted below the old one).
            post_fams = None
            while time.time() < restart_deadline:
                with urllib.request.urlopen(fleet_req, timeout=10) as resp:
                    new_text = resp.read().decode()
                post_fams = parse_prod(new_text)
                if 0 < _ttft_count(post_fams) < pre_count:
                    break
                time.sleep(1.1)  # collector cache TTL is 1s
            else:
                pytest.fail("restarted worker never re-entered the fleet scrape")
            ring.ingest(new_text, now=10.0)

            # (a) Reset awareness: every counter series' stored values are
            # monotone and every window rates non-negative — including the
            # series whose raw value just fell across the restart.
            for name, labels, kind, pts, _last in ring.series():
                if kind != "counter" or len(pts) < 2:
                    continue
                assert all(b >= a for (_, a), (_, b) in zip(pts, pts[1:])), \
                    (name, labels, pts)
                r = history_rate(pts, now=10.0)
                assert r is not None and r >= 0.0, (name, labels, pts)
            # The replacement's wire-bytes counter RAW value really fell
            # (it restarted from zero and sent one bundle)...
            wire_labels, post_wire = _prefill_wire_bytes(post_fams)
            assert post_wire and post_wire < pre_wire, (pre_wire, post_wire)
            # ...while the ring's reset-adjusted series kept rising.
            reset_pts = ring.window("serving_kv_transfer_bytes_total",
                                    wire_labels)
            assert len(reset_pts) == 2 and reset_pts[1][1] > reset_pts[0][1], \
                reset_pts

            # (b) Retirement: the dead process's class-labelled attainment
            # series are ABSENT from the post-restart scrape, absent from
            # the ring's live set, and their retained tails froze at the
            # pre-restart sample — history, never resurrected as current.
            post_attain = {
                (name, tuple(sorted(labels.items())))
                for name, labels, _v, _ in
                post_fams.get("serving_slo_attainment", {}).get("samples", [])
            }
            live = ring.live_keys()
            for key in retired_keys:
                assert key not in post_attain, key
                assert key not in live, key
                tail = ring.window(key[0], dict(key[1]))
                assert tail and tail[-1][0] == 0.0, (key, tail)

        # Oracle: the same model end-to-end in one engine.
        from lws_tpu.serving.disagg_worker import build_engine

        engine = build_engine(batch=1, max_len=32)
        oracle = engine.generate(
            np.asarray(prompt).reshape(1, -1), max_new_tokens=DECODE_STEPS + 1
        )
        np.testing.assert_array_equal(result[0], np.asarray(oracle.tokens)[0])
    finally:
        backend.shutdown()
        api.stop()


def test_disaggregated_prefill_decode_over_tcp_streamed(tmp_path):
    """The primary e2e now rides the STREAMED handoff (ISSUE 10):
    LWS_TPU_KV_CHUNK=2 chunks the 5-token prompt into 3 position ranges
    that ship while prefill still computes; tokens must stay byte-identical
    to the single-engine oracle. (The tp e2e below keeps the monolithic
    single-shot path covered — LWS_TPU_KV_CHUNK=0 — so BOTH transfer
    shapes run end to end across real processes.)"""
    _run_disagg_e2e(
        tmp_path,
        extra_env=[
            EnvVar("LWS_TPU_KV_CHUNK", "2"),
            # ISSUE 13: the "forensic" class's 1-microsecond TTFT budget
            # guarantees its one request breaches server-side and is
            # retained by the tail vault (the scenario's premium/chat
            # classes keep their generous targets — goodput asserts hold).
            EnvVar("LWS_TPU_SLO_CLASS_TARGETS",
                   '{"forensic": {"ttft_s": 0.000001, "itl_s": 30.0, '
                   '"queue_wait_s": 30.0}}'),
        ],
        expect_streamed=True,
        # ISSUE 11: a seeded two-class loadgen scenario runs over the live
        # pair mid-test; goodput + class-granular attainment must ride the
        # merged fleet exposition.
        run_scenario=True,
    )


def test_disaggregated_tp_sharded_over_tcp(tmp_path):
    """tp=2 prefill -> TCP -> tp=2 decode (VERDICT r3 next #3): each worker
    builds its engine on a 2-device tp mesh (params + cache over 'tp'), the
    bundle is host-gathered + pos-truncated on the wire, re-sharded onto the
    decode mesh — tokens identical to the single-device oracle."""
    _run_disagg_e2e(
        tmp_path,
        # LWS_TPU_KV_CHUNK=0 pins the monolithic single-shot oracle path.
        extra_env=[EnvVar("LWS_TPU_TP", "2"), EnvVar("LWS_TPU_KV_CHUNK", "0")],
        # The harness's env_overrides win over pod-declared env (it forces
        # JAX_PLATFORMS=cpu the same way), so the device count rides there.
        backend_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
    )
