"""e2e: REAL disaggregated serving — a DisaggregatedSet launches prefill and
decode as separate OS processes; a prompt flows prompt -> prefill (KV cache
handoff bundle) -> decode -> tokens, and the result is byte-identical to a
single-engine oracle (BASELINE config #5, the llm-d shape)."""

import os
import sys
import time

import numpy as np
import pytest

from lws_tpu.api.disagg import (
    DisaggregatedRoleSpec,
    DisaggregatedSet,
    DisaggregatedSetSpec,
    LeaderWorkerSetTemplateSpec,
)
from lws_tpu.api.pod import Container, EnvVar, PodSpec, PodTemplateSpec
from lws_tpu.api.types import LeaderWorkerSetSpec, LeaderWorkerTemplate
from lws_tpu.core.store import new_meta
from lws_tpu.runtime import ControlPlane
from tests.test_e2e_local import make_backend

DECODE_STEPS = 6


def role_spec(role: str, handoff: str):
    return DisaggregatedRoleSpec(
        name=role,
        replicas=1,
        template=LeaderWorkerSetTemplateSpec(
            spec=LeaderWorkerSetSpec(
                leader_worker_template=LeaderWorkerTemplate(
                    size=1,
                    worker_template=PodTemplateSpec(
                        spec=PodSpec(
                            containers=[
                                Container(
                                    name=role,
                                    command=[
                                        sys.executable, "-m", "lws_tpu.serving.disagg_worker",
                                        role, "--handoff", handoff, "--steps", str(DECODE_STEPS),
                                    ],
                                    env=[EnvVar("JAX_PLATFORMS", "cpu")],
                                )
                            ]
                        )
                    ),
                )
            )
        ),
    )


def test_disaggregated_prefill_decode_roundtrip(tmp_path):
    handoff = str(tmp_path / "handoff")
    os.makedirs(handoff)

    ds = DisaggregatedSet(
        meta=new_meta("llmd"),
        spec=DisaggregatedSetSpec(
            roles=[role_spec("prefill", handoff), role_spec("decode", handoff)]
        ),
    )
    cp = ControlPlane()
    backend = make_backend(cp, tmp_path)
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})

    try:
        cp.create(ds)
        cp.run_until_stable()
        pods = sorted(p.meta.name for p in cp.store.list("Pod"))
        assert len(pods) == 2, pods  # one prefill, one decode leader

        # Submit a request into the prefill role's queue.
        prompt = np.array([5, 9, 2, 11, 7], dtype=np.int32)
        np.save(str(tmp_path / "req1.prompt.npy"), prompt)
        os.replace(str(tmp_path / "req1.prompt.npy"), os.path.join(handoff, "req1.prompt.npy"))

        deadline = time.time() + 150
        result_path = os.path.join(handoff, "req1.tokens.npy")
        while time.time() < deadline:
            backend.poll_all()
            cp.run_until_stable()
            if os.path.exists(result_path):
                break
            time.sleep(0.5)
        else:
            pytest.fail(f"no decode result; handoff dir: {os.listdir(handoff)}")

        generated = np.load(result_path)

        # Oracle: the same model end-to-end in one engine.
        from lws_tpu.serving.disagg_worker import build_engine

        engine = build_engine(batch=1, max_len=32)
        result = engine.generate(
            np.asarray(prompt).reshape(1, -1), max_new_tokens=DECODE_STEPS + 1
        )
        np.testing.assert_array_equal(generated[0], np.asarray(result.tokens)[0])
    finally:
        backend.shutdown()
