"""Reference-e2e-parity flows not covered by the rolling-update matrix
(ref test/e2e/e2e_test.go): subdomain-policy change mid-life, per-replica
service scale-up under maxSurge, gang PodGroup lifecycle across group
restarts, and subgroup rollouts with surge."""

from lws_tpu.api import contract
from lws_tpu.api.types import NetworkConfig, SubdomainPolicy, SubGroupPolicyType
from lws_tpu.runtime import ControlPlane
from lws_tpu.sched import make_slice_nodes
from lws_tpu.testing import (
    LWSBuilder,
    assert_valid_lws,
    lws_pods,
    make_all_groups_ready,
    restart_pod_container,
)


def test_subdomain_policy_change_rolls_new_dns_identity():
    """Shared -> UniquePerReplica mid-life (ref e2e_test.go:305): the change
    is a template revision, so groups roll; the new pods carry per-replica
    subdomains, matching env (LWS_LEADER_ADDRESS/JAX coordinator), and
    per-replica services exist. assert_valid_lws checks the whole contract
    for whichever policy is in force."""
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    assert_valid_lws(cp.store, "sample")
    before = {p.meta.name: p.spec.subdomain for p in lws_pods(cp.store, "sample")}
    assert set(before.values()) == {"sample"}

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.network_config = NetworkConfig(
        subdomain_policy=SubdomainPolicy.UNIQUE_PER_REPLICA
    )
    cp.store.update(lws)
    make_all_groups_ready(cp, "sample", max_rounds=40)

    assert_valid_lws(cp.store, "sample")
    pods = {p.meta.name: p for p in lws_pods(cp.store, "sample")}
    for g in range(2):
        leader = pods[f"sample-{g}"]
        assert leader.spec.subdomain == f"sample-{g}"
        env = {e.name: e.value for e in leader.spec.containers[0].env}
        assert env[contract.LWS_LEADER_ADDRESS] == f"sample-{g}.sample-{g}.default"
        assert cp.store.try_get("Service", "default", f"sample-{g}") is not None


def test_per_replica_services_scale_with_surge():
    """UniquePerReplica + maxSurge (ref e2e_test.go:330): burst groups get
    their own headless services while the surge lives."""
    cp = ControlPlane()  # manual readiness: the burst must be observable
    cp.create(
        LWSBuilder().replicas(2).size(2).image("v1")
        .subdomain_policy(SubdomainPolicy.UNIQUE_PER_REPLICA)
        .rollout(max_unavailable=0, max_surge=2).build()
    )
    make_all_groups_ready(cp, "sample", max_rounds=40)

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.leader_worker_template.worker_template.spec.containers[0].image = "v2"
    cp.store.update(lws)
    cp.run_until_stable()
    # Surge groups 2..3 exist mid-update with their per-replica services.
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.replicas == 4, "maxSurge=2 must burst to 4 groups"
    for g in range(4):
        assert cp.store.try_get("Service", "default", f"sample-{g}") is not None, g

    make_all_groups_ready(cp, "sample", max_rounds=60)
    assert_valid_lws(cp.store, "sample")
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2
    # Reclaimed: burst services' groups are gone with their pods.
    assert cp.store.get("GroupSet", "default", "sample").spec.replicas == 2


def test_podgroup_follows_group_restart():
    """Gang PodGroup lifecycle across RecreateGroupOnPodRestart (ref
    e2e_gang_scheduling_test.go / e2e_test.go:365): the PodGroup is owned by
    the leader pod, so a group restart GCs it and the replacement leader's
    reconcile recreates it."""
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, scheduler_provider="gang")
    for i in range(2):
        cp.add_nodes(make_slice_nodes(f"slice-{i}", topology="2x4"))
    cp.create(LWSBuilder().replicas(2).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    groups_before = {g.meta.name: g.meta.uid for g in cp.store.list("PodGroup")}
    assert len(groups_before) == 2
    leader_uid_before = cp.store.get("Pod", "default", "sample-0").meta.uid

    restart_pod_container(cp.store, "default", "sample-0-1")
    cp.run_until_stable()
    make_all_groups_ready(cp, "sample", max_rounds=40)

    assert cp.store.get("Pod", "default", "sample-0").meta.uid != leader_uid_before
    groups_after = {g.meta.name: g.meta.uid for g in cp.store.list("PodGroup")}
    assert set(groups_after) == set(groups_before)
    changed = [n for n in groups_after if groups_after[n] != groups_before[n]]
    assert len(changed) == 1, (groups_before, groups_after)


def test_subgroup_rollout_with_surge_preserves_windows():
    """Rolling update with subGroupSize + maxSurge (ref e2e_test.go:230):
    every post-rollout pod keeps correct subgroup labels and TPU hostname
    windows — assert_valid_lws recomputes them all."""
    cp = ControlPlane(auto_ready=True)
    cp.create(
        LWSBuilder().replicas(2).size(4).tpu_chips(4).image("v1")
        .subgroup(2, SubGroupPolicyType.LEADER_WORKER)
        .rollout(max_unavailable=1, max_surge=1).build()
    )
    cp.run_until_stable()
    assert_valid_lws(cp.store, "sample")

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.leader_worker_template.worker_template.spec.containers[0].image = "v2"
    cp.store.update(lws)
    make_all_groups_ready(cp, "sample", max_rounds=60)

    assert_valid_lws(cp.store, "sample")
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2 and lws.status.ready_replicas == 2
    for p in lws_pods(cp.store, "sample"):
        assert p.spec.containers[0].image == "v2", p.meta.name
