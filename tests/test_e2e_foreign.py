"""e2e: a FOREIGN workload (examples/foreign_psum.py — zero lws_tpu
imports) bootstraps jax.distributed purely from the injected env contract
and runs a cross-process psum, driven through the real control plane.

VERDICT r4 missing #3: every prior e2e launched code that imports lws_tpu;
nothing demonstrated the contract doing its actual job — powering an engine
that has never heard of this framework (the reference's vLLM pattern,
/root/reference/docs/examples/vllm/TPU/lws.yaml:30-34). The script below is
also statically checked to contain no lws_tpu reference, so it can't
regress into importing the framework it exists to not need.
"""

import os
import sys

from lws_tpu.api.pod import Container, EnvVar, PodSpec, PodTemplateSpec
from lws_tpu.api.types import (
    LeaderWorkerSet,
    LeaderWorkerSetSpec,
    LeaderWorkerTemplate,
)
from lws_tpu.core.store import new_meta
from lws_tpu.runtime import ControlPlane
from tests.test_e2e_local import make_backend, wait_for_files

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO_ROOT, "examples", "foreign_psum.py")


def test_foreign_script_never_touches_the_framework():
    import ast

    src = open(SCRIPT).read()
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mods = [node.module or ""]
        else:
            continue
        assert not any(m.split(".")[0] == "lws_tpu" for m in mods), (
            f"foreign_psum.py imports the framework it exists to not need: {mods}"
        )
    assert "LWS_LEADER_ADDRESS" in src and "LWS_WORKER_INDEX" in src


def test_foreign_workload_bootstraps_from_env_contract(tmp_path):
    size = 2
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="engine",
                    command=[sys.executable, SCRIPT],
                    env=[
                        EnvVar("LWS_TPU_RESULT_FILE", str(tmp_path / "$(POD_NAME).txt")),
                        # Distinct port: the suite's other coordinators may
                        # be alive in the same window.
                        EnvVar("FOREIGN_COORD_PORT", "9917"),
                    ],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("foreign"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(
                worker_template=template, size=size
            ),
        ),
    )

    cp = ControlPlane()
    backend = make_backend(cp, tmp_path)
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    try:
        cp.create(lws)
        cp.run_until_stable()
        expected = {"foreign-0.txt", "foreign-0-1.txt"}
        wait_for_files(cp, backend, tmp_path, expected)
        for name in expected:
            content = (tmp_path / name).read_text()
            assert "ok=True" in content, f"{name}: {content}"
            assert "foreign" in content
    finally:
        backend.shutdown()
