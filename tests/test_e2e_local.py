"""e2e: the control plane launches REAL local processes wired purely by the
injected env contract, and they perform a distributed JAX psum with the leader
as coordinator (SURVEY §7 stage 3 acceptance / BASELINE config #2)."""

import os
import pathlib
import time

import pytest

from lws_tpu.api.pod import Container, EnvVar, PodSpec, PodTemplateSpec
from lws_tpu.api.types import (
    LeaderWorkerSet,
    LeaderWorkerSetSpec,
    LeaderWorkerTemplate,
)
from lws_tpu.core.store import new_meta
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.local import LocalBackend

import sys

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


def test_real_process_group_runs_distributed_psum(tmp_path):
    size = 2
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="worker",
                    command=[sys.executable, "-m", "lws_tpu.runtime.worker", "psum"],
                    env=[EnvVar("LWS_TPU_RESULT_FILE", str(tmp_path / "$(POD_NAME).txt"))],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("psum"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(worker_template=template, size=size),
        ),
    )

    cp = ControlPlane()
    backend = make_backend(cp, tmp_path)
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})

    try:
        cp.create(lws)
        cp.run_until_stable()
        expected = {"psum-0.txt", "psum-0-1.txt"}
        wait_for_files(cp, backend, tmp_path, expected)
        for name in expected:
            content = (tmp_path / name).read_text()
            assert "ok=True" in content, f"{name}: {content}"
    finally:
        backend.shutdown()


def make_backend(cp, tmp_path, extra_env=None):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "XLA_FLAGS": "",
    }
    env.update(extra_env or {})
    return LocalBackend(cp.store, env_overrides=env, env_drop=("PALLAS_AXON_POOL_IPS",))


def wait_for_files(cp, backend, tmp_path, expected, timeout=150):
    deadline = time.time() + timeout
    while time.time() < deadline:
        backend.poll_all()
        cp.run_until_stable()
        if expected <= {p.name for p in tmp_path.iterdir()}:
            return
        time.sleep(1.0)
    pytest.fail(f"workers never finished; files: {list(tmp_path.iterdir())}")


def test_real_process_group_runs_tp_sharded_model(tmp_path):
    """The orchestrated group forms ONE tensor-parallel mesh across real
    processes (2 procs x 2 virtual devices = tp=4) and runs a sharded llama
    forward; both processes must compute identical replicated logits."""
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="worker",
                    command=[sys.executable, "-m", "lws_tpu.runtime.worker", "tp_forward"],
                    env=[EnvVar("LWS_TPU_RESULT_FILE", str(tmp_path / "$(POD_NAME).txt"))],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("tpserve"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(worker_template=template, size=2),
        ),
    )
    cp = ControlPlane()
    backend = make_backend(
        cp, tmp_path, extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    )
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    try:
        cp.create(lws)
        cp.run_until_stable()
        wait_for_files(cp, backend, tmp_path, {"tpserve-0.txt", "tpserve-0-1.txt"})
        lines = sorted((tmp_path / n).read_text().strip() for n in ("tpserve-0.txt", "tpserve-0-1.txt"))
        assert "devices=4 tp=4" in lines[0], lines
        cks = {l.split("checksum=")[1] for l in lines}
        assert len(cks) == 1, f"processes disagree: {lines}"
        assert float(cks.pop()) > 0
    finally:
        backend.shutdown()


def test_real_process_failure_recreates_group(tmp_path):
    """Kill a real worker process: the backend reports the exit, the restart
    policy recreates the whole group, and fresh processes come up."""
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="worker",
                    command=[sys.executable, "-m", "lws_tpu.runtime.worker", "sleep", "600"],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("victim"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(worker_template=template, size=2),
        ),
    )
    cp = ControlPlane()
    backend = make_backend(cp, tmp_path)
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    try:
        cp.create(lws)
        cp.run_until_stable()
        before = {p.meta.name: p.meta.uid for p in cp.store.list("Pod")}
        assert set(before) == {"victim-0", "victim-0-1"}

        # Kill the worker's real process out from under it.
        worker_uid = before["victim-0-1"]
        backend._procs[worker_uid].kill()
        deadline = time.time() + 60
        while time.time() < deadline:
            backend.poll_all()
            cp.run_until_stable()
            after = {p.meta.name: p.meta.uid for p in cp.store.list("Pod")}
            if (
                set(after) == set(before)
                and all(after[n] != before[n] for n in before)
            ):
                break
            time.sleep(0.5)
        else:
            pytest.fail(f"group never recreated: {cp.store.list('Pod')}")
        # New processes are actually running.
        for pod in cp.store.list("Pod"):
            proc = backend._procs.get(pod.meta.uid)
            assert proc is not None and proc.poll() is None
    finally:
        backend.shutdown()


def test_real_process_group_serves_tp_sharded_engine(tmp_path):
    """VERDICT r3 #3: the orchestrated group (2 procs x 2 virtual devices =
    tp=4) serves through the TP-SHARDED Engine — params + KV cache sharded
    across process boundaries, decode_n under GSPMD — and both processes
    sample IDENTICAL tokens (multi-host serving coherence: any process can
    answer)."""
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="worker",
                    command=[sys.executable, "-m", "lws_tpu.runtime.worker", "serve_tp"],
                    env=[EnvVar("LWS_TPU_RESULT_FILE", str(tmp_path / "$(POD_NAME).txt"))],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("servetp"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(worker_template=template, size=2),
        ),
    )
    cp = ControlPlane()
    backend = make_backend(
        cp, tmp_path, extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    )
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    try:
        cp.create(lws)
        cp.run_until_stable()
        wait_for_files(cp, backend, tmp_path, {"servetp-0.txt", "servetp-0-1.txt"})
        lines = sorted((tmp_path / n).read_text().strip() for n in ("servetp-0.txt", "servetp-0-1.txt"))
        assert "tp=4" in lines[0], lines
        import ast

        token_strs = {l.split("tokens=")[1] for l in lines}
        assert len(token_strs) == 1, f"processes sampled different tokens: {lines}"
        assert len(ast.literal_eval(token_strs.pop())) == 16  # 2 slots x 8 steps
    finally:
        backend.shutdown()


def test_real_process_group_serves_paged_prefix_sampling(tmp_path):
    """The COMPOSED density stack across real process boundaries: 2 procs x
    2 virtual devices = a tp=4 mesh serving PagedBatchEngine with prefix
    caching and mixed greedy/seeded-sampled requests. Both processes must
    report identical tokens AND identical prefix-hit stats — host-side
    allocation is deterministic and every device value is replicated."""
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="worker",
                    command=[sys.executable, "-m", "lws_tpu.runtime.worker", "serve_paged"],
                    env=[EnvVar("LWS_TPU_RESULT_FILE", str(tmp_path / "$(POD_NAME).txt"))],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("servepg"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(worker_template=template, size=2),
        ),
    )
    cp = ControlPlane()
    backend = make_backend(
        cp, tmp_path, extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    )
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    try:
        cp.create(lws)
        cp.run_until_stable()
        wait_for_files(cp, backend, tmp_path, {"servepg-0.txt", "servepg-0-1.txt"})
        lines = sorted((tmp_path / n).read_text().strip() for n in ("servepg-0.txt", "servepg-0-1.txt"))
        assert "tp=4" in lines[0], lines
        assert "hits=16" in lines[0], lines  # B hit both 8-token sys blocks
        payloads = {l.split(" ", 1)[1] for l in lines}  # strip process=i/n
        assert len(payloads) == 1, f"processes diverged: {lines}"
    finally:
        backend.shutdown()
