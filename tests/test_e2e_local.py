"""e2e: the control plane launches REAL local processes wired purely by the
injected env contract, and they perform a distributed JAX psum with the leader
as coordinator (SURVEY §7 stage 3 acceptance / BASELINE config #2)."""

import os
import pathlib
import time

import pytest

from lws_tpu.api.pod import Container, EnvVar, PodSpec, PodTemplateSpec
from lws_tpu.api.types import (
    LeaderWorkerSet,
    LeaderWorkerSetSpec,
    LeaderWorkerTemplate,
)
from lws_tpu.core.store import new_meta
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.local import LocalBackend

import sys

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


def test_real_process_group_runs_distributed_psum(tmp_path):
    size = 2
    template = PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name="worker",
                    command=[sys.executable, "-m", "lws_tpu.runtime.worker", "psum"],
                    env=[EnvVar("LWS_TPU_RESULT_FILE", str(tmp_path / "$(POD_NAME).txt"))],
                )
            ]
        )
    )
    lws = LeaderWorkerSet(
        meta=new_meta("psum"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(worker_template=template, size=size),
        ),
    )

    cp = ControlPlane()
    backend = LocalBackend(
        cp.store,
        # Workers must run on the CPU backend of their own process: strip the
        # TPU plugin trigger and force cpu (the chip is single-claim).
        env_overrides={
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "XLA_FLAGS": "",
        },
        env_drop=("PALLAS_AXON_POOL_IPS",),
    )
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})

    try:
        cp.create(lws)
        cp.run_until_stable()

        deadline = time.time() + 150
        expected = {f"psum-0.txt", f"psum-0-1.txt"}
        while time.time() < deadline:
            backend.poll_all()
            cp.run_until_stable()
            have = {p.name for p in tmp_path.iterdir()}
            if expected <= have:
                break
            time.sleep(1.0)
        else:
            pytest.fail(f"workers never finished; files: {list(tmp_path.iterdir())}")

        for name in expected:
            content = (tmp_path / name).read_text()
            assert "ok=True" in content, f"{name}: {content}"
    finally:
        backend.shutdown()
