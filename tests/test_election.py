"""Leader election (HA controller manager, reference cmd/main.go:95-106):
lease acquire/renew/expiry/takeover/step-down, and ControlPlane gating."""

from lws_tpu.core.election import LeaderElector
from lws_tpu.core.store import Store
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, lws_pods


class FakeClock:
    def __init__(self) -> None:
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


def make_elector(store, identity, clock, **kw):
    return LeaderElector(
        store, identity, lease_duration_s=15, renew_deadline_s=10,
        retry_period_s=2, clock=clock, **kw,
    )


def test_first_candidate_acquires_and_renews():
    store, clock = Store(), FakeClock()
    a = make_elector(store, "a", clock)
    assert a.tick() and a.is_leader()
    lease = store.get("Lease", "_cluster", "lws-tpu-controller")
    assert lease.spec.holder_identity == "a"
    first_renew = lease.spec.renew_time
    clock.now += 5
    assert a.tick()
    assert store.get("Lease", "_cluster", "lws-tpu-controller").spec.renew_time > first_renew


def test_standby_waits_then_takes_over_on_expiry():
    store, clock = Store(), FakeClock()
    a = make_elector(store, "a", clock)
    b_started = []
    b = make_elector(store, "b", clock, on_started_leading=lambda: b_started.append(1))
    assert a.tick()
    assert not b.tick() and not b.is_leader()
    assert b.leader_identity() == "a"

    # Leader goes silent past the lease duration: standby takes over.
    clock.now += 16
    assert b.tick() and b.is_leader()
    assert b_started == [1]
    lease = store.get("Lease", "_cluster", "lws-tpu-controller")
    assert lease.spec.holder_identity == "b"
    assert lease.spec.lease_transitions == 1


def test_deposed_leader_steps_down():
    store, clock = Store(), FakeClock()
    a_stopped = []
    a = make_elector(store, "a", clock, on_stopped_leading=lambda: a_stopped.append(1))
    b = make_elector(store, "b", clock)
    assert a.tick()
    clock.now += 16
    assert b.tick()
    # The old leader's next ticks fail to renew; once past the renew deadline
    # it must stop leading (never two active controllers).
    clock.now += 11
    assert not a.tick() and not a.is_leader()
    assert a_stopped == [1]
    assert b.leader_identity() == "b"


def test_release_gives_instant_failover():
    store, clock = Store(), FakeClock()
    a = make_elector(store, "a", clock)
    b = make_elector(store, "b", clock)
    assert a.tick() and not b.tick()
    a.release()
    assert not a.is_leader()
    assert b.tick() and b.is_leader()  # no expiry wait needed


def test_control_plane_standby_does_not_reconcile():
    clock = FakeClock()
    leader = ControlPlane(auto_ready=True, leader_election=True, identity="leader",
                          clock=clock)
    standby = ControlPlane(auto_ready=True, leader_election=True, identity="standby",
                           store=leader.store, clock=clock)
    assert leader.run_until_stable() == 0 or True  # first call elects + settles
    leader.create(LWSBuilder().replicas(1).size(2).build())
    leader.run_until_stable()
    assert len(lws_pods(leader.store, "sample")) == 2

    # The standby shares the store but must stay passive.
    standby.resync()
    assert standby.run_until_stable() == 0
    assert not standby.elector.is_leader()

    # Leader dies (stops renewing): standby takes over and reconciles drift.
    leader.elector.release()
    leader.store.delete("GroupSet", "default", "sample-0")
    standby.resync()
    standby.run_until_stable()
    assert standby.elector.is_leader()
    assert standby.store.try_get("GroupSet", "default", "sample-0") is not None


def test_threaded_standby_workers_stay_passive():
    """Split-brain guard in THREADED mode: a standby's worker threads must
    hold queued work (not reconcile) until the lease is theirs."""
    import time as _time

    clock = FakeClock()
    leader = ControlPlane(auto_ready=True, leader_election=True, identity="leader",
                          clock=clock)
    leader.elector.tick()
    standby = ControlPlane(auto_ready=True, leader_election=True, identity="standby",
                           store=leader.store, clock=clock)
    standby.manager.start(poll_interval=0.005)
    try:
        standby.elector.tick()
        leader.create(LWSBuilder().replicas(1).size(2).build())
        _time.sleep(0.2)
        # Standby workers saw the events but must not have acted on them.
        assert not lws_pods(leader.store, "sample")

        # Leader releases; standby's next tick elects it and workers drain.
        leader.elector.release()
        standby.elector.tick()
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and len(lws_pods(leader.store, "sample")) < 2:
            _time.sleep(0.05)
        assert len(lws_pods(leader.store, "sample")) == 2
    finally:
        standby.manager.stop()
