"""Every shipped example manifest must ADMIT and CONVERGE on a control plane
(≈ the reference's config/samples being applied by its e2e suite): the
flagship examples are the first thing a user runs, and a placeholder command
or schema drift here is a broken front door (VERDICT r3 missing #5)."""

import glob
import os

import pytest

from lws_tpu.manifest import load_manifests
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import assert_valid_lws

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    p for p in glob.glob(os.path.join(ROOT, "examples", "*.yaml"))
    if not p.endswith("config.yaml")  # component config, not an API object
)


@pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_applies_and_converges(path):
    objs = load_manifests(path)
    assert objs, f"{path} parsed to nothing"
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, scheduler_provider="gang")
    # Examples that placement-constrain (exclusive topology / TPU requests)
    # need a fleet; give every run the nodes the fleet example ships.
    from lws_tpu.sched import make_slice_nodes

    for i in range(8):
        cp.add_nodes(make_slice_nodes(f"slice-{i}", topology="2x4"))
    created = []
    for obj in objs:
        if obj.kind == "Node":
            cp.add_nodes([obj])
        else:
            created.append(cp.create(obj))  # admission must accept as-is
    cp.run_until_stable()

    for obj in created:
        if obj.kind == "LeaderWorkerSet":
            fetched = cp.store.get("LeaderWorkerSet", obj.meta.namespace, obj.meta.name)
            assert fetched.status.ready_replicas == fetched.spec.replicas, (
                f"{path}: {obj.meta.name} never became ready"
            )
            assert_valid_lws(cp.store, obj.meta.name, obj.meta.namespace)
        elif obj.kind == "DisaggregatedSet":
            fetched = cp.store.get("DisaggregatedSet", obj.meta.namespace, obj.meta.name)
            ready = {r.name: r.ready_replicas for r in fetched.status.roles}
            want = {r.name: r.replicas for r in fetched.spec.roles}
            slices = max(1, fetched.spec.slices)
            assert ready == {k: v * slices for k, v in want.items()}, (
                f"{path}: roles never ready: {ready} != {want} x {slices} slices"
            )


def test_examples_have_no_placeholder_commands():
    """The flagship examples must run code that exists in this repo — no
    serve_prefill.py-style placeholders (VERDICT r3 missing #5). Checked on
    the PARSED container commands, not the YAML text, so formatting can't
    false-fail it."""
    def containers(obj):
        if obj.kind == "LeaderWorkerSet":
            yield from obj.spec.leader_worker_template.worker_template.spec.containers
        elif obj.kind == "DisaggregatedSet":
            for role in obj.spec.roles:
                lwt = role.template.spec.leader_worker_template
                yield from lwt.worker_template.spec.containers

    for path in EXAMPLES:
        for obj in load_manifests(path):
            for c in containers(obj):
                cmd = list(c.command or [])
                assert not any("serve_prefill" in a or "serve_decode" in a for a in cmd), (
                    path, cmd,
                )
                if any("disagg_worker" in a for a in cmd):
                    assert "lws_tpu.serving.disagg_worker" in " ".join(cmd), (path, cmd)
