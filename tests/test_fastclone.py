"""Native fast-clone (native/fastclone.c): must be semantically identical to
the pure-Python clone at the Store's copy boundaries."""

import copy
import enum

import pytest

from lws_tpu.api.meta import to_plain
from lws_tpu.core import store as store_mod
from lws_tpu.testing import LWSBuilder

native = pytest.importorskip("lws_tpu.core._fastclone")


def sample_objects():
    from lws_tpu.api.lease import Lease
    from lws_tpu.sched import make_slice_nodes

    lws = LWSBuilder().replicas(2).size(4).tpu_chips(4).exclusive_topology().build()
    lws.meta.annotations["a/b"] = "c"
    return [lws, make_slice_nodes("s", topology="2x4")[0], Lease()]


def test_native_matches_python_clone():
    native.init(enum.Enum, copy.deepcopy)
    for obj in sample_objects():
        a, b = native.clone(obj), store_mod._py_clone(obj)
        assert to_plain(a) == to_plain(b) == to_plain(obj)
        assert a is not obj and a.meta is not obj.meta


def test_native_clone_isolates_mutations():
    native.init(enum.Enum, copy.deepcopy)
    obj = sample_objects()[0]
    c = native.clone(obj)
    c.spec.replicas = 99
    c.meta.labels["x"] = "y"
    c.spec.leader_worker_template.worker_template.spec.containers[0].resources["r"] = 1
    assert obj.spec.replicas == 2
    assert "x" not in obj.meta.labels
    assert "r" not in obj.spec.leader_worker_template.worker_template.spec.containers[0].resources


def test_exotic_types_fall_back():
    native.init(enum.Enum, copy.deepcopy)
    c = native.clone({"s": {1, 2}, "t": (1, [2])})
    assert c == {"s": {1, 2}, "t": (1, [2])}
    c["s"].add(3)
    c["t"][1].append(9)


def test_cyclic_object_does_not_crash():
    """A cyclic structure must not exhaust the C stack: past the depth bound
    the walk delegates to copy.deepcopy, whose memo handles cycles."""
    native.init(enum.Enum, copy.deepcopy)
    cyc = {}
    cyc["self"] = cyc
    out = native.clone(cyc)
    # The top CLONE_MAX_DEPTH levels are fresh dicts; past the bound the
    # deepcopy fallback preserves the cycle. Walking far past the bound
    # proves no crash and an intact structure.
    cur = out
    for _ in range(500):
        cur = cur["self"]
    assert out is not cyc


def test_clone_before_init_raises():
    import subprocess
    import sys

    # Fresh interpreter importing the extension DIRECTLY (importing
    # lws_tpu.core would run store.py, which calls init): clone() before
    # init() must raise, not segfault. An enum forces the enum_type path.
    code = (
        "import importlib.util, glob\n"
        "spec = importlib.util.spec_from_file_location('_fastclone', "
        "glob.glob('lws_tpu/core/_fastclone*.so')[0])\n"
        "fc = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(fc)\n"
        "import enum\n"
        "class E(enum.Enum):\n    X = 1\n"
        "try:\n    fc.clone(E.X)\nexcept RuntimeError as e:\n"
        "    print('raised', e)\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, cwd=".")
    assert "raised" in out.stdout, (out.stdout, out.stderr, out.returncode)


def test_store_uses_native_when_available(monkeypatch):
    import os
    assert os.environ.get("LWS_TPU_PURE_PY") or store_mod._clone is native.clone
