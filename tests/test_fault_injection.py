"""Fault-injection substrate (ISSUE 8 tentpole): deterministic schedule
semantics, the LWS_TPU_FAULTS grammar, the /debug/faults control surface on
both servers, the store conflict hook, and the disarmed fast path.

Everything here is seeded/counter-driven — the same schedule fires the same
way every run; the only sleeps are injected `delay` faults ≤ 0.05s."""

import json
import urllib.error
import urllib.request

import pytest

from lws_tpu.core import faults, metrics
from lws_tpu.core.faults import Fault, FaultInjector, parse


@pytest.fixture
def injector():
    return FaultInjector(env="")


@pytest.fixture
def global_faults():
    """Arm the PROCESS injector (what the wired fault points read) with
    guaranteed disarm-after: a leaked schedule would poison later tests."""
    yield faults.INJECTOR
    faults.INJECTOR.disarm()


# ---------------------------------------------------------------------------
# Grammar + schedules


def test_parse_grammar():
    specs = parse("kv.ack=drop:1, disagg.prefill.handoff=exit:1;"
                  "kv.client.connect=fail_n_times:2:ConnectionError")
    assert specs == {
        "kv.ack": "drop:1",
        "disagg.prefill.handoff": "exit:1",
        "kv.client.connect": "fail_n_times:2:ConnectionError",
    }


@pytest.mark.parametrize("bad", [
    "pointonly", "=spec", "point=", "p=unknown_mode:1",
    "p=fail_n_times:x", "p=fail_n_times:1:NotAnException",
    "p=every_k:0", "p=prob:0.5",  # prob requires a seed
])
def test_bad_specs_rejected(bad, injector):
    with pytest.raises(ValueError):
        injector.arm_many(parse(bad))


def test_fail_n_times_fires_then_passes(injector):
    injector.arm("kv.client.connect", "fail_n_times:2:ConnectionError")
    for _ in range(2):
        with pytest.raises(ConnectionError, match="injected fault"):
            injector.fire("kv.client.connect")
    assert injector.fire("kv.client.connect") is None  # budget spent
    snap = injector.snapshot()
    assert snap["trips"]["kv.client.connect"] == 2
    assert snap["hits"]["kv.client.connect"] == 3


def test_every_k_fires_periodically(injector):
    injector.arm("store.conflict", "every_k:3")
    fired = [injector.hit("store.conflict") is not None for _ in range(9)]
    assert fired == [False, False, True] * 3  # deterministic period


def test_delay_sleeps_then_stops(injector):
    import time

    injector.arm("kv.client.recv", "delay:0.03:1")
    t0 = time.perf_counter()
    assert injector.fire("kv.client.recv") is None  # slept, no error
    assert time.perf_counter() - t0 >= 0.03
    t0 = time.perf_counter()
    assert injector.fire("kv.client.recv") is None  # budget spent: no sleep
    assert time.perf_counter() - t0 < 0.02


def test_drop_and_partial_write_are_cooperative(injector):
    injector.arm("kv.ack", "drop:1")
    injector.arm("kv.server.send_bundle", "partial_write:6:1")
    fault = injector.fire("kv.ack")
    assert isinstance(fault, Fault) and fault.mode == "drop"
    assert injector.fire("kv.ack") is None
    fault = injector.fire("kv.server.send_bundle")
    assert fault.mode == "partial_write" and fault.arg == 6.0


def test_pace_mode_is_cooperative_and_always_fires(injector):
    """pace:MBPS (ISSUE 10's DCN-link emulation): cooperative Fault with
    the MB/s as arg, firing on EVERY call (a link has no trip budget), and
    armable only on the send points that implement the pacing."""
    injector.arm("kv.stream.send_chunk", "pace:150")
    for _ in range(3):
        fault = injector.fire("kv.stream.send_chunk")
        assert isinstance(fault, Fault) and fault.mode == "pace"
        assert fault.arg == 150.0
    with pytest.raises(ValueError, match="cooperative"):
        injector.arm("kv.client.connect", "pace:10")
    with pytest.raises(ValueError, match="MB/s"):
        injector.arm("kv.stream.send_chunk", "pace:0")


def test_exit_mode_raises_systemexit(injector):
    injector.arm("disagg.prefill.handoff", "exit:1")
    with pytest.raises(SystemExit):
        injector.fire("disagg.prefill.handoff")
    assert injector.fire("disagg.prefill.handoff") is None


def test_prob_is_seed_deterministic():
    a, b = FaultInjector(env=""), FaultInjector(env="")
    for injector in (a, b):
        injector.arm("fleet.scrape", "prob:0.5:42")
    pattern_a = [injector_hit(a) for _ in range(32)]
    pattern_b = [injector_hit(b) for _ in range(32)]
    assert pattern_a == pattern_b  # same seed, same schedule
    assert any(pattern_a) and not all(pattern_a)


def injector_hit(injector):
    return injector.hit("fleet.scrape") is not None


def test_env_arming():
    injector = FaultInjector(env="kv.ack=drop:1,store.conflict=every_k:2")
    assert injector.armed
    assert set(injector.snapshot()["armed"]) == {"kv.ack", "store.conflict"}


def test_disarmed_fast_path(injector):
    assert not injector.armed
    assert injector.fire("kv.ack") is None
    assert injector.hit("anything") is None
    injector.arm("kv.ack", "drop")
    injector.disarm("kv.ack")
    assert not injector.armed  # flag drops back with the last point


def test_trip_counter_metric(global_faults):
    before = metrics.REGISTRY.counter_value(
        "lws_fault_trips_total", {"point": "kv.ack", "mode": "drop"})
    global_faults.arm("kv.ack", "drop:2")
    assert faults.fire("kv.ack").mode == "drop"
    assert faults.hit("kv.ack").mode == "drop"
    after = metrics.REGISTRY.counter_value(
        "lws_fault_trips_total", {"point": "kv.ack", "mode": "drop"})
    assert after == before + 2


def test_apply_control_arm_disarm_clear(global_faults):
    out = faults.apply_control({"arm": {"kv.ack": "drop:1"}})
    assert out["armed"] == {"kv.ack": "drop:1"}
    out = faults.apply_control({"disarm": ["kv.ack"]})
    assert out["armed"] == {}
    faults.apply_control({"arm": {"a": "fail_n_times:1", "b": "delay:0.01"}})
    out = faults.apply_control({"clear": True, "arm": {"c": "exit:1"}})
    assert set(out["armed"]) == {"c"}  # clear applies first
    with pytest.raises(ValueError):
        faults.apply_control({"arm": {"p": "bogus_mode"}})
    with pytest.raises(ValueError):
        faults.apply_control({"frobnicate": True})
    faults.apply_control({"clear": True})


def test_cooperative_modes_rejected_on_non_cooperative_points(injector):
    """drop/partial_write only make sense where the call site implements
    the loss — arming them on a bare fire() point would count trips that
    injected nothing, so the arm is refused up front."""
    for point in ("kv.client.connect", "fleet.scrape", "made.up.point"):
        with pytest.raises(ValueError, match="cooperative"):
            injector.arm(point, "drop:1")
    injector.arm("kv.ack", "drop:1")  # a cooperative point still arms
    assert injector.snapshot()["armed"] == {"kv.ack": "drop:1"}


# ---------------------------------------------------------------------------
# Control surfaces


def test_debug_faults_on_worker_telemetry_server(global_faults):
    from lws_tpu.runtime.telemetry import TelemetryServer

    server = TelemetryServer(port=0, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    auth = {"Authorization": "Bearer s3cret"}
    try:
        # Bearer-gated, both verbs: the debug surface can KILL processes.
        for method, body in (("GET", None), ("POST", b"{}")):
            req = urllib.request.Request(f"{base}/debug/faults", data=body,
                                         method=method)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 401
        req = urllib.request.Request(
            f"{base}/debug/faults", method="POST", headers=auth,
            data=json.dumps({"arm": {"kv.ack": "drop:1"}}).encode(),
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["armed"] == {"kv.ack": "drop:1"}
        assert faults.INJECTOR.snapshot()["armed"] == {"kv.ack": "drop:1"}
        req = urllib.request.Request(f"{base}/debug/faults", headers=auth)
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read().decode())["trips"] == {"kv.ack": 0}
        # Bad specs are a 400, never a 500.
        req = urllib.request.Request(
            f"{base}/debug/faults", method="POST", headers=auth,
            data=json.dumps({"arm": {"p": "warp_core_breach"}}).encode(),
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
    finally:
        server.stop()


def test_debug_faults_on_api_server(global_faults):
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        req = urllib.request.Request(
            f"{base}/debug/faults", method="POST",
            data=json.dumps({"arm": {"store.conflict": "every_k:2"}}).encode(),
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read().decode())["armed"] == {
                "store.conflict": "every_k:2"
            }
        with urllib.request.urlopen(f"{base}/debug/faults", timeout=10) as resp:
            assert "store.conflict" in json.loads(resp.read().decode())["armed"]
    finally:
        api.stop()


def test_cli_faults_subcommand(global_faults, capsys):
    from lws_tpu import cli
    from lws_tpu.runtime.telemetry import TelemetryServer

    server = TelemetryServer(port=0)
    server.start()
    try:
        rc = cli.main(["faults", "--server", f"127.0.0.1:{server.port}",
                       "kv.ack=drop:1"])
        assert rc == 0
        assert '"kv.ack": "drop:1"' in capsys.readouterr().out
        rc = cli.main(["faults", "--server", f"127.0.0.1:{server.port}",
                       "--clear"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["armed"] == {}
        rc = cli.main(["faults", "--server", f"127.0.0.1:{server.port}",
                       "not-a-spec"])
        assert rc == 2
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Store conflict hook


def test_store_conflict_fault_exercises_retry_loops(global_faults):
    from lws_tpu.core.store import ConflictError, Store, new_meta
    from lws_tpu.api.node import CLUSTER_NAMESPACE, Node

    store = Store()
    store.create(Node(meta=new_meta("chaos-node", namespace=CLUSTER_NAMESPACE)))
    global_faults.arm("store.conflict", "every_k:2")
    # Every 2nd update loses an injected optimistic-concurrency race.
    node = store.get("Node", CLUSTER_NAMESPACE, "chaos-node")
    store.update(node)  # hit 1: passes
    node = store.get("Node", CLUSTER_NAMESPACE, "chaos-node")
    with pytest.raises(ConflictError, match="injected"):
        store.update(node)  # hit 2: injected loss
    store.update(node)  # retry with the SAME rv converges (hit 3 passes)


def test_api_server_retry_loops_absorb_injected_conflicts(global_faults):
    """The /scale path's _retry_conflicts must converge through an armed
    conflict schedule — the fault proves the retry loop is load-bearing."""
    import urllib.request as _rq

    from lws_tpu.api.types import (
        LeaderWorkerSet, LeaderWorkerSetSpec, LeaderWorkerTemplate,
    )
    from lws_tpu.api.pod import PodTemplateSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    cp = ControlPlane()
    cp.store.create(LeaderWorkerSet(
        meta=new_meta("scale-chaos"),
        spec=LeaderWorkerSetSpec(
            replicas=1,
            leader_worker_template=LeaderWorkerTemplate(
                size=1, worker_template=PodTemplateSpec()),
        ),
    ))
    api = ApiServer(cp, port=0)
    api.start()
    try:
        global_faults.arm("store.conflict", "every_k:2")
        req = _rq.Request(
            f"http://127.0.0.1:{api.port}/scale/default/scale-chaos",
            data=json.dumps({"replicas": 3}).encode(), method="POST",
        )
        with _rq.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read().decode())["replicas"] == 3
        global_faults.disarm()
        assert cp.store.get("LeaderWorkerSet", "default", "scale-chaos").spec.replicas == 3
    finally:
        api.stop()
