"""Flagship-scale config (models/flagship.py) + its bench stage.

VERDICT r4 #2: the representative ~8B-int8w single-chip configuration must
exist as a first-class bench stage, run on CPU in a shrunk smoke test, and
produce a record the moment hardware appears. These tests pin (a) the
direct-int8 init is structurally identical to quantize_params(init_params)
— the property that makes its throughput numbers representative — and
(b) the stage runs end to end on CPU and writes a well-formed artifact.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_direct_int8_init_matches_quantize_params_structure():
    from lws_tpu.models.flagship import flagship_config, init_quantized_params
    from lws_tpu.models.llama import init_params
    from lws_tpu.models.quant import quantize_params

    cfg = flagship_config("smoke")
    direct = init_quantized_params(cfg, jax.random.key(0))
    ref = quantize_params(init_params(cfg, jax.random.key(0)))
    assert jax.tree.structure(direct) == jax.tree.structure(ref)
    for a, b in zip(jax.tree.leaves(direct), jax.tree.leaves(ref)):
        assert a.shape == b.shape and a.dtype == b.dtype


def test_full_scale_fits_v5e_without_bf16_intermediate():
    """The sizing claim the whole stage rests on: 8B int8 weights ~8 GB
    (fits 16 GB), while the bf16 tree would be ~16 GB (does not fit).
    eval_shape only — nothing is materialized."""
    import jax.numpy as jnp

    from lws_tpu.models.flagship import flagship_config, init_quantized_params
    from lws_tpu.models.llama import init_params

    cfg = flagship_config("full")
    assert 7.5e9 < cfg.n_params() < 9e9
    qshapes = jax.eval_shape(lambda k: init_quantized_params(cfg, k), jax.random.key(0))
    q_gb = sum(a.size * jnp.dtype(a.dtype).itemsize for a in jax.tree.leaves(qshapes)) / 1e9
    assert 7.5 < q_gb < 10.0, q_gb
    fshapes = jax.eval_shape(lambda k: init_params(cfg, k), jax.random.key(0))
    f_gb = sum(a.size * jnp.dtype(a.dtype).itemsize for a in jax.tree.leaves(fshapes)) / 1e9
    assert f_gb > 14.0, f_gb  # bf16 tree genuinely does not fit the chip


def test_flagship_generates_sane_tokens():
    """Random int8 weights must not NaN out — magnitudes were chosen to
    match init_params' statistics."""
    import jax.numpy as jnp
    import numpy as np

    from lws_tpu.models.flagship import flagship_config, init_quantized_params
    from lws_tpu.serving import Engine

    cfg = flagship_config("smoke")
    params = init_quantized_params(cfg, jax.random.key(0))
    eng = Engine(cfg, params, batch_size=2, max_len=64)
    prompt = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size).astype(jnp.int32)
    r = eng.generate(prompt, max_new_tokens=8)
    toks = np.asarray(r.tokens)
    assert toks.shape[-1] >= 8
    assert ((toks >= 0) & (toks < cfg.vocab_size)).all()


@pytest.mark.slow
def test_flagship_bench_stage_cpu_smoke(tmp_path):
    """The orchestrator stage end to end on CPU: artifact written, both rows
    present, no error rows, headline parseable from the last stdout line."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "LWS_TPU_ARTIFACT_DIR": str(tmp_path),
                "LWS_TPU_ROUND": "rtest"})
    p = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "flagship_bench.py")],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=560,
    )
    assert p.returncode == 0, p.stderr[-800:]
    last = json.loads(p.stdout.strip().splitlines()[-1])
    assert last["unit"] == "tokens/s/chip" and last["value"] > 0
    art = json.load(open(tmp_path / "FLAGSHIP_rtest.json"))
    assert art["scale"] == "smoke" and not art["on_chip"]
    assert len(art["rows"]) == 2
    for row in art["rows"]:
        assert "error" not in row, row
        assert row["value"] > 0
    assert "int8w_verdict_at_scale" in art
