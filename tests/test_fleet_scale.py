"""Fleet-scale telemetry (ISSUE 17): the simulation harness, the two-tier
scrape tree, per-source series budgets, the O(delta) reconcile write
contract, and the bounded CLI renders.

Scale *claims* live in benchmarks/fleet_scale_bench.py (flat-vs-tree
wall-clock, merge peak memory, 10,000-group reconcile latency); these
tests pin the *semantics* at sizes tier-1 can afford: the harness is
deterministic and discovery-faithful, the tree shards and degrades per
shard, every budget drops loudly, and a steady-state reconcile writes
nothing."""

from __future__ import annotations

import time
from types import SimpleNamespace

from lws_tpu.core.metrics import MetricsRegistry, parse_exposition
from lws_tpu.core.store import Store
from lws_tpu.runtime.fleet import FleetCollector
from lws_tpu.runtime.simfleet import (
    SimFleet,
    SimFleetTarget,
    SimInstance,
    seed_groups,
)

# ---------------------------------------------------------------------------
# The simulation harness


def test_sim_instance_series_are_deterministic_and_schema_faithful():
    a = SimInstance("sim-0000", "prefill", "rev-a", seed=42)
    b = SimInstance("sim-0000", "prefill", "rev-a", seed=42)
    other = SimInstance("sim-0000", "prefill", "rev-a", seed=43)
    for inst in (a, b, other):
        inst.tick(5)
    assert a.registry.render() == b.registry.render()
    assert a.registry.render() != other.registry.render()
    fams = parse_exposition(a.registry.render())
    # The SLO plane's families with the SLO plane's label composition —
    # the canary/recommender folds key on exactly these.
    assert fams["serving_tokens_total"]["type"] == "counter"
    labels = dict(fams["serving_tokens_total"]["samples"][0][1])
    assert labels == {"engine": "prefill", "klass": "chat",
                      "revision": "rev-a"}
    assert "serving_ttft_seconds" in fams
    assert "serving_slo_attainment" in fams


def test_sim_fleet_serves_real_telemetry_over_http():
    with SimFleet(n_instances=2, seed=7) as fleet:
        fleet.tick(2)
        import urllib.request

        port = fleet.instances[0].port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            text = resp.read().decode()
        fams = parse_exposition(text)
        assert fams["serving_requests_total"]["samples"]


def test_sim_fleet_pods_discovered_and_sharded_by_tree_scrape():
    store = Store()
    own = MetricsRegistry()
    with SimFleet(store=store, n_instances=10, seed=3) as fleet:
        fleet.tick(1)
        fc = FleetCollector(store, shard_size=4, metrics_registry=own,
                            cache_ttl_s=0.0)
        assert len(fc.targets()) == 10
        text = fc.render_fleet(force=True)
        fams = parse_exposition(text)
        instances = {
            dict(s[1]).get("instance")
            for s in fams["serving_requests_total"]["samples"]
        }
        assert instances == {i.name for i in fleet.instances}
        # 10 instances over 2 roles with shard_size=4: prefill 5 -> 2
        # shards, decode 5 -> 2 shards; each observed its own latency.
        shards = {
            dict(labels)["shard"]
            for name, labels, _, _ in parse_exposition(own.render()).get(
                "lws_fleet_shard_scrape_seconds", {"samples": []})["samples"]
            if name == "lws_fleet_shard_scrape_seconds_count"
        }
        assert shards == {"prefill-0", "prefill-1", "decode-0", "decode-1"}
        assert own.gauge_value("lws_fleet_instances",
                               {"state": "scraped"}) == 10.0
        assert own.gauge_value("lws_fleet_instances",
                               {"state": "failed"}) == 0.0


def test_sim_fleet_target_speaks_the_loadgen_protocol():
    with SimFleet(n_instances=3, seed=5) as fleet:
        target = SimFleetTarget(fleet, seed=1)
        req = SimpleNamespace(index=0, klass="chat", prompt=[1, 2],
                              max_new_tokens=8)
        handles = [target.submit(req, 0.0) for _ in range(6)]
        target.step()
        results = [target.poll(h) for h in handles]
        assert all(r is not None and r["n_tokens"] == 8 for r in results)
        assert all(target.poll(h) is None for h in handles)  # consumed
        assert sum(i.requests for i in fleet.instances) == 6


def test_seed_groups_totals_requested_group_count():
    store = Store()
    lwss = seed_groups(store, 1001, replicas_per_lws=500)
    assert sum(l.spec.replicas for l in lwss) == 1001
    assert len(store.list("LeaderWorkerSet")) == 3


# ---------------------------------------------------------------------------
# Per-source budgets (tentpole d): every bound drops loudly.


def test_history_ring_per_source_budget_caps_one_instance():
    from lws_tpu.obs.history import HistoryRing

    reg = MetricsRegistry()
    ring = HistoryRing(interval_s=0.0, retention_s=600.0,
                       metrics_registry=reg, max_series_per_source=2)
    src = MetricsRegistry()
    for i in range(5):
        src.set("serving_active_slots", 1.0,
                {"engine": f"e{i}", "instance": "w-hot"})
    src.set("serving_active_slots", 1.0,
            {"engine": "e0", "instance": "w-calm"})
    ring.ingest(src.render(), now=1.0)
    snap = ring.snapshot()
    by_source: dict = {}
    for s in snap["series"]:
        inst = (s.get("labels") or {}).get("instance")
        by_source[inst] = by_source.get(inst, 0) + 1
    assert by_source["w-hot"] == 2  # capped at the per-source budget
    assert by_source["w-calm"] == 1  # the calm source was not starved
    assert reg.counter_value("lws_history_series_dropped_total") == 3.0


def test_journey_vault_source_budget_is_fair_across_sources():
    from lws_tpu.obs.journey import JourneyVault

    reg = MetricsRegistry()
    v = JourneyVault(budget_records=1000, source_budget_records=3,
                     sample_rate=0.0, slowest_k=0, rng=lambda: 1.0,
                     registry=reg)

    def breach(rid: str, klass: str, revision: str) -> None:
        v.on_span({"name": "serve.request", "trace_id": f"t{rid}",
                   "span_id": f"s{rid}", "parent_id": None,
                   "start_unix": 1.0, "duration_s": 0.5, "status": "ok",
                   "attrs": {}})
        v.complete(rid, trace={"trace_id": f"t{rid}"}, klass=klass,
                   revision=revision, ok=False, phases={"ttft_s": 2.0},
                   targets={"ttft_s": 1.0})

    for i in range(6):
        breach(f"hot{i}", "chat", "rev-hot")
    for i in range(2):
        breach(f"calm{i}", "batch", "rev-calm")
    # The hot source held to its share; the calm one untouched.
    assert reg.counter_value(
        "serving_journeys_dropped_total", {"reason": "source_budget"}) == 3.0
    assert v.get("hot0") is None and v.get("hot5") is not None
    assert v.get("calm0") is not None and v.get("calm1") is not None
    stats = v.stats()
    assert stats["sources"] == 2
    assert stats["source_budget_records"] == 3
    # The global budget path still works above the per-source one.
    assert stats["records"] == 5


def test_rollout_ledger_per_kind_budget_and_counted_eviction():
    from lws_tpu.obs.rollout import RolloutLedger

    reg = MetricsRegistry()
    ledger = RolloutLedger(capacity=100, capacity_per_kind=3, registry=reg,
                           clock=lambda: 50.0)
    for i in range(5):
        ledger.record("pod_created", obj=f"Pod p{i}")
    ledger.record("revision_flip", obj="GroupSet g")
    entries = ledger.snapshot(limit=100)
    pods = [e for e in entries if e["kind"] == "pod_created"]
    assert len(pods) == 3
    assert pods[0]["object"] == "Pod p2"  # oldest two evicted
    assert [e["kind"] for e in entries][-1] == "revision_flip"
    assert reg.counter_value("lws_rollout_ledger_dropped_total",
                             {"kind": "pod_created"}) == 2.0


def test_rollout_ledger_global_capacity_still_counts_drops():
    from lws_tpu.obs.rollout import RolloutLedger

    reg = MetricsRegistry()
    ledger = RolloutLedger(capacity=4, capacity_per_kind=0, registry=reg,
                           clock=lambda: 50.0)
    for i in range(6):
        ledger.record("scale", obj=f"LeaderWorkerSet l{i}")
    assert len(ledger.snapshot(limit=100)) == 4
    assert reg.counter_value("lws_rollout_ledger_dropped_total",
                             {"kind": "scale"}) == 2.0


# ---------------------------------------------------------------------------
# Satellite: O(delta) reconcile — a steady-state pass writes NOTHING.


def test_steady_state_reconcile_writes_nothing_at_200_groups():
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.testing import LWSBuilder, make_all_groups_ready

    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(200).size(1).build())
    cp.run_until_stable()
    make_all_groups_ready(cp, "sample")
    cp.run_until_stable()
    kinds = ("LeaderWorkerSet", "GroupSet", "Pod", "Service",
             "ControllerRevision", "Event", "PodGroup")
    before = {k: cp.store.kind_version(k) for k in kinds}
    started = time.perf_counter()
    cp.resync()  # enqueue EVERY object to every controller: a full pass
    cp.run_until_stable()
    elapsed = time.perf_counter() - started
    after = {k: cp.store.kind_version(k) for k in kinds}
    assert after == before, {
        k: (before[k], after[k]) for k in kinds if after[k] != before[k]
    }
    # The per-replica memo makes the pass cheap, not just write-free;
    # generous ceiling so slow CI never flakes.
    assert elapsed < 30.0


# ---------------------------------------------------------------------------
# Satellite: bounded CLI renders.


def test_render_top_bounds_rows_worst_first_with_footer():
    from lws_tpu.cli import render_top

    rows = {}
    for i in range(50):
        rows[(f"w{i:03d}", "paged")] = {
            "slo": 1.0 - i * 0.01, "requests": 10.0,
        }
    frame = render_top({}, rows=rows, top_k=5)
    body = frame.splitlines()
    # Worst attainment first: w049 (0.51) leads, healthy w000 elided.
    assert body[2].startswith("w049")
    assert not any(line.startswith("w000") for line in body)
    assert body[-1] == "… 45 more instances (raise --top-k)"
    # Unbounded renders everything, no footer.
    full = render_top({}, rows=rows, top_k=0)
    assert any(line.startswith("w000") for line in full.splitlines())
    assert "more instances" not in full


def test_render_top_default_bound_matches_issue_contract():
    from lws_tpu.cli import render_top

    rows = {
        (f"i{n:04d}", "paged"): {"slo": 0.99, "requests": 1.0}
        for n in range(1000)
    }
    frame = render_top({}, rows=rows)
    lines = frame.splitlines()
    assert lines[-1] == "… 960 more instances (raise --top-k)"
    assert len([l for l in lines if l.startswith("i")]) == 40


def test_render_monitor_bounds_burn_table_hottest_first():
    from lws_tpu.cli import render_monitor

    samples = [
        ("serving_slo_burn_rate",
         {"engine": "paged", "instance": f"w{i:03d}", "window": "fast"},
         float(i), None)
        for i in range(10)
    ]
    fams = {"serving_slo_burn_rate": {"type": "gauge", "help": "",
                                      "samples": samples}}
    frame = render_monitor({"series": []}, fams, top_k=3)
    lines = frame.splitlines()
    burn_rows = [l for l in lines if "@w" in l]
    assert len(burn_rows) == 3
    assert "@w009" in burn_rows[0]  # hottest first
    assert any("… 7 more instances (raise --top-k)" in l for l in lines)
    unbounded = render_monitor({"series": []}, fams, top_k=0)
    assert len([l for l in unbounded.splitlines() if "@w" in l]) == 10
