"""Direct GroupSet controller semantics (the statefulset-controller role the
reference outsources to Kubernetes): ordinals, parallel creation, partition
rolling updates within the unavailability budget, PVC provisioning."""

from lws_tpu.api.groupset import GroupSet, GroupSetSpec, GroupSetUpdateStrategy, groupset_ready
from lws_tpu.api.pod import Container, PodSpec, PodTemplateSpec, TemplateMeta, VolumeClaimTemplate
from lws_tpu.controllers.groupset_controller import GroupSetReconciler
from lws_tpu.core.events import EventRecorder
from lws_tpu.core.store import Store, new_meta
from lws_tpu.testing import set_pod_ready


def make_gs(name="gs", replicas=3, start=0, image="a:v1", partition=0, max_unavailable=1):
    return GroupSet(
        meta=new_meta(name),
        spec=GroupSetSpec(
            replicas=replicas,
            start_ordinal=start,
            template=PodTemplateSpec(
                metadata=TemplateMeta(labels={"app": name}),
                spec=PodSpec(containers=[Container(image=image)]),
            ),
            service_name=name,
            update_strategy=GroupSetUpdateStrategy(partition=partition, max_unavailable=max_unavailable),
        ),
    )


def harness():
    store = Store()
    rec = GroupSetReconciler(store, EventRecorder())
    return store, rec


def reconcile(store, rec, name="gs"):
    rec.reconcile(("GroupSet", "default", name))


def test_creates_ordinal_range_in_parallel():
    store, rec = harness()
    store.create(make_gs(replicas=3, start=1))
    reconcile(store, rec)
    names = sorted(p.meta.name for p in store.list("Pod"))
    assert names == ["gs-1", "gs-2", "gs-3"]  # start_ordinal=1 (worker sets)
    gs = store.get("GroupSet", "default", "gs")
    assert gs.status.replicas == 3
    assert gs.status.update_revision


def test_scale_down_removes_highest_ordinals():
    store, rec = harness()
    gs = store.create(make_gs(replicas=4))
    reconcile(store, rec)
    gs = store.get("GroupSet", "default", "gs")
    gs.spec.replicas = 2
    store.update(gs)
    reconcile(store, rec)
    assert sorted(p.meta.name for p in store.list("Pod")) == ["gs-0", "gs-1"]


def test_rolling_update_highest_first_within_budget():
    store, rec = harness()
    store.create(make_gs(replicas=3))
    reconcile(store, rec)
    for p in store.list("Pod"):
        set_pod_ready(store, "default", p.meta.name)
    reconcile(store, rec)
    gs = store.get("GroupSet", "default", "gs")
    assert groupset_ready(gs)

    gs.spec.template.spec.containers[0].image = "a:v2"
    store.update(gs)
    reconcile(store, rec)  # deletes the highest old-revision pod (budget 1)
    assert sorted(p.meta.name for p in store.list("Pod")) == ["gs-0", "gs-1"]
    reconcile(store, rec)  # recreates it from the new template
    pods = {p.meta.name: p for p in store.list("Pod")}
    assert pods["gs-2"].spec.containers[0].image == "a:v2"
    assert pods["gs-0"].spec.containers[0].image == "a:v1"
    reconcile(store, rec)
    pods = {p.meta.name: p for p in store.list("Pod")}
    assert pods["gs-1"].spec.containers[0].image == "a:v1"  # still held back

    set_pod_ready(store, "default", "gs-2")
    reconcile(store, rec)  # budget freed: deletes gs-1
    reconcile(store, rec)  # recreates gs-1 from the new template
    pods = {p.meta.name: p for p in store.list("Pod")}
    assert pods["gs-1"].spec.containers[0].image == "a:v2"


def test_partition_floor_respected():
    store, rec = harness()
    store.create(make_gs(replicas=3, partition=2))
    reconcile(store, rec)
    for p in store.list("Pod"):
        set_pod_ready(store, "default", p.meta.name)
    gs = store.get("GroupSet", "default", "gs")
    gs.spec.template.spec.containers[0].image = "a:v2"
    store.update(gs)
    for _ in range(4):
        reconcile(store, rec)
        for p in store.list("Pod"):
            if not p.status.ready:
                set_pod_ready(store, "default", p.meta.name)
    pods = {p.meta.name: p for p in store.list("Pod")}
    assert pods["gs-2"].spec.containers[0].image == "a:v2"
    assert pods["gs-0"].spec.containers[0].image == "a:v1"
    assert pods["gs-1"].spec.containers[0].image == "a:v1"


def test_pvcs_created_per_pod():
    store, rec = harness()
    gs = make_gs(replicas=2)
    gs.spec.volume_claim_templates = [VolumeClaimTemplate(name="data", storage="1Gi")]
    gs.spec.pvc_retention_policy_when_scaled = "Delete"
    store.create(gs)
    reconcile(store, rec)
    assert sorted(p.meta.name for p in store.list("PersistentVolumeClaim")) == [
        "data-gs-0", "data-gs-1",
    ]
    fresh = store.get("GroupSet", "default", "gs")
    fresh.spec.replicas = 1
    store.update(fresh)
    reconcile(store, rec)
    # whenScaled=Delete: the removed ordinal's PVC goes too.
    assert sorted(p.meta.name for p in store.list("PersistentVolumeClaim")) == ["data-gs-0"]
