"""Fleet time-series history plane (ISSUE 12): retained scrape rings
(reset-aware, retention/cardinality-bounded), pure derived signals (rates,
windowed quantiles, SRE-workbook multi-window burn), the dry-run scale
recommender + edge-triggered burn alerts, the /debug/history surfaces, the
`lws-tpu monitor`/`top` renders, and the deterministic end-to-end proof: a
seeded flash-crowd scenario against a live engine drives attainment below
target -> the fast-burn tier fires a Watchdog alert whose dump embeds the
offending series window, `serving_scale_recommendation{role="decode"}`
rises on the merged fleet exposition, and the opt-in annotation adapter
feeds the stock AutoscalerReconciler to the recommended replica count."""

import json
import urllib.error
import urllib.request

import pytest

from lws_tpu import obs
from lws_tpu.core import metrics, slo
from lws_tpu.core.flightrecorder import FlightRecorder, Watchdog, default_rules
from lws_tpu.core.metrics import MetricsRegistry, parse_exposition
from lws_tpu.obs.history import HistoryRing
from lws_tpu.obs.recommend import AnnotationAdapter, ScaleRecommender

# A second-scale twin of the SRE windows: same thresholds, 1/100th wall.
WINDOWS = tuple(w.scaled(0.05) for w in obs.DEFAULT_BURN_WINDOWS)


def _counter_text(name: str, labels: dict, value: float) -> str:
    reg = MetricsRegistry()
    reg.inc(name, labels, value)  # vet-exempt: test fixture, not lws_tpu/
    return reg.render()


# ---------------------------------------------------------------------------
# HistoryRing semantics


def test_ring_counter_reset_never_negative():
    """A restarted source's counter drops to (near) zero on the wire; the
    ring's reset adjustment keeps the stored series monotone, so rate() and
    increase() stay non-negative across the restart."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    labels = {"engine": "paged"}
    ring.ingest(_counter_text("serving_requests_total", labels, 100.0), now=0.0)
    ring.ingest(_counter_text("serving_requests_total", labels, 150.0), now=10.0)
    # Restart: raw value falls back to 7.
    ring.ingest(_counter_text("serving_requests_total", labels, 7.0), now=20.0)
    pts = ring.window("serving_requests_total", labels)
    assert [v for _, v in pts] == [100.0, 150.0, 157.0]
    assert obs.rate(pts, now=20.0) == pytest.approx((150 + 7 - 100) / 20.0)
    assert obs.increase(pts, window_s=15.0, now=20.0) == pytest.approx(7.0)


def test_ring_retention_and_retirement():
    """Points age out of the retention window; a series the source stopped
    exposing freezes (absent from live_keys), then drops wholesale once its
    tail ages out — retired series are never resurrected as current."""
    ring = HistoryRing(interval_s=0.0, retention_s=30.0)
    reg = MetricsRegistry()
    reg.set("serving_slo_attainment", 0.5, {"engine": "paged"})
    ring.ingest(reg.render(), now=0.0)
    key = ("serving_slo_attainment", (("engine", "paged"),))
    assert key in ring.live_keys()
    # The source retired the series (clear_gauge): later ingests omit it.
    reg.clear_gauge("serving_slo_attainment", {"engine": "paged"})
    reg.set("serving_active_slots", 1.0, {"engine": "paged"})
    ring.ingest(reg.render(), now=10.0)
    assert key not in ring.live_keys()
    # ...but the tail is retained (history, not current state) until the
    # retention bound passes, then the whole series disappears.
    assert ring.window("serving_slo_attainment", {"engine": "paged"})
    ring.ingest(reg.render(), now=45.0)
    assert ring.window("serving_slo_attainment", {"engine": "paged"}) == []
    assert not ring.series("serving_slo_attainment")


def test_ring_cardinality_cap_counts_drops():
    own = MetricsRegistry()
    ring = HistoryRing(interval_s=0.0, retention_s=60.0, max_series=2,
                       metrics_registry=own)
    reg = MetricsRegistry()
    for i in range(5):
        reg.inc("serving_requests_total", {"engine": f"e{i}"})
    ring.ingest(reg.render(), now=0.0)
    assert len(ring.series("serving_requests_total")) == 2
    assert own.counter_value("lws_history_series_dropped_total") == 3.0
    assert own.counter_value("lws_history_samples_total") == 1.0


def test_ring_ingest_if_due_gates_on_interval():
    ring = HistoryRing(interval_s=5.0, retention_s=60.0)
    calls = []

    def render():
        calls.append(1)
        return _counter_text("serving_requests_total", {}, float(len(calls)))

    assert ring.ingest_if_due(render, now=0.0) is True
    assert ring.ingest_if_due(render, now=2.0) is False  # inside the interval
    assert ring.ingest_if_due(render, now=5.0) is True
    assert len(calls) == 2  # the render thunk is only paid when due


def test_ring_histogram_buckets_are_reset_aware_counters():
    ring = HistoryRing(interval_s=0.0, retention_s=600.0)
    reg = MetricsRegistry()
    reg.observe("serving_ttft_seconds", 0.2, {"engine": "paged"})
    ring.ingest(reg.render(), now=0.0)
    reg.observe("serving_ttft_seconds", 3.0, {"engine": "paged"})
    ring.ingest(reg.render(), now=10.0)
    rows = ring.series("serving_ttft_seconds_bucket",
                       {"engine": "paged", "le": "+Inf"})
    assert len(rows) == 1
    _, _, kind, pts, _ = rows[0]
    assert kind == "counter"
    assert [v for _, v in pts] == [1.0, 2.0]


def test_ring_snapshot_roundtrip_seeds_a_client_ring():
    """load_snapshot rebases server timestamps onto the client clock while
    keeping relative spacing — the `lws-tpu top` first-frame seed path."""
    server = HistoryRing(interval_s=0.0, retention_s=600.0)
    labels = {"role": "prefill"}
    server.ingest(_counter_text("serving_kv_transfer_bytes_total", labels, 1e6),
                  now=1000.0)
    server.ingest(_counter_text("serving_kv_transfer_bytes_total", labels, 3e6),
                  now=1010.0)
    snap = server.snapshot()
    assert snap["series_total"] == 1
    client = HistoryRing(interval_s=0.0, retention_s=600.0)
    assert client.load_snapshot(snap, now=50.0) == 2
    pts = client.window("serving_kv_transfer_bytes_total", labels)
    assert [t for t, _ in pts] == [40.0, 50.0]
    assert obs.rate(pts, now=50.0) == pytest.approx(2e5)


def test_ring_seed_preserves_raw_state_across_server_resets():
    """A seeded client ring must keep comparing raw-to-raw: the server
    ring's ADJUSTED tail (raw 100 + offset 500 = 600) followed by a live
    raw sample of 101 is +1 of growth, not a fresh reset worth +101."""
    server = HistoryRing(interval_s=0.0, retention_s=600.0)
    labels = {"engine": "paged"}
    server.ingest(_counter_text("serving_requests_total", labels, 500.0), now=0.0)
    server.ingest(_counter_text("serving_requests_total", labels, 100.0), now=10.0)
    assert server.window("serving_requests_total", labels)[-1][1] == 600.0
    client = HistoryRing(interval_s=0.0, retention_s=600.0)
    client.load_snapshot(server.snapshot(), now=10.0)
    client.ingest(_counter_text("serving_requests_total", labels, 101.0), now=11.0)
    pts = client.window("serving_requests_total", labels)
    assert [v for _, v in pts] == [500.0, 600.0, 601.0]
    # ...and a REAL reset right after seeding still adjusts cleanly.
    client.ingest(_counter_text("serving_requests_total", labels, 2.0), now=12.0)
    assert client.window("serving_requests_total", labels)[-1][1] == 603.0


def test_ring_ingest_if_due_claims_the_slot_atomically():
    """Two threads crossing the interval boundary together must produce
    ONE ingest (the handler runs on a ThreadingHTTPServer)."""
    import threading

    ring = HistoryRing(interval_s=5.0, retention_s=60.0)
    text = _counter_text("serving_requests_total", {}, 1.0)
    results = []
    gate = threading.Barrier(2)

    def hit():
        gate.wait()
        results.append(ring.ingest_if_due(text, now=10.0))

    threads = [threading.Thread(target=hit) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == [False, True]
    assert len(ring.window("serving_requests_total", {})) == 1


# ---------------------------------------------------------------------------
# Signals


def test_rate_and_increase_need_two_points():
    assert obs.rate([(0.0, 5.0)]) is None
    assert obs.increase([(0.0, 5.0)]) is None
    assert obs.rate([]) is None


def test_rate_uses_observed_span_not_the_window():
    """A skipped scrape widens the denominator instead of corrupting the
    rate: 100 increments over 20 observed seconds is 5/s even when asked
    about a 60s window."""
    pts = [(0.0, 0.0), (20.0, 100.0)]
    assert obs.rate(pts, window_s=60.0, now=20.0) == pytest.approx(5.0)


def test_mean_is_time_weighted():
    # 0.0 held for 9s, then 1.0 for 1s: the simple mean (0.5) would
    # over-weight the late burst.
    pts = [(0.0, 0.0), (9.0, 1.0), (10.0, 1.0)]
    assert obs.mean(pts, now=10.0) == pytest.approx(0.1)


def test_ewma_and_slope():
    pts = [(float(t), float(t)) for t in range(10)]
    assert obs.slope(pts) == pytest.approx(1.0)
    smoothed = obs.ewma(pts, tau_s=1.0)
    assert smoothed is not None and 7.0 < smoothed < 9.0
    assert obs.slope([(0.0, 1.0)]) is None


def test_quantile_over_window_recovers_after_bad_hour():
    """The windowed quantile sags back once traffic improves — the lifetime
    histogram can't, which is why the monitor uses this one."""
    # Bad era (t<=10): 100 slow observations land past every finite bucket;
    # good era (t>10): 200 fast observations land under 0.1s.
    buckets = {
        "0.1": [(0.0, 0.0), (10.0, 0.0), (20.0, 100.0), (30.0, 200.0)],
        "1.0": [(0.0, 0.0), (10.0, 0.0), (20.0, 100.0), (30.0, 200.0)],
        "+Inf": [(0.0, 0.0), (10.0, 100.0), (20.0, 200.0), (30.0, 300.0)],
    }
    lifetime = obs.quantile_over_window(buckets, 0.95, now=30.0)
    recent = obs.quantile_over_window(buckets, 0.95, window_s=15.0, now=30.0)
    assert lifetime > 0.5
    assert recent <= 0.1


def test_breach_fraction_grades_against_the_covering_bucket():
    buckets = {
        "0.5": [(0.0, 0.0), (10.0, 80.0)],
        "1.0": [(0.0, 0.0), (10.0, 90.0)],
        "+Inf": [(0.0, 0.0), (10.0, 100.0)],
    }
    # Target 1.0 -> covering bucket le=1.0 -> 10% breached.
    assert obs.breach_fraction(buckets, 1.0, now=10.0) == pytest.approx(0.10)
    # Target 0.7 falls between bounds -> conservative covering le=1.0.
    assert obs.breach_fraction(buckets, 0.7, now=10.0) == pytest.approx(0.10)
    # A target past every finite bucket: the widest bucket's observations
    # are certainly within target; the open-ended tail stays counted.
    assert obs.breach_fraction(buckets, 99.0, now=10.0) == pytest.approx(0.10)
    assert obs.breach_fraction({}, 1.0, now=10.0) is None


def _ledger(points):
    """(good, total) point lists from [(t, good_cum, total_cum)]."""
    return ([(t, g) for t, g, _ in points], [(t, tot) for t, _, tot in points])


def test_multiwindow_burn_blip_does_not_fire_sustained_does():
    """The SRE AND-of-two-windows: a 15s blip of 100% errors inside an
    otherwise healthy long window burns the short window hot but not the
    long one — no page. Sustained errors burn both."""
    fast = WINDOWS[0]  # short 15s, long 180s at threshold 14.4
    # Blip: healthy, regularly-sampled traffic (50 tok/s all on time),
    # then 15s of all-bad — the short window burns hot, the long window
    # dilutes the blip below threshold.
    blip_pts = [(t, t * 50.0, t * 50.0) for t in range(0, 181, 30)]
    blip_pts.append((195.0, 9000.0, 9500.0))
    good, total = _ledger(blip_pts)
    verdicts = obs.multiwindow_burn(good, total, 0.99, WINDOWS, now=195.0)
    fast_v = verdicts[0]
    assert fast_v.short_burn == pytest.approx(100.0)
    assert fast_v.long_burn < fast.threshold
    assert not fast_v.firing
    # Sustained: the whole long window is all-bad.
    bad_pts = [(t, 0.0, t * 50.0) for t in range(0, 181, 30)]
    bad_pts.append((195.0, 0.0, 9500.0))
    good, total = _ledger(bad_pts)
    verdicts = obs.multiwindow_burn(good, total, 0.99, WINDOWS, now=195.0)
    assert verdicts[0].firing
    assert verdicts[0].short_burn == pytest.approx(100.0)


def test_burn_window_scale_env(monkeypatch):
    monkeypatch.setenv("LWS_TPU_BURN_WINDOW_SCALE", "0.01")
    ws = obs.burn_windows()
    assert ws[0].short_s == pytest.approx(3.0)
    assert ws[0].long_s == pytest.approx(36.0)
    assert ws[0].threshold == 14.4  # thresholds are scale-free
    monkeypatch.delenv("LWS_TPU_BURN_WINDOW_SCALE")
    assert obs.burn_windows() == obs.DEFAULT_BURN_WINDOWS


def test_burn_from_gauge_series():
    err = [(0.0, 0.0), (10.0, 0.5), (20.0, 0.5)]
    burn = obs.burn_rate_from_gauge(err, 0.95, window_s=10.0, now=20.0)
    assert burn == pytest.approx(10.0)  # 50% errors / 5% budget


# ---------------------------------------------------------------------------
# Recommender


def _burning_ring(now_span=195.0):
    """A ring whose decode-side ITL histogram breaches hard and whose
    goodput ledger burns both fast windows, plus calm prefill series."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    # Cumulative snapshots, all-bad from the start: the token ledger grows
    # with ZERO goodput (an all-late workload never increments the goodput
    # counter at all — the recommender must read absence as zero, not as
    # no-signal), and every ITL observation lands 50x over target.
    acc_total, acc_itl = 0.0, 0
    for t in (0.0, 90.0, 180.0, now_span):
        acc_total += 500.0
        acc_itl += 10
        cum = MetricsRegistry()
        for _ in range(acc_itl):
            cum.observe("serving_itl_seconds", 5.0, {"engine": "paged"})
        cum.inc("serving_tokens_total", {"engine": "paged"}, acc_total)
        ring.ingest(cum.render(), now=t)
    return ring


def test_recommender_scales_decode_on_itl_burn_and_publishes_gauges():
    ring = _burning_ring()
    reg = MetricsRegistry()
    fr = FlightRecorder()
    rec = ScaleRecommender(
        ring, targets=slo.SLOTargets(ttft_s=1.0, itl_s=0.1, queue_wait_s=0.5),
        attainment_target=0.99, windows=WINDOWS,
        current={"prefill": 1, "decode": 2}, max_replicas=8,
        registry=reg, recorder=fr,
    )
    verdict = rec.evaluate(now=195.0)
    # Every ITL observation is 5s against a 0.1s target: breach 1.0, burn
    # 100x -> severity caps at 4x of current.
    assert verdict.desired["decode"] == 8
    assert verdict.desired["prefill"] == 1  # no prefill-side signal
    assert "paged" in verdict.firing
    assert reg.gauge_value("serving_scale_recommendation",
                           {"role": "decode"}) == 8.0
    assert reg.gauge_value("serving_scale_recommendation",
                           {"role": "prefill"}) == 1.0
    fast_burn = reg.gauge_value("serving_slo_burn_rate",
                                {"engine": "paged", "window": "fast"})
    assert fast_burn is not None and fast_burn >= 14.4


def test_recommender_edge_triggered_watchdog_alert_with_window_in_dump():
    """The firing edge produces ONE alert + dump per episode (the
    circuit_open convention), and the dump's event ring carries the
    offending error-series window — evidence, not just a verdict."""
    ring = _burning_ring()
    reg = MetricsRegistry()
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=default_rules())
    rec = ScaleRecommender(ring, attainment_target=0.99, windows=WINDOWS,
                           registry=reg, recorder=fr)
    rec.evaluate(now=195.0)
    firing = wd.check_now(now=196.0)
    assert "burn_rate" in firing
    assert metrics.REGISTRY.gauge_value(
        "lws_watchdog_active", {"watchdog": "burn_rate"}) == 1.0
    dump = wd.last_dump
    assert dump is not None and dump["reason"] == "watchdog:burn_rate"
    fired = [e for e in dump["events"] if e["kind"] == "burn_rate_fired"]
    assert fired, dump["events"]
    assert fired[0]["series"] == "paged"
    assert fired[0]["error_window"], fired[0]
    assert all(v >= 0.99 for _, v in fired[0]["error_window"])
    # Steady firing: neither a second alert nor a second edge event.
    rec.evaluate(now=200.0)
    wd.check_now(now=201.0)
    assert metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "burn_rate"}) >= 1.0
    assert len([e for e in fr.events() if e["kind"] == "burn_rate_fired"]) == 1
    # The dump embeds a history snapshot alongside the usual surfaces.
    assert "history" in dump


def test_recommender_publishes_worst_instance_burn_not_last_write():
    """On a fleet-fed ring the same (engine, klass) exists once per
    instance: the published burn gauge must be the WORST instance's, never
    whichever series happened to iterate last."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    for t in (0.0, 90.0, 180.0, 195.0):
        reg = MetricsRegistry()
        good_cal = 500.0 * (t / 195.0 * 3 + 1)
        # w-calm delivers everything on time; w-hot delivers nothing on time.
        reg.inc("serving_tokens_total",
                {"engine": "paged", "instance": "w-calm"}, good_cal)
        reg.inc("serving_goodput_tokens_total",
                {"engine": "paged", "instance": "w-calm"}, good_cal)
        reg.inc("serving_tokens_total",
                {"engine": "paged", "instance": "w-hot"}, good_cal)
        ring.ingest(reg.render(), now=t)
    out = MetricsRegistry()
    rec = ScaleRecommender(ring, attainment_target=0.99, windows=WINDOWS,
                           registry=out, recorder=FlightRecorder())
    verdict = rec.evaluate(now=195.0)
    burn = out.gauge_value("serving_slo_burn_rate",
                           {"engine": "paged", "window": "fast"})
    assert burn is not None and burn >= 14.4, burn  # w-hot's 100x, not 0x
    assert verdict.firing == ["paged"]  # one alert key, not one per instance
    assert any(b["instance"] == "w-hot" and b["firing"] for b in verdict.burns
               if b["window"] == "fast")


def test_recommender_retires_burn_gauges_when_series_leave_the_ring():
    """A burn gauge whose feeding goodput pair vanished (retired worker,
    aged-out class) must retire, not freeze at its last value — the same
    staleness contract core/slo.py applies to attainment."""
    ring = _burning_ring()
    reg = MetricsRegistry()
    rec = ScaleRecommender(ring, attainment_target=0.99, windows=WINDOWS,
                           registry=reg, recorder=FlightRecorder())
    rec.evaluate(now=195.0)
    labels = {"engine": "paged", "window": "fast"}
    assert reg.gauge_value("serving_slo_burn_rate", labels) is not None
    ring.clear()
    rec.evaluate(now=200.0)
    assert reg.gauge_value("serving_slo_burn_rate", labels) is None


def test_recommender_kv_occupancy_bumps_decode_without_burn():
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    for t, live in ((0.0, 80.0), (5.0, 88.0), (10.0, 92.0)):
        reg = MetricsRegistry()
        reg.set("serving_kv_pool_blocks", live, {"engine": "paged", "state": "live"})
        reg.set("serving_kv_pool_blocks", 100.0 - live,
                {"engine": "paged", "state": "free"})
        reg.set("serving_kv_pool_blocks", 0.0, {"engine": "paged", "state": "parked"})
        ring.ingest(reg.render(), now=t)
    rec = ScaleRecommender(ring, windows=WINDOWS, current={"decode": 2},
                           registry=MetricsRegistry(), recorder=FlightRecorder())
    verdict = rec.evaluate(now=10.0)
    assert verdict.desired["decode"] == 3
    assert "occupancy" in verdict.reasons["decode"]


def test_recommender_scales_in_one_step_when_calm_and_never_on_no_data():
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    # Calm: plenty of observations (dense enough that even the 15s short
    # window holds two samples), all within target.
    for t, n in ((0.0, 1), (90.0, 50), (180.0, 80), (190.0, 90), (195.0, 100)):
        reg = MetricsRegistry()
        for _ in range(max(1, n)):
            reg.observe("serving_itl_seconds", 0.001, {"engine": "paged"})
        reg.set("serving_kv_pool_blocks", 5.0, {"engine": "paged", "state": "live"})
        reg.set("serving_kv_pool_blocks", 95.0, {"engine": "paged", "state": "free"})
        reg.set("serving_kv_pool_blocks", 0.0, {"engine": "paged", "state": "parked"})
        ring.ingest(reg.render(), now=t)
    rec = ScaleRecommender(ring, windows=WINDOWS,
                           current={"prefill": 3, "decode": 3},
                           registry=MetricsRegistry(), recorder=FlightRecorder())
    verdict = rec.evaluate(now=195.0)
    assert verdict.desired["decode"] == 2  # one step, not a cliff
    # No data at all: recommendation holds — absence of data is not calm.
    empty = ScaleRecommender(HistoryRing(interval_s=0.0), windows=WINDOWS,
                             current={"prefill": 3, "decode": 3},
                             registry=MetricsRegistry(),
                             recorder=FlightRecorder())
    hold = empty.evaluate(now=0.0)
    assert hold.desired == {"prefill": 3, "decode": 3}
    assert hold.reasons["decode"] == "no signal"


def test_default_recommender_syncs_current_from_store_ds_roles():
    """The auto-evaluated process recommender must scale from the fleet's
    REAL per-role width, not a hardcoded baseline of 1."""
    from lws_tpu.api.disagg import (
        DisaggregatedRoleSpec,
        DisaggregatedSet,
        DisaggregatedSetSpec,
    )
    from lws_tpu.core.store import Store, new_meta
    from lws_tpu.obs import recommend as recmod

    store = Store()
    store.create(DisaggregatedSet(
        meta=new_meta("pair"),
        spec=DisaggregatedSetSpec(roles=[
            DisaggregatedRoleSpec(name="prefill", replicas=2),
            DisaggregatedRoleSpec(name="decode", replicas=5),
        ]),
    ))
    assert recmod.role_replicas_from_store(store) == {"prefill": 2,
                                                      "decode": 5}
    rec = recmod.default_recommender(store)
    try:
        assert rec.current["decode"] == 5
        assert rec.current["prefill"] == 2
    finally:
        recmod.RECOMMENDER = None  # don't leak the baseline across tests


# ---------------------------------------------------------------------------
# The opt-in actuation seam


def test_annotation_adapter_feeds_stock_autoscaler_to_recommended_count():
    from lws_tpu.api.autoscaler import Autoscaler, AutoscalerSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.testing import LWSBuilder

    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.create(Autoscaler(
        meta=new_meta("rec-asc"),
        spec=AutoscalerSpec(
            target="sample", min_replicas=1, max_replicas=6,
            metric="scale_recommendation", target_value=1.0,
            scale_down_stabilization=2,
        ),
    ))
    cp.run_until_stable()
    adapter = AnnotationAdapter(cp.store, "default", "sample")
    assert adapter.publish(4) == 1  # one ready leader annotated
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.spec.replicas == 4
    # The normalization holds at the new width: every leader reports
    # desired/n, so the HPA math reproduces the recommendation, not n x it.
    assert adapter.publish(4) == 4
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 4
    # Scale-in rides the controller's own stabilization guardrail.
    assert adapter.publish(2) == 4
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 4
    adapter.publish(2)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 2


def test_annotation_adapter_exact_on_awkward_float_pairs():
    """(desired=25, n=11): a bare desired/n share makes the HPA ceil land
    on 26 (float round-trip epsilon); the half-offset share must reproduce
    the recommendation exactly at every width."""
    from lws_tpu.api.autoscaler import Autoscaler, AutoscalerSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.testing import LWSBuilder

    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(11).size(1).build())
    cp.create(Autoscaler(
        meta=new_meta("rec-asc"),
        spec=AutoscalerSpec(
            target="sample", min_replicas=1, max_replicas=40,
            metric="scale_recommendation", target_value=1.0,
        ),
    ))
    cp.run_until_stable()
    adapter = AnnotationAdapter(cp.store, "default", "sample")
    assert adapter.publish(25) == 11
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 25


# ---------------------------------------------------------------------------
# /debug/history surfaces


def test_worker_telemetry_serves_history_with_limit_and_token_parity():
    from lws_tpu.obs import history as historymod
    from lws_tpu.runtime.telemetry import TelemetryServer

    historymod.HISTORY.clear()
    historymod.HISTORY.ingest(
        _counter_text("serving_requests_total", {"engine": "paged"}, 3.0),
        now=0.0,
    )
    server = TelemetryServer(port=0, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/history", timeout=10)
        assert err.value.code == 401  # bearer-gating parity
        req = urllib.request.Request(
            f"{base}/debug/history?limit=8",
            headers={"Authorization": "Bearer s3cret"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read().decode())
        names = {s["name"] for s in body["series"]}
        assert "serving_requests_total" in names
        assert body["retention_s"] > 0
        bad = urllib.request.Request(
            f"{base}/debug/history?limit=wat",
            headers={"Authorization": "Bearer s3cret"},
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(bad, timeout=10)
        assert err.value.code == 400  # parse_limit parity
    finally:
        server.stop()


def test_api_server_serves_history_and_fleet_scrape_feeds_the_ring():
    from lws_tpu.obs import history as historymod
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    historymod.HISTORY.clear()
    cp = ControlPlane(auto_ready=True)
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        # With a fleet collector wired, /metrics does NOT feed the ring
        # (two sources racing one interval gate would starve each other) —
        # the fleet scrape is the control plane's one history source, and
        # each fresh ingest also evaluates the default dry-run recommender
        # so the recommendation gauges exist on the NEXT scrape.
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.status == 200
        assert historymod.HISTORY.last_ingest_age() is None
        with urllib.request.urlopen(f"{base}/metrics/fleet", timeout=10) as resp:
            assert resp.status == 200
        assert historymod.HISTORY.last_ingest_age() is not None
        # The ingest evaluated the default recommender: the decision gauge
        # is in the process registry and rides the next FRESH fleet render
        # (the served text above predates it by construction — it was
        # rendered before the evaluation ran).
        assert metrics.REGISTRY.gauge_value(
            "serving_scale_recommendation", {"role": "decode"}) is not None
        assert "serving_scale_recommendation" in cp.fleet.render_fleet(force=True)
        with urllib.request.urlopen(f"{base}/debug/history?limit=0", timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["series_total"] > 0
        assert body["series"] == []  # limit=0 keeps the body bounded
        assert body["truncated"] == body["series_total"]
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/history?limit=-1", timeout=10)
        assert err.value.code == 400
    finally:
        api.stop()


# ---------------------------------------------------------------------------
# CLI renders


def _fleet_fams(rec_reg: MetricsRegistry) -> dict:
    return parse_exposition(rec_reg.render())


def test_render_monitor_sparklines_burn_and_recommendation():
    from lws_tpu.cli import render_monitor

    ring = HistoryRing(interval_s=0.0, retention_s=600.0)
    reg = MetricsRegistry()
    for t, v in ((0.0, 0.0), (10.0, 100.0), (20.0, 400.0)):
        cum = MetricsRegistry()
        cum.inc("serving_tokens_total", {"engine": "paged"}, v or 0.001)
        cum.set("serving_active_slots", t / 10.0, {"engine": "paged"})
        ring.ingest(cum.render(), now=t)
    reg.set("serving_slo_burn_rate", 20.0,
            {"engine": "paged", "klass": "chat", "window": "fast"})
    reg.set("serving_scale_recommendation", 3.0, {"role": "decode"})
    frame = render_monitor(
        ring.snapshot(), _fleet_fams(reg),
        alerts={"burn_rate": [{"source": "burn_rate:paged/chat"}]},
        now=20.0,
    )
    assert frame.startswith("MONITOR")
    assert "ALERT burn_rate" in frame
    assert "decode=3" in frame
    assert "20.0x" in frame  # the burn column
    assert "serving_tokens_total" in frame
    assert any(ch in frame for ch in "▁▂▃▄▅▆▇█")  # sparklines rendered


def test_top_first_frame_rates_from_seeded_history():
    """Satellite: `lws-tpu top --watch` frame 1 — KV_MB/S and GOOD% derive
    from the HistoryRing (seeded from the server's /debug/history), so the
    first rendered frame is never blank."""
    from lws_tpu.cli import _top_rows, history_rates, render_top

    server = HistoryRing(interval_s=0.0, retention_s=600.0)
    for t, kv, good, tot, disp in ((0.0, 0.0, 0.0, 0.0, 0.0),
                                   (10.0, 20e6, 900.0, 1000.0, 40.0)):
        reg = MetricsRegistry()
        reg.inc("serving_kv_transfer_bytes_total",
                {"instance": "w0", "role": "prefill"}, kv or 1e-9)
        reg.inc("serving_tokens_total",
                {"instance": "w0", "engine": "disagg"}, tot or 1e-9)
        reg.inc("serving_goodput_tokens_total",
                {"instance": "w0", "engine": "disagg"}, good or 1e-9)
        reg.observe("serving_decode_dispatch_duration_seconds", 0.01,
                    {"instance": "w0", "engine": "disagg"})
        for _ in range(int(disp)):
            reg.observe("serving_decode_dispatch_duration_seconds", 0.01,
                        {"instance": "w0", "engine": "disagg"})
        server.ingest(reg.render(), now=t)
    # The client ring seeds from the server snapshot BEFORE its first
    # fleet fetch — one fetch later it renders real rates.
    client = HistoryRing(interval_s=0.0, retention_s=600.0)
    assert client.load_snapshot(server.snapshot(), now=100.0) > 0
    reg = MetricsRegistry()
    reg.inc("serving_kv_transfer_bytes_total",
            {"instance": "w0", "role": "prefill"}, 20e6)
    reg.inc("serving_tokens_total", {"instance": "w0", "engine": "disagg"}, 1000.0)
    reg.inc("serving_goodput_tokens_total",
            {"instance": "w0", "engine": "disagg"}, 900.0)
    reg.observe("serving_decode_dispatch_duration_seconds", 0.01,
                {"instance": "w0", "engine": "disagg"})
    text = reg.render()
    client.ingest(text, now=100.0)
    fams = parse_exposition(text)
    rates = history_rates(client, now=100.0, window_s=600.0)
    rows = _top_rows(fams)
    frame = render_top(fams, rows=rows, rates=rates)
    line = next(ln for ln in frame.splitlines() if ln.startswith("w0"))
    assert "2.0" in line       # KV_MB/S: 20 MB over 10s
    assert "90%" in line       # GOOD% from the windowed ledger
    # Without history the same first frame would dash both columns.
    blank = next(ln for ln in render_top(fams, rows=rows).splitlines()
                 if ln.startswith("w0"))
    assert blank.rstrip().endswith("-")


# ---------------------------------------------------------------------------
# The end-to-end proof (ISSUE 12 acceptance): flash crowd -> burn alert ->
# recommendation on the fleet surface -> adapter feeds the stock autoscaler.


def test_flash_crowd_drives_burn_alert_recommendation_and_autoscaler():
    import numpy as np

    from lws_tpu import loadgen
    from lws_tpu.api.autoscaler import Autoscaler, AutoscalerSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.obs import history as historymod
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.testing import LWSBuilder

    historymod.HISTORY.clear()
    ring = historymod.HISTORY

    # A seeded flash-crowd scenario with UNMEETABLE targets: every token is
    # late by construction, so attainment lands below any target and the
    # goodput ledger burns its whole budget — deterministically.
    spec = loadgen.load_scenario("flash_crowd")
    for c in spec["classes"]:
        c["targets"] = {"ttft_s": 1e-4, "itl_s": 1e-6, "queue_wait_s": 1e-4}
    schedule = loadgen.build_schedule(spec, seed=7)
    assert loadgen.schedule_digest(schedule) == \
        loadgen.schedule_digest(loadgen.build_schedule(spec, seed=7))
    targets = loadgen.install_class_targets(spec)
    try:
        target = loadgen.build_local_target("paged", spec)
        # Warm one request per class BEFORE the baseline sample: every SLO
        # series must exist at t=0 so the window's deltas are the crowd's
        # alone (a counter born mid-window carries no first delta).
        warm = [
            loadgen.ScheduledRequest(index=i, arrival_s=0.0, klass=klass,
                                     prompt=np.array([5, 6, 7 + i], np.int32),
                                     max_new_tokens=2)
            for i, klass in enumerate(("chat", "premium"))
        ]
        warm_result = loadgen.run_schedule(warm, target, max_wall_s=30.0)
        assert all(o.completed for o in warm_result.outcomes)
        ring.ingest(metrics.REGISTRY.render(), now=0.0)  # pre-crowd baseline
        result = loadgen.run_schedule(schedule, target, max_wall_s=90.0)
        report = loadgen.summarize(result, targets, spec["horizon_s"],
                                   "flash_crowd", 7)
        assert report["all"]["completed"] == len(schedule)
        assert report["all"]["attainment"] == 0.0  # below target, hard
        ring.ingest(metrics.REGISTRY.render(), now=195.0)

        # Attainment on the live registry really sits below target.
        att = metrics.REGISTRY.gauge_value(
            "serving_slo_attainment", {"engine": "paged", "klass": "chat"})
        assert att is not None and att < 0.99

        fr = FlightRecorder()
        wd = Watchdog(recorder=fr, rules=default_rules())
        # The wall-scale SRE windows: the two injected sample times (0,
        # 195) both sit inside the 5m fast-short window, so the whole run
        # IS the window — deterministic regardless of how fast the engine
        # actually drained it.
        rec = ScaleRecommender(
            ring, class_targets=targets, attainment_target=0.99,
            windows=obs.DEFAULT_BURN_WINDOWS,
            current={"prefill": 1, "decode": 1},
            max_replicas=6, recorder=fr,
        )
        verdict = rec.evaluate(now=195.0)

        # 1. The fast-burn tier fires an edge-triggered Watchdog alert
        #    whose dump embeds the offending series window.
        assert any(k.startswith("paged") for k in verdict.firing), verdict
        firing = wd.check_now(now=196.0)
        assert "burn_rate" in firing
        dump = wd.last_dump
        fired = [e for e in dump["events"] if e["kind"] == "burn_rate_fired"]
        assert fired and fired[0]["error_window"], fired
        assert all(v > 0.9 for _, v in fired[0]["error_window"])
        assert "history" in dump  # the ring itself rides the dump

        # 2. The recommendation rises and rides the MERGED fleet
        #    exposition (the recommender publishes into the process
        #    registry, exactly like every other sensor).
        assert verdict.desired["decode"] > 1
        merged = metrics.merge_expositions([
            ({"instance": "engine-0", "role": "decode"},
             metrics.REGISTRY.render()),
        ])
        fams = parse_exposition(merged)
        rec_samples = {
            labels.get("role"): value
            for name, labels, value, _ in
            fams["serving_scale_recommendation"]["samples"]
            if name == "serving_scale_recommendation"
        }
        assert rec_samples["decode"] == float(verdict.desired["decode"])
        assert rec_samples["decode"] > 1.0
        burn_samples = [
            value for name, labels, value, _ in
            fams["serving_slo_burn_rate"]["samples"]
            if name == "serving_slo_burn_rate" and labels.get("window") == "fast"
        ]
        assert burn_samples and max(burn_samples) >= 14.4

        # 3. The opt-in annotation adapter feeds the stock
        #    AutoscalerReconciler to the recommended count, store-backed.
        cp = ControlPlane(auto_ready=True)
        cp.create(LWSBuilder().replicas(1).size(1).build())
        cp.create(Autoscaler(
            meta=new_meta("rec-asc"),
            spec=AutoscalerSpec(
                target="sample", min_replicas=1, max_replicas=6,
                metric="scale_recommendation", target_value=1.0,
            ),
        ))
        cp.run_until_stable()
        adapter = AnnotationAdapter(cp.store, "default", "sample")
        assert adapter.publish(verdict.desired["decode"]) == 1
        cp.run_until_stable()
        lws = cp.store.get("LeaderWorkerSet", "default", "sample")
        assert lws.spec.replicas == verdict.desired["decode"]
    finally:
        slo.RECORDER.set_class_targets({})
        historymod.HISTORY.clear()
