"""Install-bundle smoke (VERDICT r4 missing #2): the artifact users actually
deploy — `install DIR`'s rendered start.sh + config + TLS + tokens — must
itself stand up a working control plane. Render, launch start.sh as a real
OS process, apply examples/psum-smoke.yaml through the HTTPS API with the
rendered admin token + CA, and wait for the LWS to converge with the real
worker processes run by the bundle's local backend (≈ the reference's
image-build + kind deploy e2e, test/e2e/suite_test.go:101-118, without
needing a cluster)."""

import json
import os
import socket
import ssl
import subprocess
import time
import urllib.request

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_rendered_bundle_serves_and_runs_an_example(tmp_path):
    from lws_tpu.cli import main

    root = tmp_path / "bundle"
    assert main(["install", str(root)]) == 0

    port = free_port()
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    })
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # start.sh appends "$@" after its own flags; argparse last-wins, so the
    # ephemeral port overrides the rendered 9443 without editing the bundle.
    proc = subprocess.Popen(
        ["sh", str(root / "start.sh"), "--port", str(port)],
        cwd=ROOT, env=env,
        stdout=open(tmp_path / "serve.log", "wb"),
        stderr=subprocess.STDOUT,
    )
    server = f"https://127.0.0.1:{port}"
    ctx = ssl.create_default_context(cafile=str(root / "tls" / "ca.crt"))
    ctx.check_hostname = False  # cert SANs cover hostnames, not 127.0.0.1
    admin_token = open(root / "tokens.csv").read().splitlines()[1].split(",")[0]

    def api(path, raw=False):
        req = urllib.request.Request(
            f"{server}{path}", headers={"Authorization": f"Bearer {admin_token}"}
        )
        with urllib.request.urlopen(req, context=ctx, timeout=5) as r:
            body = r.read()
            return body if raw else json.loads(body)

    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                api("/healthz", raw=True)
                break
            except Exception:
                assert proc.poll() is None, open(tmp_path / "serve.log").read()[-2000:]
                time.sleep(0.5)
        else:
            pytest.fail("bundle server never became healthy")

        rc = main([
            "--cacert", str(root / "tls" / "ca.crt"),
            "--token", admin_token,
            "apply", "-f", os.path.join(ROOT, "examples", "psum-smoke.yaml"),
            "--server", server,
        ])
        assert rc == 0

        deadline = time.time() + 150
        status = {}
        while time.time() < deadline:
            lws = api("/apis/LeaderWorkerSet/default/psum")
            status = lws.get("status") or {}
            if status.get("ready_replicas") == 1:
                break
            time.sleep(1.0)
        assert status.get("ready_replicas") == 1, (
            status, open(tmp_path / "serve.log").read()[-2000:]
        )
        # The example's real worker processes ran the distributed psum and
        # wrote their result files (the bundle backend is the real
        # LocalBackend, same as production `backend: local`).
        deadline = time.time() + 90
        results = []
        while time.time() < deadline and len(results) < 2:
            results = [
                p for p in os.listdir("/tmp")
                if p.startswith("lws-tpu-psum-psum-") and p.endswith(".txt")
            ]
            time.sleep(1.0)
        assert len(results) >= 2, results
        for name in results:
            assert "ok=True" in open(os.path.join("/tmp", name)).read()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        for p in os.listdir("/tmp"):
            if p.startswith("lws-tpu-psum-psum-"):
                os.unlink(os.path.join("/tmp", p))
