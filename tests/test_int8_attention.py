"""int8-KV decode attention kernel: exactness vs the dequantize-then-attend
reference path (interpret mode; the on-chip win is the whole point —
cache HBM traffic stays int8 instead of materializing bf16 copies)."""

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.models.llama import _cached_attention, _dequantize_kv, _quantize_kv
from lws_tpu.ops.int8_attention import int8_decode_attention


def make_case(B=2, T=64, H=8, Hkv=4, hd=32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (B, 1, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, hd), jnp.float32)
    kq, k_scale = _quantize_kv(k)
    vq, v_scale = _quantize_kv(v)
    return q, kq, k_scale, vq, v_scale


def reference(q, kq, k_scale, vq, v_scale, pos):
    k = _dequantize_kv(kq, k_scale, jnp.float32)
    v = _dequantize_kv(vq, v_scale, jnp.float32)
    return _cached_attention(q, k, v, pos)


def test_matches_dequant_reference_scalar_pos():
    q, kq, k_scale, vq, v_scale = make_case()
    for pos in (0, 7, 63):
        want = reference(q, kq, k_scale, vq, v_scale, jnp.asarray(pos))
        got = int8_decode_attention(
            q, kq, k_scale, vq, v_scale, jnp.asarray(pos), interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )


def test_matches_dequant_reference_per_batch_pos():
    q, kq, k_scale, vq, v_scale = make_case(B=3, seed=1)
    pos = jnp.asarray([3, 40, 63])
    want = reference(q, kq, k_scale, vq, v_scale, pos)
    got = int8_decode_attention(q, kq, k_scale, vq, v_scale, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_engine_int8_kv_decode_still_exact():
    """The Engine's kv_quant decode (which routes through the kernel on TPU
    and the XLA path elsewhere) stays consistent with the bf16 engine to
    quantization tolerance."""
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving import Engine

    base = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=96, max_seq_len=64, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    cfg16 = LlamaConfig(**base)
    cfg8 = LlamaConfig(**base, kv_quant=True)
    params = jax.jit(lambda: init_params(cfg16, jax.random.key(0)))()
    prompt = jax.random.randint(jax.random.key(1), (2, 12), 0, 128).astype(jnp.int32)
    out16 = Engine(cfg16, params, batch_size=2, max_len=48).generate(prompt, 8)
    out8 = Engine(cfg8, params, batch_size=2, max_len=48).generate(prompt, 8)
    # Greedy argmax is robust to int8 KV noise on a random tiny model most
    # steps; require the large majority to agree rather than bit equality.
    same = (np.asarray(out16.tokens) == np.asarray(out8.tokens)).mean()
    assert same >= 0.75, same
