"""Fused int8 matmul kernel: exactness vs the XLA dequantize path
(interpret mode — the real-chip win is measured by bench.py BENCH_INT8=1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.models.quant import quantize_array
from lws_tpu.ops.int8_matmul import int8_matmul, supported


@pytest.mark.parametrize("m,d,f", [(8, 512, 256), (16, 1024, 512), (3, 512, 256)])
def test_matches_xla_dequant_path(m, d, f):
    key = jax.random.key(0)
    x = jax.random.normal(key, (m, d), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (d, f), jnp.float32)
    qa = quantize_array(w)
    want = (x @ qa.q.astype(jnp.float32)) * qa.scale.astype(jnp.float32)
    got = int8_matmul(x, qa.q, qa.scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-4)


def test_leading_dims_roundtrip():
    x = jax.random.normal(jax.random.key(2), (2, 4, 512), jnp.float32)
    w = jax.random.normal(jax.random.key(3), (512, 256), jnp.float32)
    qa = quantize_array(w)
    got = int8_matmul(x, qa.q, qa.scale, interpret=True)
    want = (x @ qa.q.astype(jnp.float32)) * qa.scale.astype(jnp.float32)
    assert got.shape == (2, 4, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-4)


def test_supported_gating():
    assert supported(16, 2048, 5632)      # decode MLP
    assert supported(16, 2048, 32000)     # lm_head (F = 125 * 256)
    assert not supported(16, 2048, 1000)  # ragged F
    assert not supported(16, 100, 256)    # ragged D
    assert not supported(4096, 2048, 5632)  # prefill-sized M: XLA wins there
