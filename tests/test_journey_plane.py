"""Request-journey forensics (ISSUE 13): the tail-sampled trace vault,
its three feeds (trace finish listener, flight-recorder observer, SLO
sink), the /debug/request[s] surfaces on both servers, the fleet join, and
the `lws-tpu explain` renderer.

Every retention test drives the vault with injected rng/clock — no
wall-clock sleeps, no probabilistic flake. The HTTP tests run real servers
on ephemeral ports, the same localhost path the multi-process e2e
(test_e2e_disagg) exercises with separate OS processes."""

import io
import json
import urllib.error
import urllib.request
from contextlib import redirect_stdout

from lws_tpu.core import flightrecorder, trace
from lws_tpu.core.flightrecorder import FlightRecorder
from lws_tpu.core.metrics import MetricsRegistry
from lws_tpu.core.slo import SLORecorder, SLOTargets
from lws_tpu.core.trace import Tracer, connected_tree
from lws_tpu.obs import journey
from lws_tpu.obs.journey import VAULT, JourneyVault, verdict


def make_vault(**kw):
    kw.setdefault("sample_rate", 0.0)
    kw.setdefault("slowest_k", 0)
    kw.setdefault("rng", lambda: 1.0)  # reservoir roll always loses
    kw.setdefault("registry", MetricsRegistry())
    return JourneyVault(**kw)


def span_record(trace_id, span_id, parent=None, name="serve.request",
                start=1.0, dur=0.5, attrs=None, status="ok"):
    return {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent, "start_unix": start, "duration_s": dur,
            "status": status, "attrs": attrs or {}}


TARGETS = {"ttft_s": 1.0, "itl_s": 0.1, "queue_wait_s": 0.5}


# ---------------------------------------------------------------------------
# Retention policy


def test_breached_journey_retained_and_resolved_by_either_id():
    v = make_vault()
    v.on_span(span_record("t1", "s1"))
    out = v.complete("r1", trace={"trace_id": "t1", "span_id": "s1"},
                     engine="disagg", ok=False,
                     phases={"ttft_s": 2.0}, targets=TARGETS)
    assert out == "breached"
    by_rid, by_tid = v.get("r1"), v.get("t1")
    assert by_rid is not None and by_tid is not None
    assert by_rid["id"] == by_tid["id"] == "r1"
    assert len(by_rid["spans"]) == 1
    assert v._registry.counter_value(
        "serving_journeys_retained_total", {"outcome": "breached"}) == 1.0


def test_healthy_request_not_sampled_is_dropped_and_counted():
    v = make_vault()
    v.on_span(span_record("t1", "s1"))
    out = v.complete("r1", trace={"trace_id": "t1"}, ok=True,
                     phases={"ttft_s": 0.1}, targets=TARGETS)
    assert out is None and v.get("r1") is None
    assert v._registry.counter_value(
        "serving_journeys_dropped_total", {"reason": "not_sampled"}) == 1.0


def test_reservoir_keeps_a_healthy_fraction():
    rolls = iter([0.9, 0.001, 0.9])  # only the middle request wins
    v = make_vault(sample_rate=0.02, rng=lambda: next(rolls))
    for i in range(3):
        v.complete(f"r{i}", trace={"trace_id": f"t{i}"}, ok=True,
                   phases={"ttft_s": 0.1}, targets=TARGETS)
    assert v.get("r0") is None and v.get("r2") is None
    assert v.get("r1")["outcome"] == "sampled"


def test_slowest_k_window_keeps_the_slow_tail():
    v = make_vault(slowest_k=2)
    for rid, ttft in (("a", 0.10), ("b", 0.30), ("c", 0.20)):
        v.complete(rid, trace={"trace_id": "t" + rid}, ok=True,
                   phases={"ttft_s": ttft}, targets=TARGETS)
    # "a" (the fastest) was displaced when "c" beat it.
    assert v.get("a") is None
    assert v.get("b")["outcome"] == "slowest"
    assert v.get("c")["outcome"] == "slowest"
    assert v._registry.counter_value(
        "serving_journeys_dropped_total", {"reason": "displaced"}) == 1.0
    # A faster-than-floor newcomer is NOT kept (and displaces nothing).
    assert v.complete("d", trace={"trace_id": "td"}, ok=True,
                      phases={"ttft_s": 0.05}, targets=TARGETS) is None
    assert v.get("b") is not None and v.get("c") is not None


def test_must_keep_classes_always_retained():
    v = make_vault()
    assert v.complete("e1", outcome="errored", error="boom",
                      trace={"trace_id": "te"}) == "errored"
    assert v.complete("d1", outcome="deadline_expired",
                      trace={"trace_id": "td"}) == "deadline_expired"
    # A retried-but-healthy request: the event flags it before completion.
    v.on_event({"kind": "kv_stream_torn", "request_id": "rt", "ts": 1.0})
    assert v.complete("rt", trace={"trace_id": "tt"}, ok=True,
                      phases={"ttft_s": 0.1}, targets=TARGETS) == "retried"
    # A fault-touched healthy request is kept too (chaos forensics).
    v.on_event({"kind": "fault_injected", "request_id": "rf",
                "point": "kv.ack", "mode": "drop", "ts": 1.0})
    assert v.complete("rf", trace={"trace_id": "tf"}, ok=True,
                      phases={"ttft_s": 0.1}, targets=TARGETS) == "fault"
    assert {row["id"] for row in v.index(outcome="all", limit=0) or []} == set()
    assert {row["id"] for row in v.index(outcome="retried")} == {"rt"}
    assert {row["id"] for row in v.index(outcome="errored")} == {"e1"}


def test_healthy_flood_never_evicts_retained_breached_journey():
    """The acceptance invariant: under a flood of retained-healthy traffic
    the budget evicts sampled journeys first — a breached journey survives,
    and the drop counters account for every loss."""
    reg = MetricsRegistry()
    v = make_vault(sample_rate=1.0, rng=lambda: 0.0,  # keep EVERY healthy
                   budget_records=40, registry=reg)
    v.on_span(span_record("tb", "sb"))
    assert v.complete("bad", trace={"trace_id": "tb"}, ok=False,
                      phases={"ttft_s": 5.0}, targets=TARGETS) == "breached"
    for i in range(200):  # each journey carries one span record
        v.on_span(span_record(f"t{i}", f"s{i}"))
        v.complete(f"ok{i}", trace={"trace_id": f"t{i}"}, ok=True,
                   phases={"ttft_s": 0.01}, targets=TARGETS)
    assert v.stats()["records"] <= v.budget_records
    assert v.get("bad") is not None, "healthy flood evicted a breached journey"
    retained = sum(
        reg.counter_value("serving_journeys_retained_total", {"outcome": o})
        for o in journey.OUTCOMES if o != "all"
    )
    dropped_budget = reg.counter_value(
        "serving_journeys_dropped_total", {"reason": "budget"})
    assert retained == 201.0
    # Everything retained beyond what fits was evicted under `budget`.
    assert dropped_budget == retained - v.stats()["kept"]


def test_aged_journeys_evicted_with_counter():
    clock = {"t": 0.0}
    v = make_vault(retention_s=10.0, clock=lambda: clock["t"])
    v.complete("old", trace={"trace_id": "t1"}, ok=False,
               phases={"ttft_s": 5.0}, targets=TARGETS)
    clock["t"] = 100.0
    v.complete("new", trace={"trace_id": "t2"}, ok=False,
               phases={"ttft_s": 5.0}, targets=TARGETS)
    assert v.get("old") is None and v.get("new") is not None
    assert v._registry.counter_value(
        "serving_journeys_dropped_total", {"reason": "aged"}) == 1.0


def test_annotation_payloads_count_against_the_budget():
    """KV chunk timelines attached to a KEPT journey are budget-tracked
    records — a retained streamed journey can't hold unbounded uncounted
    memory, and its eviction is accounted in the same record units."""
    v = make_vault(budget_records=10)
    v.complete("r1", trace={"trace_id": "t1"}, outcome="errored")
    v.annotate("r1", chunks=[{"seq": i} for i in range(8)])
    assert v.stats()["records"] == 8
    v.annotate("r1", chunks_produced=[{"seq": i} for i in range(8)])
    # 16 records under a 10-record budget: the must-keep class ALONE
    # exceeds the budget, so the oldest-flagged pass reclaims it — counted.
    assert v.get("r1") is None and v.stats()["records"] == 0
    assert v._registry.counter_value(
        "serving_journeys_dropped_total", {"reason": "budget"}) == 16.0


def test_read_paths_age_out_retained_journeys_without_traffic():
    """The age bound must hold on a QUIET process: with no further
    completions, index()/get() themselves sweep — retained journeys do not
    outlive LWS_TPU_JOURNEY_RETENTION_S just because traffic stopped."""
    clock = {"t": 0.0}
    v = make_vault(retention_s=10.0, clock=lambda: clock["t"])
    v.complete("r1", trace={"trace_id": "t1"}, ok=False,
               phases={"ttft_s": 9.0}, targets=TARGETS)
    clock["t"] = 100.0
    assert v.index(outcome="all") == []
    assert v.get("r1") is None
    assert v._registry.counter_value(
        "serving_journeys_dropped_total", {"reason": "aged"}) == 1.0


def test_second_engine_request_on_shared_trace_gets_its_own_verdict():
    """Engine paths carry no wire request id, so complete() keys on the
    trace id: two requests finishing on ONE shared trace must BOTH retain
    their verdicts — the second is a new journey under a distinct key, not
    an idempotent re-finish that silently discards a breach."""
    v = make_vault()
    v.complete("", trace={"trace_id": "T", "span_id": "r1-root"},
               ok=False, phases={"ttft_s": 9.0}, targets=TARGETS)
    out = v.complete("", trace={"trace_id": "T", "span_id": "r2-root"},
                     ok=False, phases={"ttft_s": 3.0}, targets=TARGETS)
    assert out == "breached"
    assert v._registry.counter_value(
        "serving_journeys_retained_total", {"outcome": "breached"}) == 2.0
    # Trace-id lookup still resolves to the NEWEST shared-trace journey,
    # even though the oldest one's key IS the trace id.
    assert v.get("T")["timeline"]["ttft_s"] == 3.0


def test_kill_switch_disables_direct_vault_entry_points(monkeypatch):
    """LWS_TPU_JOURNEYS=0 must disable the PLANE, not just install(): the
    disagg workers call VAULT.complete()/annotate() directly, so those
    entry points gate on the env too."""
    monkeypatch.setenv(journey.JOURNEYS_ENV, "0")
    v = make_vault()
    assert v.complete("r1", trace={"trace_id": "t1"},
                      outcome="errored") is None
    v.annotate("r1", chunks=[{"seq": 0}])
    assert v.get("r1") is None
    assert v.stats()["kept"] == 0 and v.stats()["pending"] == 0
    assert v._registry.counter_value(
        "serving_journeys_retained_total", {"outcome": "errored"}) == 0.0


def test_open_trace_buffer_is_lru_bounded_and_counted():
    v = make_vault(max_open_traces=4)
    for i in range(8):
        v.on_span(span_record(f"t{i}", f"s{i}"))
    assert v.stats()["open_traces"] == 4
    assert v._registry.counter_value(
        "serving_journeys_dropped_total", {"reason": "open_evicted"}) >= 4.0


def test_late_root_span_attaches_after_completion():
    """The serve.request root closes AFTER the timeline finishes (finish
    runs inside the span): a completed journey keeps absorbing its trace's
    spans."""
    v = make_vault()
    v.on_span(span_record("t1", "child", parent="root", name="serve.prefill"))
    v.complete("r1", trace={"trace_id": "t1"}, ok=False,
               phases={"ttft_s": 9.0}, targets=TARGETS)
    v.on_span(span_record("t1", "root", name="serve.request"))
    got = v.get("r1")
    assert {s["span_id"] for s in got["spans"]} == {"child", "root"}
    assert connected_tree(got["spans"])


# ---------------------------------------------------------------------------
# The three feeds, wired like install() does — on PRIVATE instances


def test_shared_trace_requests_do_not_steal_each_others_spans():
    """Two sequential requests grafted onto ONE trace (a client parenting
    both onto the same reconcile root — the e2e shape): the first retained
    journey's trace claim must release once its own root span attaches, or
    it would swallow the second request's spans forever."""
    v = make_vault()
    # Request 1: child, completion (ctx names the root), late root.
    v.on_span(span_record("T", "r1-child", parent="r1-root",
                          name="serve.prefill"))
    v.complete("r1", trace={"trace_id": "T", "span_id": "r1-root"},
               ok=False, phases={"ttft_s": 9.0}, targets=TARGETS)
    v.on_span(span_record("T", "r1-root", name="serve.request"))
    # Request 2 on the SAME trace id.
    v.on_span(span_record("T", "r2-child", parent="r2-root",
                          name="serve.prefill"))
    v.complete("r2", trace={"trace_id": "T", "span_id": "r2-root"},
               ok=False, phases={"ttft_s": 9.0}, targets=TARGETS)
    v.on_span(span_record("T", "r2-root", name="serve.request"))
    got1, got2 = v.get("r1"), v.get("r2")
    assert {s["span_id"] for s in got1["spans"]} == {"r1-child", "r1-root"}
    assert {s["span_id"] for s in got2["spans"]} == {"r2-child", "r2-root"}
    # Trace-id lookup prefers the NEWEST journey on the shared trace.
    assert v.get("T")["id"] == "r2"


def test_mid_request_trace_only_retry_event_raises_retried_flag():
    """resilience.call's `retry` events carry no request id — only the
    live trace ctx. One recorded MID-REQUEST (before any completion names
    the trace) must still join the journey at complete() and raise the
    must-keep `retried` flag: an otherwise-healthy retried request is a
    100%-retention class, not a reservoir roll."""
    v = make_vault()  # sample_rate 0: only the retried flag can keep it
    v.on_event({"kind": "retry", "site": "kv.pull_bundle",
                "trace": {"trace_id": "T", "span_id": "s-mid"}})
    v.on_span(span_record("T", "s1"))
    out = v.complete("r1", trace={"trace_id": "T", "span_id": "s1"},
                     ok=True, phases={"ttft_s": 0.1}, targets=TARGETS)
    assert out == "retried"
    got = v.get("r1")
    assert "retried" in got["flags"]
    assert any(e["kind"] == "retry" for e in got["events"])


def test_completed_journey_never_steals_spans_when_root_never_closes():
    """The worker deadline-drop shape: complete() against the CLIENT's
    wire ctx, whose root span never closes in this process — the claim
    can't release via the root-arrival path. A second request re-using
    the trace must still get its spans buffered fresh, not grafted onto
    the finished journey."""
    v = make_vault()
    v.complete("r1", trace={"trace_id": "T", "span_id": "remote-root"},
               outcome="deadline_expired")
    # Request 2's spans arrive on the same trace while r1 still "owns" it.
    v.on_span(span_record("T", "r2-child", parent="r2-root",
                          name="serve.prefill"))
    v.complete("r2", trace={"trace_id": "T", "span_id": "r2-root"},
               ok=False, phases={"ttft_s": 9.0}, targets=TARGETS)
    v.on_span(span_record("T", "r2-root", name="serve.request"))
    assert {s["span_id"] for s in v.get("r1")["spans"]} == set()
    assert {s["span_id"] for s in v.get("r2")["spans"]} == \
        {"r2-child", "r2-root"}


def test_slo_sink_completes_journey_with_phases_targets_and_verdict():
    v = make_vault()
    rec = SLORecorder(SLOTargets(ttft_s=1.0, itl_s=1.0, queue_wait_s=1.0),
                      registry=MetricsRegistry(), window=8)
    rec.journey_sinks.append(v.on_timeline)
    tl = rec.request("disagg", klass="premium", request_id="rq")
    tl.queue_wait(0.2)
    tl.first_token(2.5)  # breach
    tl.tokens(4, 0.02)
    assert tl.finish() is False
    got = v.get("rq")
    assert got is not None and got["outcome"] == "breached"
    assert got["klass"] == "premium" and got["engine"] == "disagg"
    assert got["timeline"]["ttft_s"] == 2.5
    assert got["timeline"]["targets"]["ttft_s"] == 1.0
    vd = verdict(got)
    assert not vd["ok"] and vd["phase"] == "ttft"
    assert "2.5000s" in vd["text"] and "1.0000s" in vd["text"]


def test_ring_wrap_mid_request_resolved_via_vault_first():
    """The exemplar dead-end regression: a long-lived request whose early
    spans the bounded span ring evicts mid-request still resolves — the
    vault buffered every span by trace id, and a breaching request is
    retained, so lookup by the exemplar's trace id finds the WHOLE
    subtree the ring already lost."""
    tracer = Tracer(ring=4, enabled=True, sample_rate=1.0)
    v = make_vault()
    tracer.add_finish_listener(v.on_span)
    with tracer.span("serve.request", request_id="long1") as root:
        trace_id = root.trace_id
        for i in range(16):  # wraps the 4-slot ring mid-request
            with tracer.span("serve.decode_dispatch", step=i):
                pass
    ring_ids = {s["span_id"] for s in tracer.spans()}
    assert len(ring_ids) == 4, "ring should have wrapped"
    v.complete("long1", trace={"trace_id": trace_id, "span_id": root.span_id},
               ok=False, phases={"ttft_s": 9.0}, targets=TARGETS)
    got = v.get(trace_id)  # the exemplar carries the TRACE id
    assert got is not None and got["id"] == "long1"
    vault_ids = {s["span_id"] for s in got["spans"]}
    assert len(vault_ids) == 17  # every dispatch + the root
    assert not (vault_ids <= ring_ids), "vault must outlive the ring wrap"


def test_flightrecorder_observer_joins_events_by_trace_ctx():
    rec = FlightRecorder()
    v = make_vault()
    rec.add_observer(v.on_event)
    tracer = Tracer(ring=64, enabled=True, sample_rate=1.0)
    tracer.add_finish_listener(v.on_span)
    # No way to fake trace.current_context() on a private tracer from the
    # recorder: hand the ctx explicitly, like the torn-stream events do.
    v.on_span(span_record("tr9", "s9"))
    v.complete("r9", trace={"trace_id": "tr9"}, ok=False,
               phases={"ttft_s": 9.0}, targets=TARGETS)
    rec.record("retry", site="kv.pull_bundle", request_id="r9")
    got = v.get("r9")
    assert "retried" in got["flags"]
    assert any(e["kind"] == "retry" for e in got["events"])


def test_vault_annotations_ride_the_journey():
    v = make_vault()
    chunks = [{"chunk": 0, "t_s": 0.01, "bytes": 100},
              {"chunk": 1, "t_s": 0.02, "bytes": 100}]
    v.annotate("rq", chunks=chunks)
    v.complete("rq", trace={"trace_id": "tq"}, ok=False,
               phases={"ttft_s": 9.0}, targets=TARGETS)
    assert v.get("rq")["annotations"]["chunks"] == chunks


def test_watchdog_dump_embeds_worst_journeys():
    VAULT.clear()
    try:
        VAULT.complete("dump-bad", trace={"trace_id": "tdump"},
                       engine="disagg", ok=False,
                       phases={"ttft_s": 9.0}, targets=TARGETS)
        dump = flightrecorder.dump(reason="test")
        assert any(j["id"] == "dump-bad" for j in dump["journeys"]), \
            dump["journeys"]
    finally:
        VAULT.clear()


# ---------------------------------------------------------------------------
# Debug surfaces: worker telemetry server + API server (400/401 parity)


def _get(url, token=None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode())


def _get_code(url, token=None):
    try:
        return _get(url, token)[0]
    except urllib.error.HTTPError as e:
        return e.code


def test_worker_journey_endpoints_gating_and_validation():
    from lws_tpu.runtime.telemetry import TelemetryServer

    VAULT.clear()
    server = TelemetryServer(port=0, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        VAULT.complete("w-bad", trace={"trace_id": "tw"}, engine="disagg",
                       klass="chat", ok=False,
                       phases={"ttft_s": 9.0}, targets=TARGETS)
        # Bearer gating parity with the other debug surfaces.
        assert _get_code(f"{base}/debug/request/w-bad") == 401
        assert _get_code(f"{base}/debug/requests") == 401
        status, body = _get(f"{base}/debug/request/w-bad", token="s3cret")
        assert status == 200 and body["outcome"] == "breached"
        assert body["source"] == "vault"
        # Trace-id resolution (the exemplar path) works over HTTP too.
        status, body = _get(f"{base}/debug/request/tw", token="s3cret")
        assert status == 200 and body["id"] == "w-bad"
        status, rows = _get(
            f"{base}/debug/requests?outcome=breached&klass=chat",
            token="s3cret")
        assert status == 200 and [r["id"] for r in rows] == ["w-bad"]
        # 400-parity: bad limit and unknown outcome are caller errors.
        assert _get_code(f"{base}/debug/requests?limit=-1",
                         token="s3cret") == 400
        assert _get_code(f"{base}/debug/requests?limit=bogus",
                         token="s3cret") == 400
        assert _get_code(f"{base}/debug/requests?outcome=weird",
                         token="s3cret") == 400
        assert _get_code(f"{base}/debug/request/unknown-id",
                         token="s3cret") == 404
    finally:
        server.stop()
        VAULT.clear()


def test_api_server_journey_endpoints_fleet_joined(tmp_path):
    """The cross-process join, over real localhost HTTP: two stub 'worker'
    servers each serve one leg of a request's journey; the API server's
    /debug/request/{id} merges them (plus its own local spans for the
    trace) into ONE connected tree, and /debug/requests merges the
    instance-labelled indexes."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from lws_tpu.api.pod import PodPhase
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer
    from tests.test_telemetry_plane import _make_worker_pod

    VAULT.clear()
    # The client/reconcile leg lives in THIS process: a root span whose
    # trace the workers' legs join (exactly how the e2e's client span
    # parents the prefill/decode subtrees).
    root = trace.TRACER.span("serve.request", role="client",
                             request_id="j-1")
    with root:
        pass
    tid = root.trace_id
    legs = {
        "prefill-pod": {
            "id": "j-1", "trace_id": tid, "outcome": "breached",
            "completed": True, "flags": ["breached"],
            "timeline": {"ttft_s": 2.0,
                         "targets": dict(TARGETS)},
            "events": [], "annotations": {"chunks": [
                {"chunk": 0, "t_s": 0.01, "bytes": 10}]},
            "spans": [span_record(tid, "pf-root", parent=root.span_id,
                                  name="serve.request"),
                      span_record(tid, "pf-prefill", parent="pf-root",
                                  name="serve.prefill")],
        },
        "decode-pod": {
            "id": "j-1", "trace_id": tid, "outcome": "retried",
            "completed": True, "flags": ["retried"],
            "timeline": {"worst_itl_s": 0.01,
                         "targets": dict(TARGETS)},
            "events": [{"kind": "kv_stream_torn", "request_id": "j-1",
                        "ts": 2.0, "error": "OSError('torn')"}],
            "annotations": {},
            "spans": [span_record(tid, "dc-root", parent="pf-root",
                                  name="serve.request"),
                      span_record(tid, "dc-dec", parent="dc-root",
                                  name="serve.decode_dispatch")],
        },
    }

    def make_stub(leg):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.startswith("/debug/request/j-1"):
                    body = json.dumps(leg).encode()
                elif self.path.startswith("/debug/requests"):
                    body = json.dumps([{
                        "id": "j-1", "outcome": leg["outcome"],
                        "klass": "", "engine": "disagg",
                        "latency_s": 2.0, "completed_unix": 5.0,
                    }]).encode()
                elif self.path == "/metrics":
                    body = b"# HELP x x\n# TYPE x counter\nx 1.0\n"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                else:
                    self.send_response(404)
                    body = b"{}"
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd

    stubs = [make_stub(legs["prefill-pod"]), make_stub(legs["decode-pod"])]
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        for name, httpd in zip(("prefill-pod", "decode-pod"), stubs):
            pod = cp.store.create(_make_worker_pod(
                name, httpd.server_port,
                role="prefill" if "prefill" in name else "decode"))
            pod.status.phase = PodPhase.RUNNING
            pod.status.ready = True
            pod.status.address = "127.0.0.1"
            cp.store.update_status(pod)
        status, joined = _get(
            f"http://127.0.0.1:{api.port}/debug/request/j-1")
        assert status == 200
        assert joined["connected"] is True, joined["spans"]
        instances = {s["instance"] for s in joined["spans"]}
        assert {"control-plane", "prefill-pod", "decode-pod"} <= instances
        assert set(joined["flags"]) == {"breached", "retried"}
        assert joined["outcome"] == "breached"  # worst leg wins
        assert joined["annotations"]["chunks"]
        leg_instances = {
            leg["labels"]["instance"] for leg in joined["legs"]
        }
        assert {"control-plane", "prefill-pod", "decode-pod"} <= leg_instances
        # The fleet-joined index carries instance labels.
        status, rows = _get(
            f"http://127.0.0.1:{api.port}/debug/requests?outcome=breached")
        assert status == 200
        assert any(r["instance"] == "prefill-pod" for r in rows), rows
        # 400 parity with the worker server.
        assert _get_code(
            f"http://127.0.0.1:{api.port}/debug/requests?outcome=weird"
        ) == 400
        assert _get_code(
            f"http://127.0.0.1:{api.port}/debug/requests?limit=bogus"
        ) == 400
        assert _get_code(
            f"http://127.0.0.1:{api.port}/debug/request/nobody"
        ) == 404

        # And the renderer consumes the joined record: the waterfall names
        # the legs and the verdict names the breaching phase.
        from lws_tpu.cli import render_explain

        frame = render_explain(joined)
        assert "WATERFALL" in frame and "serve.prefill" in frame
        assert "wire chunks: 1" in frame
        assert "kv_stream_torn" in frame
        assert "VERDICT" in frame and "ttft" in frame and "BREACHED" in frame

        # The CLI verb end to end against the live API server.
        from lws_tpu import cli as climod

        out = io.StringIO()
        with redirect_stdout(out):
            rc = climod.main(["explain", "j-1",
                              "--server", f"127.0.0.1:{api.port}"])
        assert rc == 0
        assert "VERDICT" in out.getvalue()
        out = io.StringIO()
        with redirect_stdout(out):
            rc = climod.main(["explain", "--breached",
                              "--server", f"127.0.0.1:{api.port}"])
        assert rc == 0
        assert "j-1" in out.getvalue()
    finally:
        api.stop()
        for httpd in stubs:
            httpd.shutdown()
        VAULT.clear()


def test_local_journey_falls_back_to_span_ring():
    """An unretained (healthy, unsampled) request is still explainable
    while its spans survive in the ring: vault first, ring second."""
    VAULT.clear()
    with trace.TRACER.span("serve.request", request_id="fresh-1") as s:
        tid = s.trace_id
    # Pretend the vault dropped it (healthy): wipe the open buffers.
    VAULT.clear()
    got = journey.local_journey(tid)
    assert got is not None and got["source"] == "ring"
    assert any(sp["trace_id"] == tid for sp in got["spans"])
    assert journey.local_journey("never-seen") is None


# ---------------------------------------------------------------------------
# loadgen worst-K offenders


def test_loadgen_report_lists_worst_requests_with_journey_ids():
    from lws_tpu.loadgen.report import render_report
    from lws_tpu.loadgen.runner import (
        RequestOutcome,
        RunResult,
        summarize,
    )

    targets = SLOTargets(ttft_s=0.5, itl_s=1.0, queue_wait_s=1.0)
    outcomes = [
        RequestOutcome(index=0, klass="chat", arrival_s=0.0,
                       request_id="lg-0", ttft_s=0.1, total_s=0.2,
                       n_tokens=4, completed=True),
        RequestOutcome(index=1, klass="chat", arrival_s=0.1,
                       request_id="lg-1", ttft_s=2.0, total_s=2.2,
                       n_tokens=4, completed=True),  # breach
        RequestOutcome(index=2, klass="chat", arrival_s=0.2,
                       request_id="lg-2"),           # never finished
    ]
    report = summarize(RunResult(outcomes=outcomes, wall_s=3.0),
                       {"chat": targets}, horizon_s=1.0, worst_k=2)
    worst = report["classes"]["chat"]["worst"]
    assert [w["id"] for w in worst] == ["lg-2", "lg-1"]
    assert worst[0]["completed"] is False
    assert worst[1]["attained"] is False
    frame = render_report(report)
    assert "worst chat: lg-2" in frame and "incomplete" in frame
    assert "worst chat: lg-1" in frame and "MISS" in frame


def test_run_schedule_stamps_request_ids():
    from lws_tpu.loadgen.runner import run_schedule
    from lws_tpu.loadgen.workload import ScheduledRequest

    class Target:
        def submit(self, req, arrival_wall_t):
            return f"rid-{req.index}"

        def step(self):
            pass

        def poll(self, handle):
            return {"n_tokens": 2}

    schedule = [
        ScheduledRequest(index=i, klass="chat", arrival_s=0.0,
                         prompt=[1, 2], max_new_tokens=2)
        for i in range(2)
    ]
    result = run_schedule(schedule, Target(), max_wall_s=5.0)
    assert [o.request_id for o in result.outcomes] == ["rid-0", "rid-1"]


# ---------------------------------------------------------------------------
# Renderer edge cases


def test_render_request_index_empty():
    from lws_tpu.cli import render_request_index

    assert "no retained journeys" in render_request_index([])


def test_verdict_shapes():
    assert verdict({"flags": ["errored"],
                    "timeline": {"error": "ValueError('x')"}})["phase"] == "error"
    assert verdict({"flags": ["deadline_expired"],
                    "timeline": {}})["phase"] == "deadline"
    ok = verdict({"flags": [], "timeline": {
        "ttft_s": 0.1, "targets": dict(TARGETS)}})
    assert ok["ok"] is True and ok["phase"] is None
    worst = verdict({"flags": ["breached"], "timeline": {
        "queue_wait_s": 5.0, "ttft_s": 1.1, "targets": dict(TARGETS)}})
    assert worst["phase"] == "queue_wait"  # 10x overrun beats 1.1x
