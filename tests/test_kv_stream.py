"""Streamed KV handoff (ISSUE 10): chunk-granular transfer with real
engines over real sockets, in one process. The contract under test is the
one the benchmark budgets and the e2e proves across processes:

  * BYTE-IDENTICAL greedy token streams, streamed vs the monolithic
    single-shot oracle (streaming reorders WHEN bytes move, never what the
    decode math sees);
  * the incremental CacheAssembler builds exactly the cache
    bundle_to_cache builds (device and host/mesh assembly paths);
  * speculative decode seeds its drafting history from the streamed prompt
    tokens without changing the token stream;
  * short prompts fall back to the single-shot path (a one-chunk stream is
    the monolithic transfer with extra frames).
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving import kv_transport as kt
from lws_tpu.serving.disagg_worker import (
    _decode_bundle,
    _prefill_streamed,
    kv_chunk_tokens,
    use_streaming,
)
from lws_tpu.serving.engine import Engine

MAX_LEN = 48
STEPS = 6


def tiny_cfg(**kw):
    return LlamaConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False, **kw,
    )


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return cfg, params


@pytest.fixture(scope="module")
def prompt():
    return np.asarray(
        jax.random.randint(jax.random.key(3), (21,), 0, 128), np.int32
    )


def make_engine(model):
    cfg, params = model
    return Engine(cfg, params, batch_size=1, max_len=MAX_LEN)


def pull_streamed(server, engine, device=True):
    """One decode-side pull with the worker's CacheAssembler shape, run in
    a thread (the server delivers from a connection thread while the
    caller produces)."""
    out = {}

    def puller():
        out["got"] = kt.pull_bundle(
            ("127.0.0.1", server.port), timeout=15.0, ack_timeout=60.0,
            receiver_factory=lambda m: kt.CacheAssembler(
                max_len=engine.max_len, device=device),
        )

    t = threading.Thread(target=puller, daemon=True)
    t.start()
    return t, out


def test_streamed_handoff_byte_identical_to_monolithic_oracle(model, prompt):
    pre = make_engine(model)
    dec_mono, dec_stream = make_engine(model), make_engine(model)
    server = kt.KVServer(port=0, host="127.0.0.1")
    try:
        # Oracle: the retained single-shot path, same engines end to end.
        token, cache = pre.prefill(jnp.asarray(prompt).reshape(1, -1))
        bundle = kt.cache_to_bundle(cache, token)
        want, mono_stats, _ = _decode_bundle(dec_mono, bundle, steps=STEPS)
        assert "streamed" not in mono_stats

        thread, out = pull_streamed(server, dec_stream)
        _prefill_streamed(pre, server, kt, {"id": "r1"}, "r1", prompt, 8, None)
        thread.join(timeout=60)
        meta, payload = out["got"]
        got, stats, _ = _decode_bundle(dec_stream, payload, steps=STEPS)

        np.testing.assert_array_equal(got, want)  # the whole point
        assert stats["streamed"] and stats["chunks"] == 3
        # Wire accounting agrees end to end: prefill's reported bundle
        # bytes == the receiver's counted payload bytes == decode's stats.
        assert meta["handoff"]["bundle_bytes"] == meta["payload_bytes"]
        assert stats["bundle_bytes"] == meta["payload_bytes"]
        assert meta["handoff"]["streamed"] and meta["handoff"]["chunks"] == 3
    finally:
        server.close()


def test_cache_assembler_matches_bundle_to_cache(model, prompt):
    """Feed prefill_chunked_stream's chunks straight into a CacheAssembler
    (no sockets): the assembled device cache is BIT-IDENTICAL to
    bundle_to_cache of the same chunked prefill's monolithic bundle."""
    pre, pre2 = make_engine(model), make_engine(model)
    tokens = jnp.asarray(prompt).reshape(1, -1)

    asm = kt.CacheAssembler(max_len=MAX_LEN, device=True)
    token_s, cache_s, stats = pre.prefill_chunked_stream(
        tokens, 8, emit=lambda lo, hi, a: asm.chunk(
            {"pos_range": [lo, hi]}, a),
    )
    asm.finish({}, {"token": np.asarray(token_s),
                    "pos": np.asarray(int(cache_s.pos), np.int32)})
    cache_a, token_a, pos, context = asm.take()

    token_c, cache_c = pre2.prefill_chunked(tokens, chunk_size=8)
    ref_cache, ref_token = kt.bundle_to_cache(
        kt.cache_to_bundle(cache_c, token_c), max_len=MAX_LEN)

    assert pos == len(prompt) == int(ref_cache.pos)
    np.testing.assert_array_equal(np.asarray(cache_a.k), np.asarray(ref_cache.k))
    np.testing.assert_array_equal(np.asarray(cache_a.v), np.asarray(ref_cache.v))
    np.testing.assert_array_equal(np.asarray(token_a), np.asarray(ref_token))
    np.testing.assert_array_equal(context[0], prompt)  # spec seeding input
    assert stats["chunks"] == asm.chunks == 3


def test_host_assembly_path_matches_device_path(model, prompt):
    """The mesh-decode shape (device=False): host-assembled np buffers ==
    the device path's arrays — the single device_put reshard leg sees the
    same cache either way."""
    pre, pre2 = make_engine(model), make_engine(model)
    tokens = jnp.asarray(prompt).reshape(1, -1)

    def run(engine, device):
        asm = kt.CacheAssembler(max_len=MAX_LEN, device=device)
        token, cache, _ = engine.prefill_chunked_stream(
            tokens, 8, emit=lambda lo, hi, a: asm.chunk(
                {"pos_range": [lo, hi]}, a),
        )
        asm.finish({}, {"token": np.asarray(token),
                        "pos": np.asarray(int(cache.pos), np.int32)})
        return asm.take()

    cache_d, _, _, _ = run(pre, True)
    cache_h, _, _, _ = run(pre2, False)
    assert isinstance(cache_h.k, np.ndarray)
    np.testing.assert_array_equal(np.asarray(cache_d.k), cache_h.k)
    np.testing.assert_array_equal(np.asarray(cache_d.v), cache_h.v)


def test_streamed_spec_leg_seeds_history_and_stays_byte_identical(model, prompt):
    """gamma > 0 over a streamed handoff: the drafting history seeds from
    the streamed prompt tokens (context is not None reaches
    decode_speculative) and the greedy stream is STILL byte-identical —
    acceptance only ever keeps the model's own argmax chain."""
    pre = make_engine(model)
    dec_plain, dec_spec = make_engine(model), make_engine(model)
    server = kt.KVServer(port=0, host="127.0.0.1")
    try:
        token, cache = pre.prefill(jnp.asarray(prompt).reshape(1, -1))
        want, _, _ = _decode_bundle(
            dec_plain, kt.cache_to_bundle(cache, token), steps=STEPS)

        thread, out = pull_streamed(server, dec_spec)
        _prefill_streamed(pre, server, kt, {"id": "r2"}, "r2", prompt, 8, None)
        thread.join(timeout=60)
        _, payload = out["got"]
        assert payload._token_parts, "stream did not ship prompt tokens"
        got, stats, _ = _decode_bundle(
            dec_spec, payload, steps=STEPS, gamma=3, ngram=2)
        np.testing.assert_array_equal(got, want)
        assert stats["spec_gamma"] == 3 and stats["streamed"]
    finally:
        server.close()


def test_short_prompts_fall_back_to_single_shot():
    assert not use_streaming(prompt_len=5, chunk_tokens=256)
    assert not use_streaming(prompt_len=256, chunk_tokens=256)  # one chunk
    assert use_streaming(prompt_len=257, chunk_tokens=256)
    assert not use_streaming(prompt_len=10_000, chunk_tokens=0)  # oracle knob
    # Chunk padding must FIT the engine budget: a 270-token prompt under
    # chunk=256/max_len=300 pads to 512 — single-shot serves it fine, so
    # it must fall back instead of raising in the engine (crash loop).
    assert not use_streaming(prompt_len=270, chunk_tokens=256, max_len=300)
    assert use_streaming(prompt_len=270, chunk_tokens=256, max_len=512)
    assert use_streaming(prompt_len=21, chunk_tokens=8, max_len=24)  # pad 3 fits


def test_stream_fail_after_acks_keeps_gauge_consistent():
    """fail() racing an in-flight chunk ack must not double-decrement the
    process-wide inflight gauge (it would eat another live stream's
    contribution): fail() advances the ack high-water mark so a late
    chunk_acked() is a no-op."""
    import lws_tpu.serving.kv_transport as ktmod

    base = ktmod._INFLIGHT_CHUNKS
    stream = kt.KVStream(4)
    for lo in (0, 4, 8):
        stream.put_chunk(lo, lo + 4, {"k": np.zeros((1, 1, 4, 1, 1), np.float32)})
    stream.chunk_acked(0)
    assert ktmod._INFLIGHT_CHUNKS == base + 2
    stream.fail()  # clears the stream's remaining contribution...
    assert ktmod._INFLIGHT_CHUNKS == base
    stream.chunk_acked(1)  # ...and a LATE ack is a no-op, not a decrement
    stream.chunk_acked(2)
    assert ktmod._INFLIGHT_CHUNKS == base


def test_kv_chunk_env_knob(monkeypatch):
    monkeypatch.setenv("LWS_TPU_KV_CHUNK", "0")
    assert kv_chunk_tokens() == 0
    monkeypatch.setenv("LWS_TPU_KV_CHUNK", "64")
    assert kv_chunk_tokens() == 64
    monkeypatch.delenv("LWS_TPU_KV_CHUNK")
    assert kv_chunk_tokens() == 256  # streaming-by-default for long prompts


def test_prefill_chunked_stream_serial_ring_matches(model, prompt):
    """ring_depth=0 (fully serial gather) must emit the same chunks and
    first token as the overlapped default — the ring only schedules WHEN
    gathers happen, never what they contain."""
    pre_a, pre_b = make_engine(model), make_engine(model)
    tokens = jnp.asarray(prompt).reshape(1, -1)
    a, b = [], []
    tok_a, _, _ = pre_a.prefill_chunked_stream(
        tokens, 8, emit=lambda lo, hi, ar: a.append((lo, hi, ar)))
    tok_b, _, _ = pre_b.prefill_chunked_stream(
        tokens, 8, emit=lambda lo, hi, ar: b.append((lo, hi, ar)),
        ring_depth=0)
    assert [x[:2] for x in a] == [x[:2] for x in b] == [(0, 8), (8, 16), (16, 21)]
    assert int(tok_a[0]) == int(tok_b[0])
    for (_, _, ar_a), (_, _, ar_b) in zip(a, b):
        np.testing.assert_array_equal(ar_a["k"], ar_b["k"])


def test_assembler_rejects_rows_past_decode_budget(model, prompt):
    """The decode-budget contract bundle_to_cache enforces holds for
    streams too: a chunk (or final pos) past max_len is refused."""
    asm = kt.CacheAssembler(max_len=4, device=True)
    with pytest.raises(ValueError, match="max_len"):
        asm.chunk({"pos_range": [0, 8]},
                  {"k": np.zeros((2, 1, 8, 2, 3), np.float32)})


def test_streamed_poison_bundle_fails_request_not_worker(model, prompt):
    """Prefill budget larger than decode budget over a STREAMED handoff
    (the poison shape the monolithic guard already covers): the assembler's
    rejection must flow into the worker's process() as a PoisonPayload —
    consumed with a failed result and acked — never crash the pull loop
    (an un-consumed poison stream would re-queue and crash every
    successor: a head-of-line crash loop)."""
    cfg, params = model
    pre = Engine(cfg, params, batch_size=1, max_len=MAX_LEN)
    small_budget = 8  # decode max_len < the 21-row prompt
    server = kt.KVServer(port=0, host="127.0.0.1")
    try:
        results = {}

        def process(meta, payload):
            # The decode worker's poison-guard shape (run_decode_tcp).
            try:
                _decode_bundle(None, payload, steps=STEPS)
            except Exception as e:  # noqa: BLE001 — the worker's guard
                results[meta["id"]] = f"failed: {e!r}"
                return
            results[meta["id"]] = "decoded"

        out = {}

        def puller():
            out["r"] = kt.pull_bundle(
                ("127.0.0.1", server.port), timeout=15.0, ack_timeout=60.0,
                receiver_factory=lambda m: kt.CacheAssembler(
                    max_len=small_budget, device=True),
                process=process,
            )

        t = threading.Thread(target=puller, daemon=True)
        t.start()
        _prefill_streamed(pre, server, kt, {"id": "poison"}, "poison",
                          prompt, 8, None)
        t.join(timeout=60)
        assert results.get("poison", "").startswith("failed:"), results
        assert "max_len" in results["poison"]
        # Consumed, not re-queued: no successor can crash on it.
        import time as _time
        deadline = _time.time() + 5
        while server.delivery_counts()[0] < 1 and _time.time() < deadline:
            _time.sleep(0.02)
        assert server.delivery_counts()[0] == 1
        assert kt.pull_bundle(("127.0.0.1", server.port), timeout=0.3) is None
    finally:
        server.close()
