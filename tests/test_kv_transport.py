"""KV transport protocol semantics (no model, no jax): end-to-end
at-least-once bundle delivery, token auth, pull_result eviction/race rules.
Ref anchor: the -prv service endpoint publication this transport rides,
/root/reference/pkg/controllers/disaggregatedset/service_manager.go:126-163."""

import threading
import time

import pytest

from lws_tpu.serving import kv_transport as kt


def wait_for(predicate, timeout=5.0):
    """The ack is one-way: the client returns before the server has counted
    it, so counter asserts poll briefly."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


@pytest.fixture
def server():
    s = kt.KVServer(port=0, host="127.0.0.1")
    yield s
    s.close()


def ep(server):
    return ("127.0.0.1", server.port)


def test_process_failure_requeues_bundle(server):
    """Ack-after-process: a puller that dies mid-processing must NOT lose the
    bundle — the server re-queues it and the next pull redelivers."""
    server.offer_bundle({"id": "r1"}, b"payload")

    with pytest.raises(RuntimeError, match="mid-process"):
        kt.pull_bundle(ep(server), timeout=1.0,
                       process=lambda m, p: (_ for _ in ()).throw(RuntimeError("mid-process")),
                       ack_timeout=2.0)
    assert server.bundles_delivered == 0

    got = kt.pull_bundle(ep(server), timeout=2.0)  # redelivery
    assert got is not None and got[0]["id"] == "r1" and got[1] == b"payload"
    assert wait_for(lambda: server.bundles_delivered == 1)


def test_process_success_acks_and_consumes(server):
    server.offer_bundle({"id": "r2"}, b"xyz")
    seen = {}

    def process(meta, payload):
        seen["meta"], seen["payload"] = meta, payload
        return "done"

    assert kt.pull_bundle(ep(server), timeout=1.0, process=process) == "done"
    assert seen["payload"] == b"xyz"
    assert wait_for(lambda: server.bundles_delivered == 1)
    assert kt.pull_bundle(ep(server), timeout=0.2) is None  # consumed


def test_token_auth_rejects_unauthenticated_ops(monkeypatch):
    s = kt.KVServer(port=0, host="127.0.0.1", token="sekret")
    try:
        monkeypatch.delenv("LWS_TPU_KV_TOKEN", raising=False)
        with pytest.raises(RuntimeError, match="submit_prompt failed"):
            kt.submit_prompt(ep(s), "r", b"p")
        with pytest.raises(RuntimeError, match="rejected"):
            kt.pull_bundle(ep(s), timeout=0.2)
        s.post_result("r", {"id": "r"}, b"out")
        with pytest.raises(RuntimeError, match="rejected"):
            kt.pull_result(ep(s), "r")
        # With the token in the client env, everything flows.
        monkeypatch.setenv("LWS_TPU_KV_TOKEN", "sekret")
        kt.submit_prompt(ep(s), "r2", b"p2")
        assert s.next_prompt(timeout=1.0)[0]["id"] == "r2"
        assert kt.pull_result(ep(s), "r")[1] == b"out"
    finally:
        s.close()


def test_pull_result_evicts_once(server):
    server.post_result("a", {"id": "a"}, b"res")
    assert kt.pull_result(ep(server), "a")[1] == b"res"
    assert kt.pull_result(ep(server), "a") is None  # evicted on delivery
    assert server.results_served == 1


def test_pull_result_concurrent_single_delivery(server):
    """The pop-under-lock rule: N concurrent pulls for one id deliver it
    exactly once (results_served drives --once exit)."""
    server.post_result("c", {"id": "c"}, b"res")
    hits = []

    def pull():
        got = kt.pull_result(ep(server), "c")
        if got is not None:
            hits.append(got)

    threads = [threading.Thread(target=pull) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 1 and server.results_served == 1


def test_bind_failure_closes_socket(server):
    """Error-path resource hygiene (vet: resource-ctor-leak): a KVServer
    that fails to bind — port already owned by the fixture's server — must
    close the socket it created instead of leaking it until GC."""
    created = []
    real_socket = kt.socket.socket

    def recording_socket(*args, **kwargs):
        s = real_socket(*args, **kwargs)
        created.append(s)
        return s

    kt.socket.socket = recording_socket
    try:
        with pytest.raises(OSError):
            kt.KVServer(port=server.port, host="127.0.0.1")
    finally:
        kt.socket.socket = real_socket
    assert len(created) == 1
    assert created[0].fileno() == -1, "failed bind leaked its socket"
