"""KV transport protocol semantics (no model, no jax): end-to-end
at-least-once bundle delivery, token auth, pull_result eviction/race rules.
Ref anchor: the -prv service endpoint publication this transport rides,
/root/reference/pkg/controllers/disaggregatedset/service_manager.go:126-163."""

import threading
import time

import pytest

from lws_tpu.serving import kv_transport as kt


def wait_for(predicate, timeout=5.0):
    """The ack is one-way: the client returns before the server has counted
    it, so counter asserts poll briefly."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


@pytest.fixture
def server():
    s = kt.KVServer(port=0, host="127.0.0.1")
    yield s
    s.close()


def ep(server):
    return ("127.0.0.1", server.port)


def test_process_failure_requeues_bundle(server):
    """Ack-after-process: a puller that dies mid-processing must NOT lose the
    bundle — the server re-queues it and the next pull redelivers."""
    server.offer_bundle({"id": "r1"}, b"payload")

    with pytest.raises(RuntimeError, match="mid-process"):
        kt.pull_bundle(ep(server), timeout=1.0,
                       process=lambda m, p: (_ for _ in ()).throw(RuntimeError("mid-process")),
                       ack_timeout=2.0)
    assert server.bundles_delivered == 0

    got = kt.pull_bundle(ep(server), timeout=2.0)  # redelivery
    assert got is not None and got[0]["id"] == "r1" and got[1] == b"payload"
    assert wait_for(lambda: server.bundles_delivered == 1)


def test_process_success_acks_and_consumes(server):
    server.offer_bundle({"id": "r2"}, b"xyz")
    seen = {}

    def process(meta, payload):
        seen["meta"], seen["payload"] = meta, payload
        return "done"

    assert kt.pull_bundle(ep(server), timeout=1.0, process=process) == "done"
    assert seen["payload"] == b"xyz"
    assert wait_for(lambda: server.bundles_delivered == 1)
    assert kt.pull_bundle(ep(server), timeout=0.2) is None  # consumed


def test_token_auth_rejects_unauthenticated_ops(monkeypatch):
    s = kt.KVServer(port=0, host="127.0.0.1", token="sekret")
    try:
        monkeypatch.delenv("LWS_TPU_KV_TOKEN", raising=False)
        with pytest.raises(RuntimeError, match="submit_prompt failed"):
            kt.submit_prompt(ep(s), "r", b"p")
        with pytest.raises(RuntimeError, match="rejected"):
            kt.pull_bundle(ep(s), timeout=0.2)
        s.post_result("r", {"id": "r"}, b"out")
        with pytest.raises(RuntimeError, match="rejected"):
            kt.pull_result(ep(s), "r")
        # With the token in the client env, everything flows.
        monkeypatch.setenv("LWS_TPU_KV_TOKEN", "sekret")
        kt.submit_prompt(ep(s), "r2", b"p2")
        assert s.next_prompt(timeout=1.0)[0]["id"] == "r2"
        assert kt.pull_result(ep(s), "r")[1] == b"out"
    finally:
        s.close()


def test_pull_result_evicts_once(server):
    server.post_result("a", {"id": "a"}, b"res")
    assert kt.pull_result(ep(server), "a")[1] == b"res"
    assert kt.pull_result(ep(server), "a") is None  # evicted on delivery
    assert server.results_served == 1


def test_pull_result_concurrent_single_delivery(server):
    """The pop-under-lock rule: N concurrent pulls for one id deliver it
    exactly once (results_served drives --once exit)."""
    server.post_result("c", {"id": "c"}, b"res")
    hits = []

    def pull():
        got = kt.pull_result(ep(server), "c")
        if got is not None:
            hits.append(got)

    threads = [threading.Thread(target=pull) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 1 and server.results_served == 1


def test_pack_roundtrip_preserves_shapes_and_is_zero_copy():
    """The raw wire format (ISSUE 10): dtype/shape round-trip including
    0-d scalars, and the decoded arrays are frombuffer VIEWS into the
    payload (no receive-side copy), ml_dtypes extension types included."""
    import numpy as np

    arrays = {
        "k": np.arange(48, dtype=np.float32).reshape(2, 1, 4, 2, 3),
        "pos": np.asarray(7, np.int32),
        "token": np.asarray([3], np.int32),
        "empty": np.zeros((2, 0, 4), np.float32),
    }
    data = kt.arrays_to_bytes(**arrays)
    out = kt.bytes_to_arrays(data)
    assert set(out) == set(arrays)
    for name, want in arrays.items():
        assert out[name].dtype == want.dtype and out[name].shape == want.shape
        np.testing.assert_array_equal(out[name], want)
    assert out["pos"].ndim == 0 and int(out["pos"]) == 7
    assert out["k"].base is not None, "decode copied instead of viewing"

    import ml_dtypes

    bf = np.arange(6, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 3)
    back = kt.bytes_to_arrays(kt.arrays_to_bytes(x=bf))["x"]
    assert back.dtype == bf.dtype
    np.testing.assert_array_equal(back, bf)


def test_vectored_send_survives_large_multi_buffer_payloads(server):
    """Scatter-gather framing: a payload made of MANY separate buffers,
    larger than any socket buffer, arrives byte-exact (the partial-sendmsg
    continuation loop)."""
    import numpy as np

    parts = [np.random.RandomState(i).bytes(257 * 1024) for i in range(9)]
    # Drive send_msg directly over a connected socket via the submit op:
    # the prompt payload rides the same vectored path.
    payload = [memoryview(p) for p in parts]
    with kt.socket.create_connection(ep(server)) as sock:
        kt.tune_socket(sock)
        kt.send_msg(sock, {"op": "submit_prompt", "id": "vec"}, payload)
        reply, _ = kt.recv_msg(sock)
    assert reply == {"ok": True}
    meta, got = server.next_prompt(timeout=2.0)
    assert meta["id"] == "vec" and got == b"".join(parts)


def test_kv_sockets_run_nodelay(server):
    """Satellite: every KV-transport socket disables Nagle (small ack
    frames must not queue behind MB-scale payload segments)."""
    with kt.socket.create_connection(ep(server)) as sock:
        kt.tune_socket(sock)
        assert sock.getsockopt(kt.socket.IPPROTO_TCP, kt.socket.TCP_NODELAY) != 0
    assert server._sock.getsockopt(
        kt.socket.IPPROTO_TCP, kt.socket.TCP_NODELAY) != 0


def stream_of(chunks, end_arrays, chunk_tokens=4):
    import numpy as np

    stream = kt.KVStream(chunk_tokens)
    lo = 0
    for width in chunks:
        arrays = {
            "k": np.full((2, 1, width, 2, 3), float(lo), np.float32),
            "v": np.full((2, 1, width, 2, 3), float(lo + 1), np.float32),
            "tokens": np.arange(lo, lo + width, dtype=np.int32)[None, :],
        }
        stream.put_chunk(lo, lo + width, arrays)
        lo += width
    stream.finish({"handoff": {"streamed": True}}, end_arrays)
    return stream, lo


def test_streamed_pull_default_receiver_reassembles(server):
    """BEGIN/CHUNK/END over a real socket: the default HostAssembler hands
    back the monolithic array dict, per-chunk acked, checksum verified,
    and the final ack counts ONE delivery."""
    import numpy as np

    end = {"token": np.asarray([9], np.int32), "pos": np.asarray(12, np.int32)}
    stream, total = stream_of([4, 4, 4], end)
    server.offer_stream({"id": "s1"}, stream)
    meta, arrays = kt.pull_bundle(ep(server), timeout=2.0, ack_timeout=10.0)
    assert meta["id"] == "s1" and meta["streamed"] and meta["chunks"] == 3
    assert meta["payload_bytes"] == stream.payload_bytes
    assert arrays["k"].shape[2] == total
    np.testing.assert_array_equal(
        arrays["tokens"][0], np.arange(total, dtype=np.int32))
    assert int(arrays["pos"]) == 12 and arrays["token"][0] == 9
    # Chunk boundaries landed in the right rows.
    assert arrays["k"][0, 0, 0, 0, 0] == 0.0 and arrays["k"][0, 0, 4, 0, 0] == 4.0
    import time as _time
    deadline = _time.time() + 5
    while server.delivery_counts()[0] < 1 and _time.time() < deadline:
        _time.sleep(0.02)
    assert server.delivery_counts()[0] == 1
    assert kt.pull_bundle(ep(server), timeout=0.2) is None  # consumed


def test_stream_receiver_rejection_is_poison_not_requeue(server):
    """A RECEIVER exception mid-stream is a CONTENT verdict, not a wire
    failure: re-queueing could never heal it (every successor would
    re-pull and re-die — a head-of-line crash loop), so the stream drains
    per protocol and the error surfaces as a poison delivery — exactly
    the consume-with-failed-result path a poison monolithic bundle takes
    through the decode worker's guard."""
    import numpy as np

    end = {"token": np.asarray([1], np.int32), "pos": np.asarray(8, np.int32)}
    stream, _ = stream_of([4, 4], end)
    server.offer_stream({"id": "s2"}, stream)

    class RejectsContent(kt.HostAssembler):
        def chunk(self, cmeta, arrays):
            raise ValueError("rows past this side's budget")

    # Worker shape: process() sees the PoisonPayload, consumes it (posts a
    # failed result in the real worker), and the delivery ACKS.
    seen = {}

    def process(meta, payload):
        assert isinstance(payload, kt.PoisonPayload)
        with pytest.raises(ValueError, match="budget"):
            raise payload.error
        seen["meta"] = meta

    kt.pull_bundle(ep(server), timeout=2.0, ack_timeout=10.0,
                   receiver_factory=lambda m: RejectsContent(m),
                   process=process)
    assert "receiver_error" in seen["meta"]

    def consumed():
        return server.delivery_counts()[0] == 1
    assert wait_for(consumed)
    assert kt.pull_bundle(ep(server), timeout=0.2) is None  # consumed, no loop

    # No-process shape: the error re-raises to the caller after the
    # wire-level ack (same consumed-on-ack contract as any bare pull).
    stream2, _ = stream_of([4], end)
    server.offer_stream({"id": "s2b"}, stream2)
    with pytest.raises(ValueError, match="budget"):
        kt.pull_bundle(ep(server), timeout=2.0, ack_timeout=10.0,
                       receiver_factory=lambda m: RejectsContent(m))
    assert wait_for(lambda: server.delivery_counts()[0] == 2)


def test_stream_checksum_mismatch_refused():
    """A server whose END frame advertises the wrong checksum (bit rot,
    torn buffers) is REFUSED: OSError, no ack — never a silent torn cache."""
    import threading

    import numpy as np

    lis = kt.socket.socket()
    lis.bind(("127.0.0.1", 0))
    lis.listen(1)
    port = lis.getsockname()[1]

    def evil_server():
        conn, _ = lis.accept()
        with conn:
            kt.recv_msg(conn)  # the pull op frame
            kt.send_msg(conn, {"id": "x", "stream": True})
            bufs, _ = kt.pack_payload(
                {"k": np.zeros((1, 1, 2, 1, 1), np.float32)})
            kt.send_msg(conn, {"chunk": 0, "pos_range": [0, 2]}, bufs)
            kt.recv_msg(conn)  # chunk ack
            kt.send_msg(conn, {"end": True, "chunks": 1, "checksum": 12345})

    t = threading.Thread(target=evil_server, daemon=True)
    t.start()
    try:
        with pytest.raises(OSError, match="torn kv stream"):
            kt.pull_bundle(("127.0.0.1", port), timeout=2.0, ack_timeout=5.0)
    finally:
        lis.close()
        t.join(timeout=5)


def test_stream_producer_failure_drops_not_requeues(server):
    """stream.fail() (prefill raised mid-produce): the puller gets a
    terminal error and the stream is DROPPED, never re-queued — a dead
    stream must not head-of-line block the bundle queue forever."""
    stream = kt.KVStream(4)
    server.offer_stream({"id": "s3"}, stream)
    stream.fail()
    with pytest.raises(OSError, match="failed at the sender"):
        kt.pull_bundle(ep(server), timeout=2.0, ack_timeout=10.0)
    assert kt.pull_bundle(ep(server), timeout=0.3) is None  # dropped, not queued


def test_bind_failure_closes_socket(server):
    """Error-path resource hygiene (vet: resource-ctor-leak): a KVServer
    that fails to bind — port already owned by the fixture's server — must
    close the socket it created instead of leaking it until GC."""
    created = []
    real_socket = kt.socket.socket

    def recording_socket(*args, **kwargs):
        s = real_socket(*args, **kwargs)
        created.append(s)
        return s

    kt.socket.socket = recording_socket
    try:
        with pytest.raises(OSError):
            kt.KVServer(port=server.port, host="127.0.0.1")
    finally:
        kt.socket.socket = real_socket
    assert len(created) == 1
    assert created[0].fileno() == -1, "failed bind leaked its socket"
