"""Scenario load-generation harness + per-class goodput plane (ISSUE 11):
arrival-process determinism (same seed -> byte-identical schedules, across
processes too), workload-mix composition, the goodput ledger and age-bound
attainment windows in core/slo.py, the open-loop runner against a real
paged engine, the pure report renderer, and the `lws-tpu top`
GOODPUT%/--by-class columns."""

import json
import random
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu import loadgen
from lws_tpu.core import metrics
from lws_tpu.core.metrics import MetricsRegistry
from lws_tpu.core.slo import (
    SLORecorder,
    SLOTargets,
    class_targets_from_env,
    token_deadline_s,
)
from lws_tpu.loadgen.arrivals import (
    BurstProcess,
    FlashCrowdProcess,
    GammaProcess,
    PoissonProcess,
    TraceReplayProcess,
)


# ---------------------------------------------------------------------------
# Arrival processes: determinism + shape


def _times(process, horizon, seed):
    return process.times(horizon, random.Random(seed))


@pytest.mark.parametrize("process", [
    PoissonProcess(20.0),
    GammaProcess(20.0, shape=3),
    BurstProcess(4.0, 40.0, period_s=0.5, duty=0.3),
    FlashCrowdProcess(4.0, 40.0, spike_at_s=0.5, spike_len_s=0.3),
    TraceReplayProcess([{"t_s": 0.0, "rate_rps": 5.0},
                        {"t_s": 0.5, "rate_rps": 30.0},
                        {"t_s": 1.0, "rate_rps": 5.0}]),
], ids=["poisson", "gamma", "burst", "flash", "trace"])
def test_arrivals_deterministic_and_seed_sensitive(process):
    a = _times(process, 2.0, seed=7)
    b = _times(process, 2.0, seed=7)
    c = _times(process, 2.0, seed=8)
    assert a == b  # byte-identical replay, not approximately equal
    assert a != c
    assert a == sorted(a)
    assert all(0.0 <= t < 2.0 for t in a)


def test_flash_crowd_spikes_where_told():
    """The step really is a step: arrival density inside the spike window
    dwarfs the base windows (40 rps vs 4 rps over a 2s horizon)."""
    times = _times(FlashCrowdProcess(4.0, 40.0, 0.5, 0.5), 2.0, seed=3)
    in_spike = sum(0.5 <= t < 1.0 for t in times)
    outside = len(times) - in_spike
    assert in_spike > outside  # 20 expected in-spike vs ~6 outside


def test_trace_replay_holds_segment_rates():
    trace = [{"t_s": 0.0, "rate_rps": 2.0}, {"t_s": 1.0, "rate_rps": 50.0}]
    times = _times(TraceReplayProcess(trace), 2.0, seed=11)
    assert sum(t >= 1.0 for t in times) > 5 * max(1, sum(t < 1.0 for t in times))


def test_unknown_process_rejected():
    with pytest.raises(ValueError, match="unknown arrival process"):
        loadgen.make_process({"process": "lunar"})


# ---------------------------------------------------------------------------
# Schedules: byte-reproducible, including across processes


def test_schedule_reproducible_and_divergent():
    spec = loadgen.load_scenario("steady_poisson")
    a = loadgen.build_schedule(spec, seed=42)
    b = loadgen.build_schedule(spec, seed=42)
    c = loadgen.build_schedule(spec, seed=43)
    assert loadgen.schedule_digest(a) == loadgen.schedule_digest(b)
    assert loadgen.schedule_digest(a) != loadgen.schedule_digest(c)
    # The digest covers the real content: every field byte-identical.
    for ra, rb in zip(a, b):
        assert ra.arrival_s == rb.arrival_s
        assert ra.klass == rb.klass
        assert ra.max_new_tokens == rb.max_new_tokens
        np.testing.assert_array_equal(ra.prompt, rb.prompt)


@pytest.mark.parametrize("name", ["burst", "flash_crowd", "diurnal",
                                  "shared_prefix"])
def test_every_builtin_scenario_compiles_reproducibly(name):
    spec = loadgen.load_scenario(name)
    a = loadgen.build_schedule(spec, seed=5)
    assert loadgen.schedule_digest(a) == \
        loadgen.schedule_digest(loadgen.build_schedule(spec, seed=5))
    assert len(a) > 0
    max_len = int(spec["max_len"])
    for r in a:
        assert len(r.prompt) + r.max_new_tokens <= max_len


def test_schedule_digest_stable_across_processes():
    """The committed-budget property: a FRESH interpreter compiles the same
    (spec, seed) to the same digest — no dict-order, hash-seed, or
    module-state dependence."""
    spec = loadgen.load_scenario("steady_poisson")
    local = loadgen.schedule_digest(loadgen.build_schedule(spec, seed=1234))
    out = subprocess.run(
        [sys.executable, "-c",
         "from lws_tpu.loadgen import scenario as s;"
         "print(s.schedule_digest(s.build_schedule("
         "s.load_scenario('steady_poisson'), 1234)))"],
        capture_output=True, text=True, check=True, cwd="/root/repo",
    )
    assert out.stdout.strip() == local


def test_shared_prefix_requests_share_real_prefixes():
    spec = loadgen.load_scenario("shared_prefix")
    schedule = loadgen.build_schedule(spec, seed=9)
    shared = [r for r in schedule if r.shared_prefix]
    assert shared, "0.75 ratio produced no shared-prefix requests"
    prefix_len = int(spec["prefix_len"])
    heads = {tuple(r.prompt[:prefix_len].tolist()) for r in shared}
    # Drawn from a pool of 2 — at most 2 distinct heads, shared across many.
    assert len(heads) <= int(spec["prefix_pool"])
    fresh = [r for r in schedule if not r.shared_prefix]
    for r in fresh:
        assert tuple(r.prompt[:prefix_len].tolist()) not in heads or \
            len(r.prompt) < prefix_len


def test_class_mix_and_targets_parse():
    spec = loadgen.load_scenario("steady_poisson")
    schedule = loadgen.build_schedule(spec, seed=2)
    assert {r.klass for r in schedule} <= {"chat", "batch"}
    targets = loadgen.class_targets(spec)
    assert targets["batch"].ttft_s == 10.0
    assert targets["chat"].ttft_s == 5.0
    with pytest.raises(ValueError, match="unknown SLO target"):
        SLOTargets().overridden({"ttft": 1.0})  # typo must not pass silently


def test_class_targets_from_env(monkeypatch):
    monkeypatch.setenv("LWS_TPU_SLO_CLASS_TARGETS",
                       '{"premium": {"ttft_s": 0.25}}')
    targets = class_targets_from_env(SLOTargets())
    assert targets["premium"].ttft_s == 0.25
    assert targets["premium"].itl_s == SLOTargets().itl_s  # base preserved
    monkeypatch.setenv("LWS_TPU_SLO_CLASS_TARGETS", "[1,2]")
    with pytest.raises(ValueError, match="LWS_TPU_SLO_CLASS_TARGETS"):
        class_targets_from_env(SLOTargets())


# ---------------------------------------------------------------------------
# Goodput ledger + class-granular SLO accounting (core/slo.py)


def test_token_deadline_rule():
    t = SLOTargets(ttft_s=1.0, itl_s=0.1, queue_wait_s=1.0)
    assert token_deadline_s(t, 1) == 1.0
    assert token_deadline_s(t, 5) == pytest.approx(1.4)


def test_timeline_goodput_counts_on_time_tokens_only():
    reg = MetricsRegistry()
    rec = SLORecorder(SLOTargets(ttft_s=1.0, itl_s=0.1, queue_wait_s=1.0),
                      registry=reg, window=8)
    tl = rec.request("paged", klass="gold")
    tl.first_token(0.5)   # on time (<= 1.0)
    tl.tokens(4, 0.2)     # cursor 0.7 <= deadline(5)=1.4 -> good
    tl.tokens(4, 5.0)     # cursor 5.7 >  deadline(9)=1.8 -> late
    tl.finish()
    labels = {"engine": "paged", "klass": "gold"}
    assert reg.counter_value("serving_tokens_total", labels) == 9.0
    assert reg.counter_value("serving_goodput_tokens_total", labels) == 5.0
    # Fast-but-late also failed the worst-ITL check -> attainment 0.
    assert reg.gauge_value("serving_slo_attainment", labels) == 0.0
    assert rec.attainment("paged", klass="gold") == 0.0
    # Class-free series untouched: the klass label split, not polluted.
    assert rec.attainment("paged") is None


def test_late_first_token_is_not_goodput():
    reg = MetricsRegistry()
    rec = SLORecorder(SLOTargets(ttft_s=0.1, itl_s=1.0, queue_wait_s=1.0),
                      registry=reg, window=8)
    tl = rec.request("dense")
    tl.first_token(0.5)  # late
    tl.finish()
    assert reg.counter_value("serving_tokens_total", {"engine": "dense"}) == 1.0
    assert reg.counter_value(
        "serving_goodput_tokens_total", {"engine": "dense"}) == 0.0


def test_per_class_targets_grade_each_class_separately():
    reg = MetricsRegistry()
    rec = SLORecorder(
        SLOTargets(ttft_s=0.1, itl_s=0.1, queue_wait_s=0.1), registry=reg,
        window=8,
        class_targets={"relaxed": SLOTargets(ttft_s=10.0, itl_s=10.0,
                                             queue_wait_s=10.0)},
    )
    for klass in ("relaxed", "strict"):
        tl = rec.request("paged", klass=klass)
        tl.first_token(0.5)
        tl.finish()
    assert rec.attainment("paged", klass="relaxed") == 1.0
    assert rec.attainment("paged", klass="strict") == 0.0  # default targets
    assert reg.gauge_value("serving_slo_attainment",
                           {"engine": "paged", "klass": "relaxed"}) == 1.0


def test_attainment_window_ages_out_and_series_retire():
    """The staleness satellite: a quiet engine stops advertising attainment
    — reads evict aged entries, and refresh() retires the gauge series so
    `lws-tpu top` (and the future autoscaler) can't act on fiction."""
    reg = MetricsRegistry()
    rec = SLORecorder(registry=reg, window=8, max_age_s=0.05)
    tl = rec.request("paged")
    tl.first_token(0.01)
    tl.finish()
    assert rec.attainment("paged") == 1.0
    assert reg.gauge_value("serving_slo_attainment", {"engine": "paged"}) == 1.0
    assert reg.gauge_value(
        "serving_slo_window_age_seconds", {"engine": "paged"}) == 0.0
    time.sleep(0.12)  # 2x the age bound
    assert rec.attainment("paged") is None
    rec.refresh()
    assert reg.gauge_value("serving_slo_attainment", {"engine": "paged"}) is None
    assert reg.gauge_value(
        "serving_slo_window_age_seconds", {"engine": "paged"}) is None
    assert "serving_slo_attainment" not in reg.render()


def test_refresh_retiring_classfree_window_spares_class_series():
    """Regression: clear_gauge matches by label SUBSET, so retiring the
    emptied class-free {engine} window must use an exact match — or it
    would wipe every live {engine, klass} sibling it just re-published."""
    reg = MetricsRegistry()
    rec = SLORecorder(registry=reg, window=8, max_age_s=0.05,
                      class_targets={"premium": SLOTargets(10.0, 10.0, 10.0)})
    tl = rec.request("paged")  # class-free traffic that will go quiet
    tl.first_token(0.01)
    tl.finish()
    time.sleep(0.12)  # past the age bound
    tl2 = rec.request("paged", klass="premium")  # live classed traffic
    tl2.first_token(0.01)
    tl2.finish()
    rec.refresh()
    assert reg.gauge_value("serving_slo_attainment", {"engine": "paged"}) is None
    assert reg.gauge_value(
        "serving_slo_attainment", {"engine": "paged", "klass": "premium"}
    ) == 1.0


def test_refresh_reports_window_age_for_live_series():
    reg = MetricsRegistry()
    rec = SLORecorder(registry=reg, window=8, max_age_s=60.0)
    tl = rec.request("paged")
    tl.first_token(0.01)
    tl.finish()
    time.sleep(0.05)
    rec.refresh()
    age = reg.gauge_value("serving_slo_window_age_seconds", {"engine": "paged"})
    assert age is not None and age >= 0.05
    assert reg.gauge_value("serving_slo_attainment", {"engine": "paged"}) == 1.0


# ---------------------------------------------------------------------------
# Client-side goodput grading (runner)


def test_client_goodput_split():
    t = SLOTargets(ttft_s=1.0, itl_s=0.1, queue_wait_s=1.0)
    # All on time: 5 tokens, uniform delivery well inside the deadlines.
    assert loadgen.goodput_tokens(t, 0.5, 5, 0.8) == 5
    # First token late: everything after inherits lateness too.
    assert loadgen.goodput_tokens(t, 2.0, 3, 2.1) == 0
    # Partial: on-time head, late tail.
    good = loadgen.goodput_tokens(t, 0.5, 10, 9.0)
    assert 0 < good < 10


# ---------------------------------------------------------------------------
# Open-loop runner against a real paged engine


@pytest.fixture(scope="module")
def small_engine():
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return PagedBatchEngine(cfg, params, slots=4, max_len=64, block_size=8,
                            prefix_cache=True)


def _prefix_hits() -> float:
    """Aggregate paged-engine prefix hits across the cache tiers (the hits
    counter carries a tier label since the spill hierarchy landed)."""
    return sum(
        metrics.REGISTRY.counter_value(
            "serving_prefix_cache_hits_total", {"engine": "paged", "tier": t})
        for t in ("hbm", "host", "remote"))


def test_open_loop_run_completes_and_ledgers_agree(small_engine):
    spec = loadgen.load_scenario("shared_prefix")
    schedule = loadgen.build_schedule(spec, seed=21)
    targets = loadgen.class_targets(spec)
    before_tokens = metrics.REGISTRY.counter_value(
        "serving_tokens_total", {"engine": "paged", "klass": "assist"})
    before_hits = _prefix_hits()
    result = loadgen.run_schedule(
        schedule, loadgen.EngineTarget(small_engine, "paged"), max_wall_s=90.0
    )
    report = loadgen.summarize(result, targets, spec["horizon_s"],
                               "shared_prefix", 21)
    assert report["all"]["count"] == len(schedule)
    assert report["all"]["completed"] == len(schedule)
    assert report["all"]["tokens"] == sum(r.max_new_tokens for r in schedule)
    assert report["classes"]["assist"]["ttft_p95"] is not None
    # Server-side ledger moved, class-labelled, by the same token count.
    after_tokens = metrics.REGISTRY.counter_value(
        "serving_tokens_total", {"engine": "paged", "klass": "assist"})
    assert after_tokens - before_tokens == report["all"]["tokens"]
    # The pooled prefixes really exercised the prefix cache.
    assert _prefix_hits() > before_hits
    # Open-loop accounting: offered load derives from the schedule, not
    # from how fast the engine happened to drain it.
    assert report["offered_rps"] == pytest.approx(
        len(schedule) / spec["horizon_s"])


def test_overloaded_run_reports_incompletes():
    """A target that refuses everything must show up as incomplete requests
    and zero attainment — never hang the driver."""

    class DeafTarget:
        def submit(self, req, arrival_wall_t):
            return None

        def step(self):
            time.sleep(0.001)

        def poll(self, handle):
            return None

    spec = loadgen.load_scenario("burst")
    schedule = loadgen.build_schedule(spec, seed=3)[:5]
    result = loadgen.run_schedule(schedule, DeafTarget(), max_wall_s=0.5)
    report = loadgen.summarize(result, {}, spec["horizon_s"], "burst", 3)
    assert report["all"]["completed"] == 0
    assert report["all"]["attainment"] == 0.0
    assert report["all"]["tokens"] == 0


def test_dense_target_splits_queue_from_ttft():
    """Regression: the dense target's submit() BLOCKS through generate(),
    so the loop's own stamps would fold the whole generation into queue
    wait and then double-count it into TTFT (reported first token AFTER
    completion). The wall-second overrides keep the splits honest."""

    class FakeDense:
        max_len = 64

        def generate(self, prompt, max_new_tokens, klass=""):
            time.sleep(0.08)  # decode long relative to its 0.01s TTFT

            class R:
                tokens = np.zeros((1, max_new_tokens), np.int32)
                ttft_s = 0.01

            return R()

    spec = loadgen.load_scenario("burst")
    schedule = loadgen.build_schedule(spec, seed=3)[:2]
    result = loadgen.run_schedule(
        schedule, loadgen.EngineTarget(FakeDense(), "dense"), max_wall_s=10.0
    )
    for out in result.outcomes:
        assert out.completed
        assert out.ttft_s <= out.total_s  # first token never after completion
        # TTFT ~= queue (time blocked behind the previous generate) + 0.01,
        # NOT + the 0.08s decode.
        assert out.ttft_s == pytest.approx(out.queue_s + 0.01, abs=0.03)


def test_wall_offsets_respect_time_scale():
    """Regression: target-reported offsets are WALL seconds and must be
    scaled into scenario time like every other stamp — at --time-scale 2
    a 0.1s prefill is 0.05 scenario seconds, not 0.1."""

    class InstantPair:
        def submit(self, req, arrival_wall_t):
            return req.index

        def step(self):
            time.sleep(0.001)

        def poll(self, handle):
            return {"n_tokens": 3, "ttft_after_admit_wall_s": 0.1}

    spec = loadgen.load_scenario("burst")
    schedule = loadgen.build_schedule(spec, seed=3)[:1]
    result = loadgen.run_schedule(schedule, InstantPair(), time_scale=2.0,
                                  max_wall_s=10.0)
    (out,) = result.outcomes
    assert out.ttft_s == pytest.approx(out.queue_s + 0.05, abs=0.01)


# ---------------------------------------------------------------------------
# Report rendering (pure)


def test_render_report_with_fleet_block():
    report = {
        "scenario": "steady_poisson", "seed": 1, "horizon_s": 1.5,
        "wall_s": 1.6, "offered_rps": 12.0, "achieved_rps": 11.5,
        "classes": {
            "chat": {"count": 10, "completed": 10, "attainment": 0.9,
                     "goodput_fraction": 0.8, "tokens": 60,
                     "good_tokens": 48, "ttft_p50": 0.01, "ttft_p95": 0.05,
                     "ttft_p99": 0.06, "itl_p50": 0.001, "itl_p95": 0.002,
                     "itl_p99": 0.003, "queue_p95": 0.004},
        },
        "all": {"count": 10, "completed": 10, "attainment": 0.9,
                "goodput_fraction": 0.8, "tokens": 60, "good_tokens": 48,
                "ttft_p50": 0.01, "ttft_p95": 0.05, "ttft_p99": 0.06,
                "itl_p50": 0.001, "itl_p95": 0.002, "itl_p99": 0.003},
    }
    fleet = metrics.parse_exposition(
        "# HELP serving_tokens_total x\n# TYPE serving_tokens_total counter\n"
        'serving_tokens_total{engine="paged",klass="chat"} 60.0\n'
        "# HELP serving_goodput_tokens_total x\n"
        "# TYPE serving_goodput_tokens_total counter\n"
        'serving_goodput_tokens_total{engine="paged",klass="chat"} 48.0\n'
        "# HELP serving_prefix_cache_hits_total x\n"
        "# TYPE serving_prefix_cache_hits_total counter\n"
        "serving_prefix_cache_hits_total 30.0\n"
        "# HELP serving_prefix_cache_misses_total x\n"
        "# TYPE serving_prefix_cache_misses_total counter\n"
        "serving_prefix_cache_misses_total 10.0\n"
    )
    frame = loadgen.render_report(report, fleet)
    assert "SCENARIO steady_poisson" in frame
    assert "chat" in frame and "90%" in frame and "80%" in frame
    assert "GOODPUT%=80%" in frame
    assert "PFX%=75%" in frame
    folds = loadgen.fold_fleet(fleet)
    assert folds["goodput"] == pytest.approx(0.8)
    assert folds["spec"] is None  # absent series stay None, not 0


# ---------------------------------------------------------------------------
# lws-tpu top: GOODPUT% column + --by-class rows


TOP_CLASS_EXPOSITION = """\
# HELP serving_slo_attainment x
# TYPE serving_slo_attainment gauge
serving_slo_attainment{engine="paged",instance="w0",klass="gold"} 1.0
serving_slo_attainment{engine="paged",instance="w0",klass="bulk"} 0.5
# HELP serving_tokens_total x
# TYPE serving_tokens_total counter
serving_tokens_total{engine="paged",instance="w0",klass="gold"} 100.0
serving_tokens_total{engine="paged",instance="w0",klass="bulk"} 100.0
# HELP serving_goodput_tokens_total x
# TYPE serving_goodput_tokens_total counter
serving_goodput_tokens_total{engine="paged",instance="w0",klass="gold"} 100.0
serving_goodput_tokens_total{engine="paged",instance="w0",klass="bulk"} 50.0
# HELP serving_requests_total x
# TYPE serving_requests_total counter
serving_requests_total{engine="paged",instance="w0"} 20.0
"""


def test_top_goodput_column_and_by_class_rows():
    from lws_tpu.cli import _top_rows, render_top

    fams = metrics.parse_exposition(TOP_CLASS_EXPOSITION)
    # Default fold: class series SUM into the engine row -> 150/200 = 75%.
    rows = _top_rows(fams)
    assert rows[("w0", "paged")]["tokens"] == 200.0
    assert rows[("w0", "paged")]["good_tokens"] == 150.0
    frame = render_top(fams)
    assert "GOOD%" in frame
    row = next(l for l in frame.splitlines() if l.startswith("w0"))
    assert "75%" in row
    # --by-class: one row per class, graded separately.
    by_rows = _top_rows(fams, by_class=True)
    assert by_rows[("w0", "paged", "gold")]["slo"] == 1.0
    assert by_rows[("w0", "paged", "bulk")]["slo"] == 0.5
    frame2 = render_top(fams, by_class=True)
    assert "CLASS" in frame2
    gold = next(l for l in frame2.splitlines() if "gold" in l)
    bulk = next(l for l in frame2.splitlines() if "bulk" in l)
    assert "100%" in gold and "1.00" in gold
    assert "50%" in bulk and "0.50" in bulk


# ---------------------------------------------------------------------------
# History plane integration (ISSUE 12): a run samples the live surface into
# a HistoryRing and the report appends peak burn + the recommendation trace.


def test_run_samples_history_and_report_appends_burn_trace(small_engine):
    """A committed scenario driven with the on_tick sampler: the ring fills
    DURING the run, fold_history grades each class's fast-window burn over
    the run, and the rendered report carries the HISTORY block plus the
    dry-run recommendation trace."""
    from lws_tpu.obs.history import HistoryRing

    spec = loadgen.load_scenario("steady_poisson")
    schedule = loadgen.build_schedule(spec, seed=11)
    targets = loadgen.class_targets(spec)
    target = loadgen.EngineTarget(small_engine, "paged")
    # Warm one request per class so every ledger series exists before the
    # first ring sample (a counter born at the run's LAST sample has one
    # point and no burn — this keeps the fold deterministic on any
    # machine speed), and take a final post-drain sample for the same
    # reason: every series ends with at least two points.
    warm = [loadgen.ScheduledRequest(index=i, arrival_s=0.0, klass=k,
                                     prompt=np.array([5, 6, 7 + i], np.int32),
                                     max_new_tokens=2)
            for i, k in enumerate(("chat", "batch"))]
    warm_result = loadgen.run_schedule(warm, target, max_wall_s=30.0)
    assert all(o.completed for o in warm_result.outcomes)
    ring = HistoryRing(interval_s=0.05, retention_s=3600.0)
    result = loadgen.run_schedule(
        schedule, target, max_wall_s=90.0,
        on_tick=lambda _now: ring.ingest_if_due(metrics.REGISTRY.render),
    )
    ring.ingest(metrics.REGISTRY.render())
    assert ring.series(), "the drive loop never sampled the ring"
    report = loadgen.summarize(result, targets, spec["horizon_s"],
                               "steady_poisson", 11)
    report["history"] = loadgen.fold_history(ring, targets)
    classes = report["history"]["classes"]
    # Both committed classes flowed through the ring's goodput series.
    assert {"paged/chat", "paged/batch"} <= set(classes), classes
    for key in ("paged/chat", "paged/batch"):
        assert classes[key]["peak_fast_burn"] is not None
        assert classes[key]["peak_fast_burn"] >= 0.0
    trace = report["history"]["recommendation"]
    assert trace, "the recommendation trace must record its first verdict"
    assert set(trace[0]["desired"]) == {"prefill", "decode"}
    frame = loadgen.render_report(report)
    assert "HISTORY" in frame
    assert "paged/chat" in frame
    assert "recommendation @" in frame


@pytest.mark.slow  # builds its own engine: covered by `make test`/`make check`
def test_cmd_loadgen_server_appends_history_block(tmp_path, capsys):
    """`lws-tpu loadgen SCENARIO --server` samples that server's
    /metrics/fleet into a HistoryRing for the run's duration and the final
    report appends the history block — end to end through the CLI against
    a live (stub) fleet surface."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from lws_tpu import cli

    hits = {"n": 0}

    class StubFleet(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_GET(self):
            hits["n"] += 1
            body = (
                "# HELP serving_tokens_total t\n"
                "# TYPE serving_tokens_total counter\n"
                f'serving_tokens_total{{engine="disagg",klass="chat",instance="w0"}} {100.0 * hits["n"]}\n'
                "# HELP serving_goodput_tokens_total g\n"
                "# TYPE serving_goodput_tokens_total counter\n"
                f'serving_goodput_tokens_total{{engine="disagg",klass="chat",instance="w0"}} {90.0 * hits["n"]}\n'
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), StubFleet)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rc = cli.main([
            "loadgen", "steady_poisson", "--seed", "3", "--target", "paged",
            "--max-wall", "60",
            "--server", f"127.0.0.1:{httpd.server_port}",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert hits["n"] >= 2, "the run never sampled the fleet surface"
        assert "HISTORY" in out
        assert "disagg/chat" in out
        assert "recommendation @" in out
    finally:
        httpd.shutdown()


# ---------------------------------------------------------------------------
# CLI


def test_cmd_loadgen_list(capsys):
    from lws_tpu import cli

    assert cli.main(["loadgen", "--list"]) == 0
    out = capsys.readouterr().out
    for name in loadgen.scenario_names():
        assert name in out


@pytest.mark.slow  # builds its own engine: covered by `make test`/`make check`
def test_cmd_loadgen_runs_spec_file(tmp_path, capsys):
    from lws_tpu import cli

    spec = {
        "name": "tiny", "horizon_s": 0.3, "max_len": 32, "vocab": 64,
        "arrivals": {"process": "poisson", "rate_rps": 12.0},
        "classes": [{"name": "c", "prompt_len": 4, "output_len": 2,
                     "targets": {"ttft_s": 30.0, "itl_s": 30.0,
                                 "queue_wait_s": 30.0}}],
    }
    path = tmp_path / "tiny.json"
    path.write_text(json.dumps(spec))
    rc = cli.main(["loadgen", "--spec", str(path), "--seed", "3",
                   "--target", "paged", "--max-wall", "60"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "SCENARIO tiny" in out
    assert "schedule " in out  # digest printed for reproducibility
    assert "ALL" in out


def test_cmd_loadgen_requires_scenario(capsys):
    from lws_tpu import cli

    assert cli.main(["loadgen"]) == 2


def test_scenario_bench_budget_floors_logic():
    """The bench's floor checker (pure): a missing scenario or a value
    below its floor fails; absent floors are skipped."""
    sys.path.insert(0, "/root/repo/benchmarks")
    try:
        import scenario_bench
    finally:
        sys.path.pop(0)
    budget = {"scenarios": {"s": {"min_attainment": 0.9,
                                  "min_prefix_hit_rate": 0.3}}}
    ok = {"s": {"attainment": 0.95, "prefix_hit_rate": 0.5}}
    assert scenario_bench.check(ok, budget) == []
    bad = {"s": {"attainment": 0.5, "prefix_hit_rate": None}}
    failures = scenario_bench.check(bad, budget)
    assert len(failures) == 2
    assert scenario_bench.check({}, budget)  # did not run -> failure
