"""Compute-plane tests on the virtual 8-device CPU mesh: forward shapes,
training convergence, dense + MoE, and the full dp/pp/tp/sp/ep sharded step."""

import jax
import jax.numpy as jnp
import pytest

from lws_tpu.models import LlamaConfig, forward, init_params
from lws_tpu.models.train import init_train_state, make_optimizer, make_train_step
from lws_tpu.parallel import MeshSpec, build_mesh


def tiny_cfg(**kw):
    defaults = dict(
        vocab_size=128,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=64,
        remat=False,
    )
    defaults.update(kw)
    return LlamaConfig(**defaults)


def test_forward_shapes_single_device():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, aux = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert jnp.isfinite(logits).all()


def test_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, -1].set(99)
    l1, _ = forward(params, t1, cfg)
    l2, _ = forward(params, t2, cfg)
    assert jnp.allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not jnp.allclose(l1[0, -1], l2[0, -1], atol=1e-5)


@pytest.mark.parametrize("moe", [False, True])
def test_train_step_full_mesh(moe):
    """The flagship training step with all five strategies live: dp=2, pp=2,
    tp=2 (sp rides tp on activations; ep rides tp on experts when moe)."""
    cfg = tiny_cfg(n_experts=4 if moe else 0, top_k=2)
    mesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))
    opt = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    batch = {
        "tokens": jax.random.randint(jax.random.key(1), (4, 17), 0, cfg.vocab_size).astype(jnp.int32)
    }
    params, opt_state, loss0, m0 = step(state.params, state.opt_state, batch)
    losses = [float(loss0)]
    for _ in range(5):
        params, opt_state, loss, _ = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], f"no learning: {losses}"
    assert all(jnp.isfinite(jnp.asarray(losses)))


def test_params_actually_sharded():
    cfg = tiny_cfg()
    mesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))
    opt = make_optimizer()
    state = init_train_state(cfg, mesh, opt)
    wq = state.params["layers"]["wq"]  # [L, d, nh*hd] sharded (pp, -, tp)
    assert len(wq.sharding.device_set) == 8 or wq.sharding.is_fully_replicated is False
    spec = wq.sharding.spec
    assert spec[0] == "pp" and spec[2] == "tp"
    # Each device holds 1/(pp*tp) of the tensor.
    shard = wq.addressable_shards[0].data
    assert shard.shape == (cfg.n_layers // 2, cfg.d_model, cfg.n_heads * cfg.head_dim // 2)


def test_mesh_shapes_other_factorizations():
    cfg = tiny_cfg()
    mesh = build_mesh(MeshSpec(dp=1, pp=1, tp=8))
    opt = make_optimizer()
    state = init_train_state(cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    batch = {"tokens": jnp.zeros((2, 9), jnp.int32)}
    _, _, loss, _ = step(state.params, state.opt_state, batch)
    assert jnp.isfinite(loss)


def test_graft_entry_contract():
    import importlib.util, pathlib

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("graft_entry", root / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 3
    mod.dryrun_multichip(8)


def test_context_parallel_ring_matches_dense():
    """Ring attention over the cp axis must be numerically equivalent to the
    dense-attention forward, and the full train step must run on a
    dp x pp x cp x tp mesh (all six strategies live with MoE)."""
    from lws_tpu.parallel.mesh import MeshSpec as MS
    import dataclasses

    cfg = tiny_cfg(n_layers=2, dtype=jnp.float32)  # f32: exact-order comparison
    cfg_cp = dataclasses.replace(cfg, context_parallel=True)
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, cfg.vocab_size).astype(jnp.int32)

    dense_logits, _ = forward(params, tokens, cfg)

    mesh = build_mesh(MS(dp=1, pp=1, cp=8, tp=1))
    with jax.set_mesh(mesh):
        ring_logits, _ = jax.jit(lambda p, t: forward(p, t, cfg_cp))(params, tokens)
    assert jnp.allclose(dense_logits, ring_logits, atol=2e-4), (
        float(jnp.abs(dense_logits - ring_logits).max())
    )


def test_train_step_with_cp_axis():
    # remat=True: the production default must compose with ring attention.
    cfg = tiny_cfg(n_experts=4, top_k=2, context_parallel=True, remat=True)
    from lws_tpu.parallel.mesh import MeshSpec as MS

    mesh = build_mesh(MS(dp=1, pp=2, cp=2, tp=2))
    opt = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    batch = {"tokens": jnp.ones((2, 17), jnp.int32)}
    params, opt_state, l0, _ = step(state.params, state.opt_state, batch)
    params, opt_state, l1, _ = step(params, opt_state, batch)
    assert jnp.isfinite(l0) and jnp.isfinite(l1) and float(l1) < float(l0)


def test_gpipe_pipeline_matches_dense():
    """GPipe microbatch pipelining must be numerically identical to the
    weight-gathered scan path (f32)."""
    import dataclasses

    cfg = tiny_cfg(n_layers=4, dtype=jnp.float32)
    cfg_pipe = dataclasses.replace(cfg, pipeline_microbatches=2)
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(2), (4, 16), 0, cfg.vocab_size).astype(jnp.int32)
    dense_logits, _ = forward(params, tokens, cfg)
    mesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))
    with jax.set_mesh(mesh):
        piped_logits, _ = jax.jit(lambda p, t: forward(p, t, cfg_pipe))(params, tokens)
    assert jnp.allclose(dense_logits, piped_logits, atol=1e-4), (
        float(jnp.abs(dense_logits - piped_logits).max())
    )


def test_gpipe_train_step_learns():
    """Gradients flow through the ppermute schedule (reverse pipeline); the
    full train step learns under the production default remat."""
    cfg = tiny_cfg(n_layers=4, pipeline_microbatches=4, remat=True)
    mesh = build_mesh(MeshSpec(dp=1, pp=4, tp=2))
    opt = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    batch = {
        "tokens": jax.random.randint(jax.random.key(3), (8, 17), 0, cfg.vocab_size).astype(jnp.int32)
    }
    params, opt_state, l0, _ = step(state.params, state.opt_state, batch)
    losses = [float(l0)]
    for _ in range(4):
        params, opt_state, l, _ = step(params, opt_state, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_gpipe_moe_matches_dense_and_trains():
    """MoE inside the GPipe body: numerically identical to the
    weight-gathered scan path (f32), and the train step learns. (Round 1
    rejected this combo over a GSPMD CHECK-abort whose real cause was
    cp-sharded activations crossing the manual-pp boundary — fixed by
    keeping pipeline activations off cp, see llama._no_cp_activations.)"""
    import dataclasses

    from lws_tpu.parallel.mesh import MeshSpec as MS

    cfg = tiny_cfg(n_layers=4, n_experts=4, top_k=2, dtype=jnp.float32)
    cfg_pipe = dataclasses.replace(cfg, pipeline_microbatches=2)
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(2), (4, 16), 0, cfg.vocab_size).astype(jnp.int32)
    mesh = build_mesh(MS(dp=1, pp=2, cp=2, tp=2))
    with jax.set_mesh(mesh):
        dense_logits, _ = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
        piped_logits, _ = jax.jit(lambda p, t: forward(p, t, cfg_pipe))(params, tokens)
    assert jnp.allclose(dense_logits, piped_logits, atol=1e-4), (
        float(jnp.abs(dense_logits - piped_logits).max())
    )

    opt = make_optimizer(lr=1e-2)
    state = init_train_state(cfg_pipe, mesh, opt)
    step = make_train_step(cfg_pipe, mesh, opt)
    batch = {
        "tokens": jax.random.randint(jax.random.key(3), (4, 17), 0, cfg.vocab_size).astype(jnp.int32)
    }
    params2, opt_state, l0, _ = step(state.params, state.opt_state, batch)
    params2, opt_state, l1, _ = step(params2, opt_state, batch)
    assert jnp.isfinite(l0) and float(l1) < float(l0)
