"""MoE (Mixtral-class) serving: the GShard dense-dispatch model serves
through both engines, composed with everything the dense path has.

The MoE forward was train-tested since round 1 (tests/test_model.py) but no
serving path ever pinned it: these tests cross-check the two engines
against each other (independent cache layouts — dense [B,S] vs paged block
pool — agreeing on every token is a strong exactness signal) and compose
MoE with chunked admission, speculative drain, int8 weights, and a tp mesh
(experts ride the 'tp' axis — expert parallelism, models/llama.py ep).

Also home to the dense spec-decode x prefix-cache composition test — the
one engine-feature pairing its sibling suites (test_paged_speculative,
test_prefix_cache) don't cover."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving import Engine
from lws_tpu.serving.paged_engine import PagedBatchEngine


def moe_cfg(**kw):
    return LlamaConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, n_experts=4, top_k=2, max_seq_len=256,
        dtype=jnp.float32, param_dtype=jnp.float32, remat=False, **kw,
    )


@pytest.fixture(scope="module")
def setup():
    cfg = moe_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


def paged_results(cfg, params, prompts, max_new=12, **kw):
    spec = kw.pop("speculative", False)
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16, **kw)
    rids = []
    for p in prompts:
        rids.append(eng.submit(p, max_new_tokens=max_new))
        eng.step_n(2)
    if spec:
        eng.run_until_drained_speculative(gamma=4)
    else:
        eng.run_until_drained()
    return [eng.result(r) for r in rids]


def test_moe_engines_agree(setup):
    """Plain Engine and PagedBatchEngine greedy trajectories must be
    identical for the MoE model (independent cache layouts agreeing)."""
    cfg, params = setup
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 120, size=12).astype(np.int32)

    plain = Engine(cfg, params, batch_size=1, max_len=256)
    want = np.asarray(plain.generate(prompt.reshape(1, -1), max_new_tokens=12).tokens)[0]

    got = paged_results(cfg, params, [prompt])[0]
    assert list(want) == got, (list(want), got)


def test_moe_chunked_and_speculative_compose(setup):
    cfg, params = setup
    rng = np.random.RandomState(1)
    pat = rng.randint(1, 120, size=8).astype(np.int32)
    prompts = [np.tile(pat, 6), rng.randint(1, 120, size=40).astype(np.int32)]

    base = paged_results(cfg, params, prompts)
    chunked_spec = paged_results(
        cfg, params, prompts,
        prefill_chunk=16, interleave_steps=2, speculative=True,
    )
    assert base == chunked_spec


def test_moe_int8_weights_serve(setup):
    """quantize_params covers the expert tensors ([L,E,D,F] with [L,E,F]
    scales); the quantized MoE model must serve and agree across engines."""
    cfg, params = setup
    from lws_tpu.models.quant import quantize_params

    qparams = quantize_params(params)
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 120, size=10).astype(np.int32)
    plain = Engine(cfg, qparams, batch_size=1, max_len=256)
    want = np.asarray(plain.generate(prompt.reshape(1, -1), max_new_tokens=8).tokens)[0]
    got = paged_results(cfg, qparams, [prompt], max_new=8)[0]
    assert list(want) == got


def test_moe_tp_mesh_expert_parallel(setup):
    """Experts shard over 'tp' (expert parallelism): the tp=2 engine must
    produce the single-device trajectory exactly."""
    cfg, params = setup
    from lws_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, 120, size=10).astype(np.int32)
    want = paged_results(cfg, params, [prompt], max_new=8)[0]
    got = paged_results(cfg, params, [prompt], max_new=8, mesh=mesh)[0]
    assert want == got


def test_spec_decode_with_prefix_cache():
    """Speculative drain on a prefix-cache engine: draft K/V writes land at
    pos >= prompt length, i.e. always in PRIVATE blocks — shared prefix
    blocks must come through byte-stable (token-exactness vs the
    non-speculative prefix-cache engine proves it)."""
    cfg = LlamaConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=256, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(4)
    base = rng.randint(1, 60, size=48).astype(np.int32)
    prompts = [
        np.concatenate([base, rng.randint(1, 60, size=5).astype(np.int32)])
        for _ in range(3)
    ]

    def run(spec):
        eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                               prefix_cache=True)
        rids = []
        for p in prompts:
            rids.append(eng.submit(p, max_new_tokens=16))
            eng.step_n(2)
        if spec:
            eng.run_until_drained_speculative(gamma=4)
        else:
            eng.run_until_drained()
        return [eng.result(r) for r in rids], dict(eng.stats_prefix)

    want, p0 = run(False)
    got, p1 = run(True)
    assert want == got
    assert p1["hit_tokens"] == p0["hit_tokens"] > 0
