"""Kernel numerics: pallas flash attention (interpret mode on CPU) and ring
attention over a real 8-device cp axis, both against the jnp oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from lws_tpu.ops import flash_attention, reference_attention, ring_attention


def make_qkv(key, B=2, S=256, H=4, Hkv=2, D=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, Hkv, D), dtype)
    v = jax.random.normal(kv, (B, S, Hkv, D), dtype)
    return q, k, v


@pytest.mark.parametrize("S", [128, 256, 384])
def test_flash_matches_reference_interpret(S):
    q, k, v = make_qkv(jax.random.key(0), S=S)
    expected = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_flash_gqa_head_mapping():
    # With distinct kv heads, a wrong h->kv mapping is loud.
    q, k, v = make_qkv(jax.random.key(1), B=1, S=128, H=8, Hkv=2, D=64)
    expected = reference_attention(q, k, v)
    got = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_flash_ragged_seq_padding():
    q, k, v = make_qkv(jax.random.key(2), S=200)  # not a block multiple
    expected = reference_attention(q, k, v)
    got = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_ring_attention_matches_full():
    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("cp",))
    q, k, v = make_qkv(jax.random.key(3), B=2, S=256, H=4, Hkv=2, D=32)
    expected = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, axis="cp", causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4, rtol=2e-4)


def test_ring_attention_jits_under_mesh():
    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("cp",))
    q, k, v = make_qkv(jax.random.key(4), B=1, S=128, H=4, Hkv=4, D=32)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh)

    out = f(q, k, v)
    expected = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-4, rtol=2e-4)
