"""Paged decode-attention pallas kernel: exactness vs the XLA gather
reference (interpret mode on CPU), across GQA/MHA, scrambled block tables,
block-boundary positions, and multi-layer pools (VERDICT r3 #1 — the kernel
that replaces the dense-view gather at models/llama.py forward_decode_paged)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models.llama import _cached_attention
from lws_tpu.ops.paged_attention import paged_decode_attention


def reference(q, k_pool, v_pool, table, pos_b, layer):
    """The gather path the kernel replaces: materialize each slot's logical
    view, then dense cached attention."""
    B = q.shape[0]
    Hkv, hd = k_pool.shape[3], k_pool.shape[4]
    k_l, v_l = k_pool[layer], v_pool[layer]
    k_view = k_l[table].reshape(B, -1, Hkv, hd)
    v_view = v_l[table].reshape(B, -1, Hkv, hd)
    return _cached_attention(q, k_view, v_view, pos_b)


def make_case(rng, B, H, Hkv, hd, L, num_blocks, bs, max_blocks):
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((L, num_blocks, bs, Hkv, hd)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((L, num_blocks, bs, Hkv, hd)), jnp.float32)
    # Scrambled non-contiguous allocation; unallocated tail entries -> null 0.
    table = np.zeros((B, max_blocks), np.int32)
    pool_free = list(range(1, num_blocks))
    rng.shuffle(pool_free)
    pos = np.empty((B,), np.int32)
    for b in range(B):
        pos[b] = rng.integers(0, max_blocks * bs)
        n_live = pos[b] // bs + 1
        table[b, :n_live] = [pool_free.pop() for _ in range(n_live)]
    return q, k_pool, v_pool, jnp.asarray(table), jnp.asarray(pos)


@pytest.mark.parametrize("H,Hkv", [(4, 2), (4, 4), (8, 2)])
def test_kernel_matches_gather_reference(H, Hkv):
    rng = np.random.default_rng(0)
    B, hd, L, bs, max_blocks = 5, 128, 3, 8, 6
    num_blocks = B * max_blocks + 1
    q, k_pool, v_pool, table, pos = make_case(
        rng, B, H, Hkv, hd, L, num_blocks, bs, max_blocks
    )
    for layer in range(L):
        got = paged_decode_attention(
            q, k_pool, v_pool, table, pos, layer, interpret=True
        )
        want = reference(q, k_pool, v_pool, table, pos, layer)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernel_block_boundary_positions():
    """pos exactly at block edges: last block holds exactly 1 token / is
    exactly full — the masking and live-block count must agree with the
    reference at both edges."""
    rng = np.random.default_rng(1)
    B, H, Hkv, hd, L, bs, max_blocks = 4, 4, 2, 128, 1, 8, 4
    q, k_pool, v_pool, table, _ = make_case(
        rng, B, H, Hkv, hd, L, B * max_blocks + 1, bs, max_blocks
    )
    table = jnp.asarray(
        np.arange(1, B * max_blocks + 1, dtype=np.int32).reshape(B, max_blocks)
    )
    for pos_val in [0, bs - 1, bs, 2 * bs - 1, max_blocks * bs - 1]:
        pos = jnp.full((B,), pos_val, jnp.int32)
        got = paged_decode_attention(q, k_pool, v_pool, table, pos, 0, interpret=True)
        want = reference(q, k_pool, v_pool, table, pos, 0)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernel_mixed_lengths_ignore_null_and_stale_blocks():
    """Slots at very different lengths; dead table entries point at the null
    block AND at blocks owned by other slots (release/reuse) — neither may
    leak into another slot's attention."""
    rng = np.random.default_rng(2)
    B, H, Hkv, hd, L, bs, max_blocks = 3, 8, 2, 128, 2, 8, 4
    num_blocks = 8
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((L, num_blocks, bs, Hkv, hd)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((L, num_blocks, bs, Hkv, hd)), jnp.float32)
    table = jnp.asarray(
        np.array(
            [
                [1, 2, 3, 4],   # long slot
                [5, 0, 0, 0],   # short; tail = null
                [6, 7, 1, 2],   # stale tail pointing at slot 0's blocks
            ],
            np.int32,
        )
    )
    pos = jnp.asarray([max_blocks * bs - 1, 3, 2 * bs - 1], jnp.int32)
    for layer in range(L):
        got = paged_decode_attention(q, k_pool, v_pool, table, pos, layer, interpret=True)
        want = reference(q, k_pool, v_pool, table, pos, layer)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_paged_engine_with_kernel_matches_dense(monkeypatch):
    """End-to-end: PagedBatchEngine with the kernel FORCED on (interpret
    mode on CPU) must be token-identical to the dense engine."""
    from lws_tpu.serving.batch_engine import BatchEngine
    from lws_tpu.serving.paged_engine import PagedBatchEngine
    from lws_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()

    monkeypatch.setenv("LWS_TPU_PAGED_ATTN", "interpret")

    dense = BatchEngine(cfg, params, slots=4, max_len=64)
    paged = PagedBatchEngine(cfg, params, slots=4, max_len=64, block_size=8)
    r = np.random.RandomState(3)
    ps = [r.randint(1, 255, size=r.randint(4, 40)).astype(np.int32) for _ in range(4)]
    ids_d = [dense.submit(p, max_new_tokens=12) for p in ps]
    ids_p = [paged.submit(p, max_new_tokens=12) for p in ps]
    dense.run_until_drained()
    paged.run_until_drained()
    for d, p in zip(ids_d, ids_p):
        assert dense.result(d) == paged.result(p)


# ---------------------------------------------------------------------------
# Paged x int8-KV composition (VERDICT r3 #4: the two density features must
# compose — half-width KV rows over a footprint-sized pool).


def quant_pools(rng, L, num_blocks, bs, Hkv, hd):
    from lws_tpu.models.llama import _quantize_kv

    k = jnp.asarray(rng.standard_normal((L, num_blocks, bs, Hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((L, num_blocks, bs, Hkv, hd)), jnp.float32)
    kq, ks = _quantize_kv(k)
    vq, vs = _quantize_kv(v)
    return kq, ks, vq, vs


def test_quantized_kernel_matches_dequant_reference():
    from lws_tpu.models.llama import _dequantize_kv

    rng = np.random.default_rng(4)
    B, H, Hkv, hd, L, bs, max_blocks = 4, 8, 2, 128, 2, 8, 4
    num_blocks = B * max_blocks + 1
    kq, ks, vq, vs = quant_pools(rng, L, num_blocks, bs, Hkv, hd)
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), jnp.float32)
    table = jnp.asarray(
        np.arange(1, B * max_blocks + 1, dtype=np.int32).reshape(B, max_blocks)
    )
    pos = jnp.asarray(rng.integers(0, max_blocks * bs, size=B), jnp.int32)
    for layer in range(L):
        got = paged_decode_attention(
            q, kq, vq, table, pos, layer, k_scale=ks, v_scale=vs, interpret=True
        )
        k_deq = _dequantize_kv(kq, ks, jnp.float32)
        v_deq = _dequantize_kv(vq, vs, jnp.float32)
        want = reference(q, k_deq, v_deq, table, pos, layer)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_paged_int8_engine_kernel_matches_xla_fallback(monkeypatch):
    """PagedBatchEngine with kv_quant: the pallas path and the XLA
    gather+dequant fallback must produce identical greedy tokens from the
    same quantized pool (the kernel changes traffic, not math)."""
    from lws_tpu.serving.paged_engine import PagedBatchEngine
    from lws_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False, kv_quant=True,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    r = np.random.RandomState(5)
    ps = [r.randint(1, 255, size=r.randint(4, 40)).astype(np.int32) for _ in range(3)]

    def run(mode):
        monkeypatch.setenv("LWS_TPU_PAGED_ATTN", mode)
        eng = PagedBatchEngine(cfg, params, slots=3, max_len=64, block_size=8)
        ids = [eng.submit(p, max_new_tokens=10) for p in ps]
        eng.run_until_drained()
        return [eng.result(i) for i in ids]

    assert run("interpret") == run("0")


def test_paged_int8_close_to_paged_fp32():
    """Accuracy smoke: int8-KV logits track the fp32 cache within
    quantization noise on the first decode steps (not a token-exactness
    claim — int8 IS lossy; this guards against sign/scale bugs)."""
    from lws_tpu.models.llama import (
        LlamaConfig, init_params, init_paged_cache, forward_decode_paged,
    )
    import dataclasses

    cfg32 = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    cfg8 = dataclasses.replace(cfg32, kv_quant=True)
    params = jax.jit(lambda: init_params(cfg32, jax.random.key(0)))()
    B, bs, max_blocks = 2, 8, 4
    table = jnp.asarray(
        np.arange(1, B * max_blocks + 1, dtype=np.int32).reshape(B, max_blocks)
    )
    tokens = jnp.asarray([5, 9], jnp.int32)
    pos = jnp.asarray([0, 3], jnp.int32)
    c32 = init_paged_cache(cfg32, B * max_blocks + 1, bs)
    c8 = init_paged_cache(cfg8, B * max_blocks + 1, bs)
    logits32 = logits8 = None
    for step in range(4):
        logits32, c32 = forward_decode_paged(params, tokens, c32, table, pos, cfg32)
        logits8, c8 = forward_decode_paged(params, tokens, c8, table, pos, cfg8)
        tokens = jnp.argmax(logits32, axis=-1).astype(jnp.int32)
        pos = pos + 1
    err = jnp.max(jnp.abs(logits32 - logits8)) / jnp.max(jnp.abs(logits32))
    assert err < 0.08, float(err)
