"""Paged KV cache: exactness vs the dense slotted engine, block-pool
accounting, reuse after completion, and density backpressure (VERDICT #4;
serving-density axis of the vLLM-TPU reference shape,
docs/examples/vllm/TPU/lws.yaml:22-34)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving.batch_engine import BatchEngine
from lws_tpu.serving.paged_engine import PagedBatchEngine


@pytest.fixture(scope="module")
def small_model():
    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return cfg, params


def prompts(n, rng=3):
    r = np.random.RandomState(rng)
    return [r.randint(1, 255, size=r.randint(4, 40)).astype(np.int32) for _ in range(n)]


def test_paged_matches_dense_engine(small_model):
    """Greedy decode through the paged pool must be token-identical to the
    dense slotted engine — paging changes memory layout, not math."""
    cfg, params = small_model
    dense = BatchEngine(cfg, params, slots=4, max_len=64)
    paged = PagedBatchEngine(cfg, params, slots=4, max_len=64, block_size=8)
    ids_d, ids_p = [], []
    for p in prompts(4):
        ids_d.append(dense.submit(p, max_new_tokens=12))
        ids_p.append(paged.submit(p, max_new_tokens=12))
    dense.run_until_drained()
    paged.run_until_drained()
    for d, p in zip(ids_d, ids_p):
        assert dense.result(d) == paged.result(p)


def test_paged_staggered_admission_matches(small_model):
    """Mid-stream admission into freed blocks: a later request reuses blocks
    released by an earlier one while other slots keep decoding — outputs must
    still match the dense engine run of the same schedule."""
    cfg, params = small_model
    dense = BatchEngine(cfg, params, slots=2, max_len=64)
    # Pool sized so the third request NEEDS blocks from the first's release.
    paged = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=8,
                             num_blocks=2 * 8 + 1)
    ps = prompts(3, rng=7)

    def run(engine):
        out = {}
        a = engine.submit(ps[0], max_new_tokens=4)   # finishes first
        b = engine.submit(ps[1], max_new_tokens=20)
        third = None
        for _ in range(200):
            engine.step()
            if third is None and engine.active_count < 2:
                third = engine.submit(ps[2], max_new_tokens=10)
                assert third is not None
            if engine.active_count == 0 and third is not None:
                break
        out["a"], out["b"], out["c"] = engine.result(a), engine.result(b), engine.result(third)
        return out

    assert run(dense) == run(paged)


def test_pool_exhaustion_backpressure_and_reuse(small_model):
    """Admission returns None when the pool is dry; blocks return on
    completion and admission succeeds again (the density contract)."""
    cfg, params = small_model
    # 9 usable blocks of 8 = 72 tokens of physical KV for 4 slots x 64 logical.
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=64, block_size=8,
                           num_blocks=10)
    p = np.arange(1, 9, dtype=np.int32)  # 8 tokens -> bucket 8
    # footprint = max(8, 8+24) = 32 -> 4 blocks each; two fit, third doesn't.
    a = eng.submit(p, max_new_tokens=24)
    b = eng.submit(p, max_new_tokens=24)
    assert a is not None and b is not None
    assert eng.free_blocks == 1
    assert eng.submit(p, max_new_tokens=24) is None  # pool dry, slots free
    eng.run_until_drained()
    assert eng.free_blocks == 9  # everything returned
    c = eng.submit(p, max_new_tokens=24)
    assert c is not None
    eng.run_until_drained()
    assert eng.result(c) == eng.result(a)  # same prompt, same greedy tokens


def test_paged_density_exceeds_dense_capacity(small_model):
    """The headline: with a pool HALF the dense reservation, the engine still
    serves every slot concurrently when actual footprints fit — slots x
    max_len no longer bounds memory."""
    cfg, params = small_model
    slots, max_len, bs = 8, 64, 8
    dense_blocks = slots * (max_len // bs)  # 64 blocks dense equivalent
    eng = PagedBatchEngine(cfg, params, slots=slots, max_len=max_len,
                           block_size=bs, num_blocks=dense_blocks // 2 + 1)
    ids = []
    p = np.arange(1, 17, dtype=np.int32)  # 16 tokens, footprint 16+8=24 -> 3 blocks
    for _ in range(slots):
        rid = eng.submit(p, max_new_tokens=8)
        assert rid is not None  # all 8 slots admitted on a half-size pool
        ids.append(rid)
    assert eng.active_count == slots
    eng.run_until_drained()
    results = [eng.result(r) for r in ids]
    assert all(r == results[0] for r in results)  # same prompt -> same tokens
