"""Per-request sampling under paged continuous batching (vLLM
SamplingParams parity): greedy and sampled requests share a batch without
perturbing each other; seeds make sampling reproducible; every slot draws
from its own PRNG stream."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models import init_params
from lws_tpu.models.llama import LlamaConfig
from lws_tpu.serving.engine import SamplingParams, sample_logits, sample_logits_per_slot
from lws_tpu.serving.paged_engine import PagedBatchEngine


def tiny_cfg(**kw):
    base = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    base.update(kw)
    return LlamaConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, jax.jit(lambda: init_params(cfg, jax.random.key(0)))()


def make_engine(cfg, params):
    return PagedBatchEngine(cfg, params, slots=3, max_len=32, block_size=8)


PROMPT_A = np.array([5, 9, 2], np.int32)
PROMPT_B = np.array([7, 7, 1, 4], np.int32)


def test_greedy_slot_unperturbed_by_sampled_neighbors(model):
    """A greedy request decodes the SAME tokens whether its batch neighbors
    sample or not — per-slot streams and params are fully isolated."""
    cfg, params = model
    ref = make_engine(cfg, params)
    a0 = ref.submit(PROMPT_A, max_new_tokens=8)
    ref.run_until_drained()

    eng = make_engine(cfg, params)
    a = eng.submit(PROMPT_A, max_new_tokens=8)
    b = eng.submit(PROMPT_B, max_new_tokens=8, temperature=1.5, top_k=20, seed=7)
    eng.run_until_drained()
    assert eng.result(a) == ref.result(a0)
    assert len(eng.result(b)) == 8


def test_seeded_sampling_reproducible(model):
    cfg, params = model

    def run(seed):
        eng = make_engine(cfg, params)
        r = eng.submit(PROMPT_A, max_new_tokens=10, temperature=1.0, seed=seed)
        eng.run_until_drained()
        return eng.result(r)

    assert run(42) == run(42)
    runs = {tuple(run(s)) for s in (1, 2, 3, 4, 5)}
    assert len(runs) > 1, "five seeds all produced identical samples"


def test_top_k_one_is_greedy(model):
    """temperature > 0 with top_k=1 must reduce to argmax exactly."""
    cfg, params = model
    ref = make_engine(cfg, params)
    a0 = ref.submit(PROMPT_B, max_new_tokens=8)
    ref.run_until_drained()

    eng = make_engine(cfg, params)
    a = eng.submit(PROMPT_B, max_new_tokens=8, temperature=2.0, top_k=1, seed=3)
    eng.run_until_drained()
    assert eng.result(a) == ref.result(a0)


def test_sampling_survives_slot_reuse(model):
    """A freed slot's sampling params must not leak into the next occupant:
    a greedy request admitted into a slot previously used for sampling stays
    greedy."""
    cfg, params = model
    eng = PagedBatchEngine(cfg, params, slots=1, max_len=32, block_size=8)
    s = eng.submit(PROMPT_A, max_new_tokens=4, temperature=2.0, seed=9)
    eng.run_until_drained()
    assert len(eng.result(s)) == 4

    g = eng.submit(PROMPT_B, max_new_tokens=8)  # same slot, greedy
    eng.run_until_drained()
    ref = make_engine(cfg, params)
    g0 = ref.submit(PROMPT_B, max_new_tokens=8)
    ref.run_until_drained()
    assert eng.result(g) == ref.result(g0)


def test_per_slot_sampler_matches_scalar_sampler():
    """With uniform params and the same key, the vectorized per-slot sampler
    must agree with Engine.sample_logits (same masking order, same
    categorical draw)."""
    key = jax.random.key(0)
    logits = jax.random.normal(jax.random.key(1), (4, 64)) * 3.0
    for temp, k, p in ((1.0, 0, 1.0), (0.7, 10, 1.0), (1.3, 0, 0.9), (1.0, 8, 0.8)):
        want = sample_logits(logits, key, SamplingParams(temp, k, p))
        got = sample_logits_per_slot(
            logits,
            jnp.broadcast_to(key, (4,)),
            jnp.full((4,), temp, jnp.float32),
            jnp.full((4,), k, jnp.int32),
            jnp.full((4,), p, jnp.float32),
        )
        # sample_logits draws ONE key for the whole batch (categorical over
        # [B, V]); the per-slot path draws per slot. Same key per slot ==
        # same key stream per row only for row 0; compare distributions via
        # the masked support instead: every drawn token must be inside the
        # scalar sampler's admissible set.
        V = logits.shape[-1]
        scaled = logits / temp
        masked = scaled
        if 0 < k < V:
            kth = jax.lax.top_k(masked, k)[0][:, -1][:, None]
            masked = jnp.where(masked < kth, -jnp.inf, masked)
        if p < 1.0:
            sorted_desc = jnp.sort(masked, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            cumulative = jnp.cumsum(probs, axis=-1)
            cutoff_idx = jnp.clip(jnp.sum(cumulative < p, axis=-1), 0, V - 1)
            cutoff = jnp.take_along_axis(sorted_desc, cutoff_idx[:, None], axis=1)
            masked = jnp.where(masked < cutoff, -jnp.inf, masked)
        for row in range(4):
            assert jnp.isfinite(masked[row, got[row]]), (temp, k, p, row)
            assert jnp.isfinite(masked[row, want[row]]), (temp, k, p, row)


def test_greedy_temperature_zero_ignores_keys():
    logits = jax.random.normal(jax.random.key(2), (3, 32))
    keys = jax.random.split(jax.random.key(3), 3)
    out = sample_logits_per_slot(
        logits, keys,
        jnp.zeros((3,), jnp.float32), jnp.zeros((3,), jnp.int32), jnp.ones((3,), jnp.float32),
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(jnp.argmax(logits, axis=-1)))
