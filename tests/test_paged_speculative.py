"""Speculative decoding composed with paged continuous batching (VERDICT r4
#4; device-resident with ring-riding dispatches since ISSUE 9).

step_speculative drafts each greedy slot's n-gram run ON DEVICE from a
per-slot history ring, verifies it in one batched dispatch
(models/llama.py forward_verify_paged), and commits acceptance in-kernel;
sampled slots ride the same dispatch advancing one token from their own
PRNG stream. Dispatches ride the same in-flight ring as step_n. Pinned:

  * token-exactness vs the non-speculative paged engine — all-greedy and
    MIXED (sampled+greedy) batches, int8 KV, tp=2 mesh;
  * byte-exactness vs the retained PR-8 host-loop oracle
    (step_speculative_sync), including under ring depth 2;
  * acceptance actually happens on repetitive content and the drain takes
    FEWER dispatches than sequential decode (the tokens/dispatch gain);
  * acceptance stats are recorded in engine.stats;
  * the near-max_len guard falls back instead of overrunning;
  * device n-gram drafting matches the host oracle token-for-token;
  * the steady-state spec loop never flushes the ring; an injected
    dispatch fault rolls back cleanly (discard + host-truth restore).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving.paged_engine import PagedBatchEngine


def tiny_cfg(**kw):
    return LlamaConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=256, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False, **kw,
    )


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


def prompts():
    rng = np.random.RandomState(0)
    pat = rng.randint(1, 60, size=8).astype(np.int32)
    return [np.tile(pat, 6), rng.randint(1, 60, size=20).astype(np.int32)]


def run(cfg, params, spec, sampled_second=False, mesh=None):
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                           mesh=mesh)
    p1, p2 = prompts()
    kw = dict(temperature=0.8, seed=7, top_k=10) if sampled_second else {}
    rids = [eng.submit(p1, max_new_tokens=24), eng.submit(p2, max_new_tokens=16, **kw)]
    if spec:
        eng.run_until_drained_speculative(gamma=4, ngram=3)
    else:
        eng.run_until_drained()
    return [eng.result(r) for r in rids], dict(eng.stats)


def test_greedy_exact_and_fewer_dispatches(setup):
    cfg, params = setup
    want, _ = run(cfg, params, spec=False)
    got, stats = run(cfg, params, spec=True)
    assert want == got
    assert stats["spec_accepted"] > 0, "no draft ever accepted"
    # Sequential decode needs 23 + 15 = 38 steps; spec must beat that.
    assert stats["spec_dispatches"] < 38, stats
    assert stats["spec_drafted"] >= stats["spec_accepted"]


def test_mixed_sampled_greedy_exact(setup):
    cfg, params = setup
    want, _ = run(cfg, params, spec=False, sampled_second=True)
    got, stats = run(cfg, params, spec=True, sampled_second=True)
    assert want == got
    assert stats["spec_dispatches"] > 0


def test_int8_kv_exact(setup):
    cfg, params = setup
    qcfg = dataclasses.replace(cfg, kv_quant=True)
    want, _ = run(qcfg, params, spec=False)
    got, stats = run(qcfg, params, spec=True)
    assert want == got
    assert stats["spec_dispatches"] > 0


def test_tp_mesh_exact(setup):
    cfg, params = setup
    from lws_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    want, _ = run(cfg, params, spec=False)
    got, stats = run(cfg, params, spec=True, mesh=mesh)
    assert want == got
    assert stats["spec_accepted"] > 0


def test_near_max_len_falls_back(setup):
    """A slot within gamma+1 of max_len must refuse the spec dispatch (no
    block-table overrun) and still drain correctly via single steps."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=16)
    prompt = np.arange(1, 50, dtype=np.int32)  # 49 tokens, 15 of headroom
    rid = eng.submit(prompt, max_new_tokens=14)
    # headroom 64 - 50 = 14 < gamma+1 once a few tokens land
    assert eng.step_speculative(gamma=20) is False
    eng.run_until_drained_speculative(gamma=8)
    got = eng.result(rid)
    eng2 = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=16)
    rid2 = eng2.submit(prompt, max_new_tokens=14)
    eng2.run_until_drained()
    assert got == eng2.result(rid2)


# ---------------------------------------------------------------------------
# ISSUE 9: device-resident spec loop, ring-riding dispatches


def test_ngram_draft_device_matches_host(setup):
    """The in-kernel drafting must be token-for-token the host oracle
    (Engine._draft_ngram) whenever the history ring holds the full context
    — that parity is what keeps tokens/dispatch at sync levels."""
    from lws_tpu.models.llama import ngram_draft
    from lws_tpu.serving.engine import Engine

    H = 64
    rng = np.random.RandomState(3)
    contexts = []
    for n in (5, 9, 17, 40, 63):
        pat = rng.randint(1, 60, size=max(2, n // 4)).astype(int)
        ctx = list(np.tile(pat, 8))[:n]  # repetitive: matches exist
        contexts.append(ctx)
        contexts.append(list(rng.randint(1, 60, size=n)))  # random: mostly none
    for ngram in (2, 3):
        for gamma in (1, 4):
            fn = jax.jit(
                lambda h, l, ng=ngram, g=gamma: ngram_draft(h, l, ng, g)
            )
            for ctx in contexts:
                want = Engine._draft_ngram(list(ctx), ngram, gamma)
                hist = np.zeros((H,), np.int32)
                hist[: len(ctx)] = ctx
                got = [int(t) for t in fn(jnp.asarray(hist), jnp.int32(len(ctx)))]
                assert got == [int(t) for t in want], (ctx, ngram, gamma)


def run_ring(cfg, params, sync, depth=2, sampled_second=False, **eng_kw):
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                           pipeline_depth=depth, donate_steps=False, **eng_kw)
    p1, p2 = prompts()
    kw = dict(temperature=0.8, seed=7, top_k=10) if sampled_second else {}
    rids = [eng.submit(p1, max_new_tokens=24), eng.submit(p2, max_new_tokens=16, **kw)]
    eng.run_until_drained_speculative(gamma=4, ngram=3, sync=sync)
    return [eng.result(r) for r in rids], eng


def test_sync_oracle_byte_identical(setup):
    """The device-resident ring-riding loop must emit byte-identical greedy
    streams to the PR-8 host-loop oracle — the ISSUE-9 correctness bar —
    at matching tokens/dispatch (device drafts == host drafts when the ring
    covers the context)."""
    cfg, params = setup
    want, eng_sync = run_ring(cfg, params, sync=True, depth=0)
    got, eng_pipe = run_ring(cfg, params, sync=False, depth=2)
    assert want == got
    s, p = eng_sync.stats, eng_pipe.stats
    assert p["spec_accepted"] == s["spec_accepted"]
    assert (p["spec_dispatches"] + p.get("spec_fallback_dispatches", 0)
            <= s["spec_dispatches"] + s.get("spec_fallback_dispatches", 0))


def test_mixed_batch_under_ring_matches_oracle(setup):
    """Mixed greedy+sampled batch at ring depth 2: the per-slot key schedule
    (one split per produced token) must survive pipelining — sampled streams
    stay byte-identical to the sync oracle's."""
    cfg, params = setup
    want, _ = run_ring(cfg, params, sync=True, depth=0, sampled_second=True)
    got, _ = run_ring(cfg, params, sync=False, depth=2, sampled_second=True)
    assert want == got


def test_no_steady_state_flushes(setup):
    """Acceptance criterion: NO ring flush on the speculative steady-state
    path. Ten ring-riding spec dispatches against deep budgets must leave
    the flush counter untouched (flushes remain only at spec-mode entry —
    which finds an empty ring and does not count — budget/tail boundaries,
    and rollback)."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16,
                           pipeline_depth=2, donate_steps=False)
    p1, _ = prompts()
    assert eng.submit(p1, max_new_tokens=128) is not None
    dispatched = 0
    for _ in range(10):
        assert eng.step_speculative(gamma=4, ngram=3) is True
        dispatched += 1
    stats = eng._pipeline.stats
    assert dispatched == 10
    assert stats["flushes"] == 0, stats
    assert stats["max_inflight"] == 2, stats
    eng.run_until_drained_speculative(gamma=4, ngram=3)


def test_mid_stream_admission_during_inflight_spec(setup):
    """Admission while spec chunks are in flight must seed the new slot's
    device history/budget WITHOUT a flush, and every stream must match the
    plain step_n oracle (greedy streams are schedule-independent)."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                           pipeline_depth=2, donate_steps=False)
    p1, p2 = prompts()
    r1 = eng.submit(p1, max_new_tokens=32)
    for _ in range(3):
        assert eng.step_speculative(gamma=4, ngram=3) is True
    flushes_before = eng._pipeline.stats["flushes"]
    r2 = eng.submit(p2, max_new_tokens=16)  # admitted mid-flight
    assert eng._pipeline.stats["flushes"] == flushes_before
    assert eng.step_speculative(gamma=4, ngram=3) is True
    eng.run_until_drained_speculative(gamma=4, ngram=3)

    oracle = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16)
    o1 = oracle.submit(p1, max_new_tokens=32)
    o2 = oracle.submit(p2, max_new_tokens=16)
    oracle.run_until_drained()
    assert eng.result(r1) == oracle.result(o1)
    assert eng.result(r2) == oracle.result(o2)


def test_early_retire_and_eviction_during_inflight_spec(setup):
    """Uneven budgets retire requests inside in-flight chunks; a tight
    prefix-cache pool forces LRU eviction (whose allocator flushes the
    ring). Every stream must still match the plain oracle."""
    cfg, params = setup
    kw = dict(slots=3, max_len=128, block_size=16, prefix_cache=True,
              num_blocks=17)  # 16 usable blocks: admissions contend
    eng = PagedBatchEngine(cfg, params, pipeline_depth=2, donate_steps=False,
                           **kw)
    p1, p2 = prompts()
    r1 = eng.submit(p1, max_new_tokens=40)   # long
    r2 = eng.submit(p2, max_new_tokens=6)    # retires early, mid-ring
    for _ in range(4):
        eng.step_speculative(gamma=4, ngram=3)
    # Allocation pressure: this admission evicts LRU-parked prefix blocks.
    p3 = np.tile(np.arange(1, 9, dtype=np.int32), 6)
    r3 = eng.submit(p3, max_new_tokens=12)
    assert r3 is not None
    eng.run_until_drained_speculative(gamma=4, ngram=3)

    oracle = PagedBatchEngine(cfg, params, **kw)
    o1 = oracle.submit(p1, max_new_tokens=40)
    o2 = oracle.submit(p2, max_new_tokens=6)
    oracle.run_until_drained()
    o3 = oracle.submit(p3, max_new_tokens=12)
    oracle.run_until_drained()
    assert eng.result(r1) == oracle.result(o1)
    assert eng.result(r2) == oracle.result(o2)
    assert eng.result(r3) == oracle.result(o3)


def test_interleaved_step_n_refresh(setup):
    """Alternating plain step_n and spec dispatches must stay exact: step_n
    stales the device history/budget, and the next spec entry rebuilds it
    from host truth."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16,
                           pipeline_depth=2, donate_steps=False)
    p1, _ = prompts()
    rid = eng.submit(p1, max_new_tokens=30)
    eng.step_speculative(gamma=4, ngram=3)
    eng.step_n(2)          # stales spec state
    eng.step_speculative(gamma=4, ngram=3)  # refresh path
    eng.run_until_drained_speculative(gamma=4, ngram=3)
    oracle = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16)
    oid = oracle.submit(p1, max_new_tokens=30)
    oracle.run_until_drained()
    assert eng.result(rid) == oracle.result(oid)


def test_push_fault_rollback(setup):
    """Chaos: a `pipeline.dispatch` fault injected during a spec chunk must
    roll back cleanly — in-flight chunks discarded, pos_b/tokens restored
    from host truth — and the subsequent drain must still emit the oracle
    stream."""
    from lws_tpu.core import faults

    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16,
                           pipeline_depth=2, donate_steps=False)
    p1, _ = prompts()
    rid = eng.submit(p1, max_new_tokens=24)
    assert eng.step_speculative(gamma=4, ngram=3) is True  # one chunk in flight
    faults.INJECTOR.arm("pipeline.dispatch", "fail_n_times:1:RuntimeError")
    try:
        with pytest.raises(RuntimeError):
            eng.step_speculative(gamma=4, ngram=3)
    finally:
        faults.INJECTOR.disarm()
    assert len(eng._pipeline) == 0  # everything in flight was discarded
    assert eng._pipeline.stats["discarded"] >= 1
    eng.run_until_drained_speculative(gamma=4, ngram=3)
    oracle = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16)
    oid = oracle.submit(p1, max_new_tokens=24)
    oracle.run_until_drained()
    assert eng.result(rid) == oracle.result(oid)


def test_sampled_rows_never_extend_acceptance(setup):
    """Satellite contract: sampled slots ride the gamma+1-wide verify (the
    dispatch is static-shaped) but advance EXACTLY one token per dispatch —
    their filler draft rows are masked out of acceptance in-kernel even on
    maximally repetitive content."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16)
    pat = np.tile(np.arange(1, 9, dtype=np.int32), 6)
    rg = eng.submit(pat, max_new_tokens=40)
    rs = eng.submit(pat, max_new_tokens=40, temperature=0.8, seed=3)
    by_id = {r.request_id: r for r in eng._active.values()}
    dispatches = 0
    while dispatches < 10 and len(eng._active) > eng._sampled_active:
        before = len(by_id[rs].tokens)
        assert eng.step_speculative(gamma=4, ngram=3) is True
        eng._pipeline.flush()
        dispatches += 1
        # EXACTLY one token per dispatch, even with a maximally repetitive
        # history that would draft perfect matches if the filler mask broke.
        assert len(by_id[rs].tokens) - before == 1, "sampled slot overran"
    assert dispatches == 10
    eng.run_until_drained_speculative(gamma=4, ngram=3)
    # Greedy output self-repeats under a greedy loop: drafting accepted.
    assert eng.stats["spec_accepted"] > 0
    assert len(by_id[rg].tokens) == 40 and len(by_id[rs].tokens) == 40
    # All-sampled batches refuse the wide verify outright.
    eng2 = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16)
    eng2.submit(pat, max_new_tokens=8, temperature=0.8, seed=1)
    assert eng2.step_speculative(gamma=4) is False


def test_ring_wrap_spec_history(setup):
    """A spec_history window smaller than the context still drains exactly
    (drafting is match-only; acceptance protects the stream) and keeps
    accepting on content whose period fits the window."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16,
                           spec_history=16)
    pat = np.tile(np.arange(1, 9, dtype=np.int32), 6)  # period 8 < H=16
    rid = eng.submit(pat, max_new_tokens=24)
    eng.run_until_drained_speculative(gamma=4, ngram=3)
    oracle = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16)
    oid = oracle.submit(pat, max_new_tokens=24)
    oracle.run_until_drained()
    assert eng.result(rid) == oracle.result(oid)
    assert eng.stats["spec_accepted"] > 0
