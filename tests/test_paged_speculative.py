"""Speculative decoding composed with paged continuous batching (VERDICT r4
#4: the r4 engine had spec decode only on the plain Engine at B=1; the
production engine had none).

step_speculative verifies every greedy slot's n-gram draft run in ONE
batched dispatch (models/llama.py forward_verify_paged); sampled slots ride
the same dispatch advancing one token from their own PRNG stream. Pinned:

  * token-exactness vs the non-speculative paged engine — all-greedy and
    MIXED (sampled+greedy) batches, int8 KV, tp=2 mesh;
  * acceptance actually happens on repetitive content and the drain takes
    FEWER dispatches than sequential decode (the tokens/dispatch gain);
  * acceptance stats are recorded in engine.stats;
  * the near-max_len guard falls back instead of overrunning.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving.paged_engine import PagedBatchEngine


def tiny_cfg(**kw):
    return LlamaConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=256, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False, **kw,
    )


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


def prompts():
    rng = np.random.RandomState(0)
    pat = rng.randint(1, 60, size=8).astype(np.int32)
    return [np.tile(pat, 6), rng.randint(1, 60, size=20).astype(np.int32)]


def run(cfg, params, spec, sampled_second=False, mesh=None):
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                           mesh=mesh)
    p1, p2 = prompts()
    kw = dict(temperature=0.8, seed=7, top_k=10) if sampled_second else {}
    rids = [eng.submit(p1, max_new_tokens=24), eng.submit(p2, max_new_tokens=16, **kw)]
    if spec:
        eng.run_until_drained_speculative(gamma=4, ngram=3)
    else:
        eng.run_until_drained()
    return [eng.result(r) for r in rids], dict(eng.stats)


def test_greedy_exact_and_fewer_dispatches(setup):
    cfg, params = setup
    want, _ = run(cfg, params, spec=False)
    got, stats = run(cfg, params, spec=True)
    assert want == got
    assert stats["spec_accepted"] > 0, "no draft ever accepted"
    # Sequential decode needs 23 + 15 = 38 steps; spec must beat that.
    assert stats["spec_dispatches"] < 38, stats
    assert stats["spec_drafted"] >= stats["spec_accepted"]


def test_mixed_sampled_greedy_exact(setup):
    cfg, params = setup
    want, _ = run(cfg, params, spec=False, sampled_second=True)
    got, stats = run(cfg, params, spec=True, sampled_second=True)
    assert want == got
    assert stats["spec_dispatches"] > 0


def test_int8_kv_exact(setup):
    cfg, params = setup
    qcfg = dataclasses.replace(cfg, kv_quant=True)
    want, _ = run(qcfg, params, spec=False)
    got, stats = run(qcfg, params, spec=True)
    assert want == got
    assert stats["spec_dispatches"] > 0


def test_tp_mesh_exact(setup):
    cfg, params = setup
    from lws_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    want, _ = run(cfg, params, spec=False)
    got, stats = run(cfg, params, spec=True, mesh=mesh)
    assert want == got
    assert stats["spec_accepted"] > 0


def test_near_max_len_falls_back(setup):
    """A slot within gamma+1 of max_len must refuse the spec dispatch (no
    block-table overrun) and still drain correctly via single steps."""
    cfg, params = setup
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=16)
    prompt = np.arange(1, 50, dtype=np.int32)  # 49 tokens, 15 of headroom
    rid = eng.submit(prompt, max_new_tokens=14)
    # headroom 64 - 50 = 14 < gamma+1 once a few tokens land
    assert eng.step_speculative(gamma=20) is False
    eng.run_until_drained_speculative(gamma=8)
    got = eng.result(rid)
    eng2 = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=16)
    rid2 = eng2.submit(prompt, max_new_tokens=14)
    eng2.run_until_drained()
    assert got == eng2.result(rid2)
