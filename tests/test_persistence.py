"""Store persistence: snapshot/restore round-trips the whole object graph and
a FRESH control plane process-equivalent resumes a rollout mid-flight."""

import json

from lws_tpu.core.serialize import load_store, restore_store, save_store, snapshot_store
from lws_tpu.core.store import Store
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder
from tests.test_disaggregatedset import make_ds
from tests.test_rolling_update import image_of, settle_and_make_ready, update_image


def test_snapshot_roundtrip_preserves_everything(tmp_path):
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True,
                      scheduler_provider="gang")
    from lws_tpu.sched import make_slice_nodes

    cp.add_nodes(make_slice_nodes("s0", topology="2x4"))
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).exclusive_topology().build())
    cp.create(make_ds())
    cp.run_until_stable()

    path = str(tmp_path / "state.json")
    save_store(cp.store, path)
    # JSON on disk, loadable.
    raw = json.load(open(path))
    assert {"LeaderWorkerSet", "Pod", "GroupSet", "Service", "Node", "PodGroup",
            "ControllerRevision", "DisaggregatedSet"} <= set(raw)

    fresh = Store()
    n = load_store(fresh, path)
    assert n == sum(len(v) for v in raw.values())
    # Deep equality of the restored graph.
    for kind, objs in snapshot_store(cp.store).items():
        assert snapshot_store(fresh)[kind] == objs, kind
    # Identity (uid/rv) preserved; new writes get fresh versions.
    pod = fresh.get("Pod", "default", "sample-0")
    orig = cp.store.get("Pod", "default", "sample-0")
    assert pod.meta.uid == orig.meta.uid
    assert pod.meta.resource_version == orig.meta.resource_version
    pod.status.message = "x"
    updated = fresh.update_status(pod)
    assert updated.meta.resource_version > orig.meta.resource_version


def test_restart_resumes_rolling_update(tmp_path):
    """Snapshot mid-rollout -> restore into a brand-new control plane ->
    the update completes (the reference gets this from etcd; SURVEY §5)."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(3).size(2).image("img:v1").build())
    settle_and_make_ready(cp)
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()  # mid-rollout: highest group recreated, not ready

    path = str(tmp_path / "state.json")
    save_store(cp.store, path)

    cp2 = ControlPlane()
    load_store(cp2.store, path)
    cp2.resync()
    settle_and_make_ready(cp2)
    for i in range(3):
        assert image_of(cp2, f"sample-{i}") == "img:v2"
    lws = cp2.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 3
    assert len(cp2.store.list("ControllerRevision")) == 1


def test_restore_invalidates_kind_version_caches():
    """Snapshot restore must bump kind_version: version-keyed caches (e.g.
    the scheduler node view) would otherwise serve pre-restore state."""
    from lws_tpu.core.serialize import restore_store, snapshot_store
    from lws_tpu.core.store import Store
    from lws_tpu.sched import make_slice_nodes

    src = Store()
    for n in make_slice_nodes("s", topology="2x4"):
        src.create(n)
    snap = snapshot_store(src)

    dst = Store()
    v0 = dst.kind_version("Node")
    restore_store(dst, snap)
    assert dst.kind_version("Node") > v0
    assert len(dst.list("Node")) == 2


def test_torn_tmp_snapshot_is_discarded(tmp_path):
    """kill -9 mid-snapshot leaves a partial .tmp next to the last completed
    state file; load must use the completed file and discard the torn tmp."""
    import os

    from lws_tpu.api.pod import Pod
    from lws_tpu.core.serialize import load_store, save_store
    from lws_tpu.core.store import Store, new_meta

    path = str(tmp_path / "state.json")
    src = Store()
    src.create(Pod(meta=new_meta("p0")))
    save_store(src, path)
    # Simulate the torn write: a partial JSON .tmp from a crashed snapshot.
    with open(path + ".tmp", "w") as f:
        f.write('{"Pod": [{"meta": {"name": "half')

    dst = Store()
    assert load_store(dst, path) == 1
    assert dst.get("Pod", "default", "p0") is not None
    assert not os.path.exists(path + ".tmp")


def test_corrupt_state_file_raises_not_half_restores(tmp_path):
    from lws_tpu.core.serialize import CorruptSnapshotError, load_store
    from lws_tpu.core.store import Store

    import pytest

    path = str(tmp_path / "state.json")
    with open(path, "w") as f:
        f.write('{"LeaderWorkerSet": [{"meta"')  # truncated mid-object

    dst = Store()
    with pytest.raises(CorruptSnapshotError):
        load_store(dst, path)
    assert dst.list("Pod") == []
