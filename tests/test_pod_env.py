"""Generic LWS_* + JAX coordinator env injection (parity with
pkg/utils/pod/pod_utils.go AddLWSVariables tests)."""

import pytest

from lws_tpu.api import contract
from lws_tpu.api.meta import ObjectMeta
from lws_tpu.api.pod import Container, EnvVar, Pod, PodSpec
from lws_tpu.utils.podutils import add_lws_variables


def make_pod(worker_index="0", group_index="1", size="4", subdomain="svc", env=()):
    return Pod(
        meta=ObjectMeta(
            name=f"lws-{group_index}" if worker_index == "0" else f"lws-{group_index}-{worker_index}",
            namespace="ns1",
            labels={
                contract.SET_NAME_LABEL_KEY: "lws",
                contract.GROUP_INDEX_LABEL_KEY: group_index,
                contract.WORKER_INDEX_LABEL_KEY: worker_index,
            },
            annotations={contract.SIZE_ANNOTATION_KEY: size},
        ),
        spec=PodSpec(
            containers=[Container(env=[EnvVar(*e) for e in env])],
            init_containers=[Container(name="init")],
            subdomain=subdomain,
        ),
    )


def test_injects_all_vars_leader_first():
    pod = make_pod(worker_index="2")
    add_lws_variables(pod)
    env = pod.spec.containers[0].env
    assert env[0].name == contract.LWS_LEADER_ADDRESS
    assert env[0].value == "lws-1.svc.ns1"
    values = {e.name: e.value for e in env}
    assert values[contract.LWS_GROUP_SIZE] == "4"
    assert values[contract.LWS_WORKER_INDEX] == "2"
    assert values[contract.JAX_COORDINATOR_ADDRESS] == "lws-1.svc.ns1:8471"
    assert values[contract.JAX_NUM_PROCESSES] == "4"
    assert values[contract.JAX_PROCESS_ID] == "2"
    # init containers too
    init_values = {e.name: e.value for e in pod.spec.init_containers[0].env}
    assert init_values[contract.LWS_LEADER_ADDRESS] == "lws-1.svc.ns1"


def test_injected_value_wins_and_user_env_preserved():
    pod = make_pod(env=[("MY_VAR", "x"), (contract.LWS_LEADER_ADDRESS, "stale")])
    add_lws_variables(pod)
    env = pod.spec.containers[0].env
    assert env[0].name == contract.LWS_LEADER_ADDRESS
    assert env[0].value == "lws-1.svc.ns1"
    assert [e.name for e in env].count(contract.LWS_LEADER_ADDRESS) == 1
    assert {e.name: e.value for e in env}["MY_VAR"] == "x"


def test_missing_labels_raise():
    pod = make_pod()
    del pod.meta.labels[contract.GROUP_INDEX_LABEL_KEY]
    with pytest.raises(ValueError):
        add_lws_variables(pod)
