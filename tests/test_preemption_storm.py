"""Preemption-storm soak (VERDICT r3 #9): TPU preemption is the norm the
KEP-820 budget exists for (ref keps/820-distributed-preflight-check).
At 128-slice scale, kill random slices mid-rollout and assert:

  * the fleet re-converges — every group fully ready on surviving capacity,
  * the KEP-820 restart budget is enforced (over-budget LWS goes terminally
    Failed instead of restart-looping),
  * no orphaned groups: every live pod belongs to a live group whose leader
    exists, and no group is split across slices under exclusive placement.

Marked slow: the storm case drives 128 slices x 4-pod groups through
repeated preemption waves."""

import random

import pytest

from lws_tpu.api import contract
from lws_tpu.api.pod import PodPhase
from lws_tpu.api.types import CONDITION_FAILED
from lws_tpu.runtime import ControlPlane
from lws_tpu.sched import make_slice_nodes
from lws_tpu.testing import LWSBuilder, condition_status, lws_pods


def preempt_slice(cp: ControlPlane, slice_name: str) -> None:
    """A slice going away = its nodes NotReady + its pods failed (what a
    real TPU preemption does to a v5p slice)."""
    for node in cp.store.list("Node"):
        if node.meta.labels.get(contract.NODE_TPU_SLICE_LABEL) != slice_name:
            continue
        fresh = cp.store.get("Node", node.meta.namespace, node.meta.name)
        fresh.status.ready = False
        cp.store.update_status(fresh)


def restore_slice(cp: ControlPlane, slice_name: str) -> None:
    for node in cp.store.list("Node"):
        if node.meta.labels.get(contract.NODE_TPU_SLICE_LABEL) != slice_name:
            continue
        fresh = cp.store.get("Node", node.meta.namespace, node.meta.name)
        fresh.status.ready = True
        cp.store.update_status(fresh)


def assert_no_orphans(cp: ControlPlane, lws_name: str) -> None:
    """Every pod belongs to a group whose leader exists; exclusive groups
    are never split across slices."""
    pods = lws_pods(cp.store, lws_name)
    by_group: dict[str, list] = {}
    for p in pods:
        by_group.setdefault(p.meta.labels[contract.GROUP_INDEX_LABEL_KEY], []).append(p)
    for group, members in by_group.items():
        leaders = [p for p in members
                   if p.meta.labels[contract.WORKER_INDEX_LABEL_KEY] == "0"]
        assert leaders, f"group {group} has {len(members)} pods but no leader"
        slices = set()
        for p in members:
            if not p.spec.node_name:
                continue
            node = cp.store.get("Node", "_cluster", p.spec.node_name)
            slices.add(node.meta.labels[contract.NODE_TPU_SLICE_LABEL])
        assert len(slices) <= 1, f"group {group} split across slices {slices}"


@pytest.mark.slow
def test_preemption_storm_at_128_slices():
    n_slices, replicas, size = 128, 64, 4
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True,
                      scheduler_provider="gang")
    for s in range(n_slices):
        cp.add_nodes(make_slice_nodes(f"slice-{s}", topology=f"{size}x4"))
    cp.create(
        LWSBuilder().replicas(replicas).size(size).tpu_chips(4)
        .exclusive_topology().build()
    )
    cp.run_until_stable(max_iterations=2_000_000)
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == replicas * size and all(p.status.ready for p in pods)

    rng = random.Random(7)
    # Three preemption waves, each mid-rollout: kill 8 random slices while a
    # template update is in flight, then restore them.
    for wave in range(3):
        lws = cp.store.get("LeaderWorkerSet", "default", "sample")
        for c in lws.spec.leader_worker_template.worker_template.spec.containers:
            c.image = f"v{wave + 2}"
        cp.store.update(lws)
        cp.run_until_stable(max_iterations=2_000_000)

        victims = rng.sample(range(n_slices), 8)
        for v in victims:
            preempt_slice(cp, f"slice-{v}")
        cp.run_until_stable(max_iterations=2_000_000)
        assert_no_orphans(cp, "sample")
        for v in victims:
            restore_slice(cp, f"slice-{v}")
        cp.run_until_stable(max_iterations=2_000_000)

    # Convergence: full fleet ready on the final template.
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == replicas * size
    assert all(p.status.ready for p in pods), (
        f"{sum(not p.status.ready for p in pods)} pods not ready after storm"
    )
    leaders = [p for p in pods if p.meta.labels[contract.WORKER_INDEX_LABEL_KEY] == "0"]
    assert all(p.spec.containers[0].image == "v4" for p in leaders)
    assert_no_orphans(cp, "sample")
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.ready_replicas == replicas
    assert condition_status(lws, CONDITION_FAILED) is not True


@pytest.mark.slow
def test_preemption_budget_enforced_under_storm():
    """KEP-820: an LWS with maxGroupRestarts=2 that keeps losing its slice
    goes terminally Failed instead of thrashing forever; a sibling with
    budget headroom keeps recovering."""
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    for s in range(4):
        cp.add_nodes(make_slice_nodes(f"slice-{s}", topology="2x4"))
    cp.create(
        LWSBuilder(name="budgeted").replicas(1).size(2).tpu_chips(4)
        .exclusive_topology()
        .annotation(contract.MAX_GROUP_RESTARTS_ANNOTATION_KEY, "2")
        .build()
    )
    cp.create(
        LWSBuilder(name="unbounded").replicas(1).size(2).tpu_chips(4)
        .exclusive_topology().build()
    )
    cp.run_until_stable(max_iterations=1_000_000)

    def slice_of(lws_name):
        for p in lws_pods(cp.store, lws_name):
            if p.spec.node_name:
                node = cp.store.get("Node", "_cluster", p.spec.node_name)
                return node.meta.labels[contract.NODE_TPU_SLICE_LABEL]
        return None

    for _ in range(4):  # storm: preempt whatever slice hosts each LWS
        for name in ("budgeted", "unbounded"):
            s = slice_of(name)
            if s is None:
                continue
            preempt_slice(cp, s)
            cp.run_until_stable(max_iterations=1_000_000)
            restore_slice(cp, s)
            cp.run_until_stable(max_iterations=1_000_000)

    budgeted = cp.store.get("LeaderWorkerSet", "default", "budgeted")
    assert condition_status(budgeted, CONDITION_FAILED) is True, (
        budgeted.status.conditions
    )
    # Budget exhausted -> the group stays DOWN (no restart-loop thrash).
    down = [p for p in lws_pods(cp.store, "budgeted") if p.status.phase == PodPhase.FAILED]
    live = [p for p in lws_pods(cp.store, "budgeted") if p.status.ready]
    assert not live or down, "budgeted LWS kept thrashing after Failed"

    unbounded = cp.store.get("LeaderWorkerSet", "default", "unbounded")
    assert condition_status(unbounded, CONDITION_FAILED) is not True
    assert unbounded.status.ready_replicas == 1, unbounded.status
