"""Automatic prefix caching (vLLM APC shape): block-aligned shared prompt
prefixes are reused from the pool — suffix-only prefill — with byte-exact
results vs the uncached engine, refcounted sharing, LRU parking/eviction,
and no cross-contamination."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models import init_params
from lws_tpu.models.llama import LlamaConfig
from lws_tpu.parallel import MeshSpec, build_mesh
from lws_tpu.serving.paged_engine import PagedBatchEngine


def tiny_cfg(**kw):
    base = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    base.update(kw)
    return LlamaConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, jax.jit(lambda: init_params(cfg, jax.random.key(0)))()


SYS = np.arange(1, 17, dtype=np.int32)          # 16 tokens = 2 full 8-blocks
PROMPT_X = np.concatenate([SYS, [40, 41, 42]]).astype(np.int32)
PROMPT_Y = np.concatenate([SYS, [50, 51]]).astype(np.int32)
PROMPT_Z = np.array([9, 9, 9, 9, 9, 9, 9, 9, 9, 9], np.int32)  # no shared prefix


def run(cfg, params, prompts, n=6, prefix_cache=True, slots=3, **engine_kw):
    eng = PagedBatchEngine(
        cfg, params, slots=slots, max_len=64, block_size=8,
        prefix_cache=prefix_cache, **engine_kw,
    )
    rids = [eng.submit(p, max_new_tokens=n) for p in prompts]
    assert all(r is not None for r in rids)
    eng.run_until_drained()
    return [eng.result(r) for r in rids], eng


def test_shared_prefix_exact_vs_uncached(model):
    cfg, params = model
    want, _ = run(cfg, params, [PROMPT_X, PROMPT_Y, PROMPT_Z], prefix_cache=False)
    got, eng = run(cfg, params, [PROMPT_X, PROMPT_Y, PROMPT_Z], prefix_cache=True)
    assert got == want
    # Y hit X's two system-prompt blocks (16 tokens); Z hit nothing.
    assert eng.stats_prefix["hit_tokens"] == 16
    assert eng.stats_prefix["hit_blocks"] == 2


def test_repeat_prompt_hits_all_shareable_blocks(model):
    """The same prompt twice: the repeat hits every shareable block but
    still recomputes at least one token (full-prompt caching is capped)."""
    cfg, params = model
    prompt = np.arange(1, 25, dtype=np.int32)  # 24 tokens: shareable 2 blocks
    want, _ = run(cfg, params, [prompt, prompt], prefix_cache=False)
    got, eng = run(cfg, params, [prompt, prompt], prefix_cache=True)
    assert got == want
    assert eng.stats_prefix["hit_tokens"] == 16  # (24-1)//8 = 2 blocks


def test_block_aligned_full_prompt_keeps_last_token_uncached(model):
    """plen an exact multiple of block_size: the LAST full block is never
    shared (the first-token logits must be computable)."""
    cfg, params = model
    prompt = np.arange(1, 17, dtype=np.int32)  # 16 = 2x8 exactly
    want, _ = run(cfg, params, [prompt, prompt], prefix_cache=False)
    got, eng = run(cfg, params, [prompt, prompt], prefix_cache=True)
    assert got == want
    assert eng.stats_prefix["hit_blocks"] == 1  # (16-1)//8 = 1


def test_cache_survives_release_and_is_lru_parked(model):
    """Sequential (not concurrent) sharers: the first request completes and
    releases; its prefix blocks PARK (refcount 0) and the second request
    still hits them."""
    cfg, params = model
    eng = PagedBatchEngine(
        cfg, params, slots=1, max_len=64, block_size=8, prefix_cache=True
    )
    a = eng.submit(PROMPT_X, max_new_tokens=4)
    eng.run_until_drained()
    assert eng.stats_prefix["hit_tokens"] == 0
    b = eng.submit(PROMPT_Y, max_new_tokens=4)
    eng.run_until_drained()
    assert eng.stats_prefix["hit_tokens"] == 16

    ref = PagedBatchEngine(cfg, params, slots=1, max_len=64, block_size=8)
    a0 = ref.submit(PROMPT_X, max_new_tokens=4)
    ref.run_until_drained()
    b0 = ref.submit(PROMPT_Y, max_new_tokens=4)
    ref.run_until_drained()
    assert eng.result(a) == ref.result(a0)
    assert eng.result(b) == ref.result(b0)


def test_eviction_under_pool_pressure_stays_correct(model):
    """A pool too small to keep every prefix: LRU eviction must unmap
    digests and recycle blocks without corrupting later requests."""
    cfg, params = model
    # 9 usable blocks; every distinct 25-token prompt allocates 4 and parks
    # 3 shareable ones on release — the third distinct prompt must evict.
    eng = PagedBatchEngine(
        cfg, params, slots=1, max_len=64, block_size=8, num_blocks=10,
        prefix_cache=True,
    )
    ref = PagedBatchEngine(cfg, params, slots=1, max_len=64, block_size=8)
    prompts = [
        np.arange(60 + 30 * i, 85 + 30 * i, dtype=np.int32) % 127 + 1
        for i in range(4)
    ] + [np.concatenate([SYS, [40, 41]]).astype(np.int32)]
    for p in prompts:
        r = eng.submit(p, max_new_tokens=4)
        assert r is not None
        eng.run_until_drained()
        r0 = ref.submit(p, max_new_tokens=4)
        ref.run_until_drained()
        assert eng.result(r) == ref.result(r0), p
    assert eng.stats_prefix["evictions"] > 0
    # Invariant: every pool block is accounted for exactly once.
    accounted = set(eng._free_blocks) | set(eng._lru)
    assert len(eng._free_blocks) + len(eng._lru) == len(accounted)
    assert len(accounted) == eng.num_blocks - 1


def test_concurrent_sharers_refcount(model):
    """Two ACTIVE requests share prefix blocks; the blocks stay pinned until
    both finish, then park with refcount 0."""
    cfg, params = model
    eng = PagedBatchEngine(
        cfg, params, slots=2, max_len=64, block_size=8, prefix_cache=True
    )
    a = eng.submit(PROMPT_X, max_new_tokens=12)
    b = eng.submit(PROMPT_Y, max_new_tokens=4)
    shared = [blk for blk, r in eng._block_refs.items() if r >= 2]
    assert len(shared) == 2, eng._block_refs
    eng.run_until_drained()
    assert all(eng._block_refs[b] == 0 for b in shared)
    assert all(b in eng._lru for b in shared)

    ref = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=8)
    a0 = ref.submit(PROMPT_X, max_new_tokens=12)
    b0 = ref.submit(PROMPT_Y, max_new_tokens=4)
    ref.run_until_drained()
    assert eng.result(a) == ref.result(a0)
    assert eng.result(b) == ref.result(b0)


def test_prefix_cache_with_int8_kv(model):
    cfg = tiny_cfg(kv_quant=True)
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    want, _ = run(cfg, params, [PROMPT_X, PROMPT_Y], prefix_cache=False)
    got, eng = run(cfg, params, [PROMPT_X, PROMPT_Y], prefix_cache=True)
    assert got == want
    assert eng.stats_prefix["hit_tokens"] == 16


def test_prefix_cache_under_tp_mesh(model):
    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    want, _ = run(cfg, params, [PROMPT_X, PROMPT_Y], prefix_cache=False)
    got, eng = run(cfg, params, [PROMPT_X, PROMPT_Y], prefix_cache=True, mesh=mesh)
    assert got == want
    assert eng.stats_prefix["hit_tokens"] == 16


def test_prefix_cache_with_sampling_seeded(model):
    """Cached-prefix admission composes with per-request sampling: a seeded
    sampled request produces identical tokens with and without the cache."""
    cfg, params = model
    def go(prefix_cache):
        eng = PagedBatchEngine(
            cfg, params, slots=2, max_len=64, block_size=8,
            prefix_cache=prefix_cache,
        )
        eng.submit(PROMPT_X, max_new_tokens=4)  # warm the prefix map
        eng.run_until_drained()
        r = eng.submit(PROMPT_Y, max_new_tokens=8, temperature=1.0, seed=5)
        eng.run_until_drained()
        return eng.result(r)

    assert go(True) == go(False)


def test_backpressure_with_parked_hits_rolls_back(model):
    """The reviewer scenario: hit blocks parked in the LRU are NOT extra
    allocatable capacity. When pinning the hits leaves too little pool for
    the new blocks, submit must return None (backpressure) with the pins
    rolled back — not crash — and succeed once capacity frees."""
    cfg, params = model
    eng = PagedBatchEngine(
        cfg, params, slots=2, max_len=64, block_size=8, num_blocks=10,
        prefix_cache=True,
    )
    pa = np.arange(60, 85, dtype=np.int32)  # 4 blocks, parks 3 on release
    a = eng.submit(pa, max_new_tokens=4)
    eng.run_until_drained()
    assert a is not None and len(eng._lru) == 3
    # B pins all remaining capacity and stays active.
    b = eng.submit(np.arange(2, 27, dtype=np.int32), max_new_tokens=20)
    assert b is not None
    # C resubmits A's prompt: hits=3 (all parked), needs 1 more — none left.
    c = eng.submit(pa, max_new_tokens=4)
    assert c is None  # backpressure, no crash
    # Pins rolled back: A's parked blocks are back at refcount 0 in the LRU
    # (B's own shared blocks legitimately stay pinned while it runs).
    parked_refs = [eng._block_refs[b] for b in eng._lru]
    assert parked_refs == [0, 0, 0], eng._block_refs
    assert len(eng._lru) == 3, "pins must roll back to parked"
    eng.run_until_drained()  # B completes, frees its blocks
    c = eng.submit(pa, max_new_tokens=4)
    assert c is not None
    eng.run_until_drained()
    ref = PagedBatchEngine(cfg, params, slots=1, max_len=64, block_size=8)
    c0 = ref.submit(pa, max_new_tokens=4)
    ref.run_until_drained()
    assert eng.result(c) == ref.result(c0)
