"""Hierarchical KV prefix cache (ISSUE 18): host-RAM spill tier +
cross-instance prefix fetch over the streamed KV wire.

Covers the tentpole's three tiers end to end — spill-on-evict into the
host arena, restore-on-hit back into the pool, sibling fetch over the
kv_stream protocol — plus the rider satellites: the O(1) FIFO free-block
deque, the conservation invariant across every new block-lifecycle path,
chaos on the fetch leg (a torn fetch falls back to recompute, never a
torn cache), the /debug/prefixes advertisement + fleet digest index, and
the `lws-tpu top --by-tier` breakdown."""

import collections
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.core import faults, metrics
from lws_tpu.serving import kv_host_arena
from lws_tpu.serving import kv_transport as kt
from lws_tpu.serving.kv_host_arena import KVHostArena


def _small_engine(**kw):
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return PagedBatchEngine(cfg, params, max_len=64, block_size=16, **kw)


def _assert_conserved(engine):
    """free, parked, and request-held block sets partition [1, num_blocks),
    computed independently of the engine's own accounting."""
    free = set(engine._free_blocks)
    parked = set(engine._lru)
    live = set()
    for req in engine._active.values():
        live |= set(req.blocks)
    assert not free & parked, "block in free list AND parked LRU"
    assert not free & live, "block in free list AND held by a request"
    assert not parked & live, "block parked AND held by a request"
    assert free | parked | live == set(range(1, engine.num_blocks)), \
        "pool blocks leaked or double-counted"
    acct = engine.pool_accounting()
    assert acct["free"] + acct["live"] + acct["parked"] == engine.num_blocks - 1


def _tier_hits(tier: str) -> float:
    return metrics.REGISTRY.counter_value(
        "serving_prefix_cache_hits_total", {"engine": "paged", "tier": tier})


def _spill_bytes(direction: str) -> float:
    return metrics.REGISTRY.counter_value(
        "serving_kv_spill_bytes_total", {"direction": direction})


PROMPT = np.arange(1, 25, dtype=np.int32)  # 24 tokens: 1 shareable block


def _park_then_evict(engine):
    """Drive the canonical spill sequence: park PROMPT's shareable block,
    fill the pool with two active 4-block requests, then force a 1-block
    admission to evict (and, with an arena, spill) the parked block.
    Returns the fault-free oracle tokens for PROMPT."""
    r = engine.submit(PROMPT, 8)
    assert r is not None
    engine.run_until_drained()
    oracle = engine.result(r)
    assert engine.pool_accounting()["parked"] == 1
    f1 = engine.submit(np.full((24,), 9, np.int32), 40)   # 4 blocks
    f2 = engine.submit(np.full((24,), 11, np.int32), 40)  # 4 blocks
    g = engine.submit(np.arange(30, 38, dtype=np.int32), 8)  # 1 block: evicts
    assert f1 is not None and f2 is not None and g is not None
    _assert_conserved(engine)
    engine.run_until_drained()
    _assert_conserved(engine)
    return oracle


@pytest.fixture
def armed():
    def arm(point: str, spec: str) -> None:
        faults.INJECTOR.arm(point, spec)

    yield arm
    faults.INJECTOR.disarm()


@pytest.fixture
def kv_server():
    s = kt.KVServer(port=0, host="127.0.0.1")
    yield s
    s.close()


# ---------------------------------------------------------------------------
# Satellite: the free-block pool is an O(1) FIFO deque


def test_free_block_pool_is_fifo_deque():
    """The pool must allocate in FIFO order with O(1) ends (the old list's
    pop(0) was O(n) per block): blocks leave from the head in id order, a
    refused oversized allocation leaves the order untouched, and released
    blocks recycle at the tail."""
    engine = _small_engine(slots=2, num_blocks=10)
    assert isinstance(engine._free_blocks, collections.deque)
    assert list(engine._free_blocks) == list(range(1, 10))
    assert engine._alloc_blocks(3) == [1, 2, 3]
    assert list(engine._free_blocks) == [4, 5, 6, 7, 8, 9]
    # Up-front refusal: no partial drain, order preserved.
    assert engine._alloc_blocks(7) is None
    assert list(engine._free_blocks) == [4, 5, 6, 7, 8, 9]
    # A real request's blocks recycle at the TAIL on completion: the next
    # admission still draws the untouched head first (FIFO, not LIFO).
    rid = engine.submit(np.arange(1, 9, dtype=np.int32), 8)  # 1 block: 4
    assert rid is not None
    engine.run_until_drained()
    assert list(engine._free_blocks) == [5, 6, 7, 8, 9, 4]


# ---------------------------------------------------------------------------
# The host arena itself


def test_arena_lru_capacity_oversize_and_roundtrip():
    a8 = {"k": np.arange(8, dtype=np.float32)}     # 32-byte payload
    entry = KVHostArena(1 << 20)
    entry.put(b"probe", a8)
    unit = entry.nbytes  # one entry's packed size (header + payload)

    arena = KVHostArena(2 * unit)  # room for exactly two entries
    assert arena.put(b"a", a8) and arena.put(b"b", a8)
    # get() refreshes LRU position: after touching "a", inserting "c"
    # evicts "b" (the cold end), not "a".
    got = arena.get(b"a")
    np.testing.assert_array_equal(got["k"], a8["k"])
    assert arena.put(b"c", a8)
    assert b"a" in arena and b"c" in arena and b"b" not in arena
    assert arena.digests() == [b"a", b"c"]  # cold -> hot
    # Oversized entry: dropped and COUNTED, arena unchanged.
    big = {"k": np.zeros(4 * unit, dtype=np.float32)}
    assert not arena.put(b"huge", big)
    assert arena.stats()["drops"] == 1 and len(arena) == 2
    # Re-put of an existing digest replaces, never double-counts bytes.
    assert arena.put(b"a", a8)
    assert arena.nbytes == 2 * unit
    assert arena.get(b"missing") is None
    with pytest.raises(ValueError):
        KVHostArena(0)


def test_arena_from_env(monkeypatch):
    monkeypatch.delenv(kv_host_arena.ARENA_MB_ENV, raising=False)
    assert kv_host_arena.from_env() is None
    monkeypatch.setenv(kv_host_arena.ARENA_MB_ENV, "0")
    assert kv_host_arena.from_env() is None
    monkeypatch.setenv(kv_host_arena.ARENA_MB_ENV, "2")
    arena = kv_host_arena.from_env()
    assert arena is not None and arena.capacity == 2_000_000


# ---------------------------------------------------------------------------
# Tentpole (a): spill on evict, restore on hit — conserved, byte-identical


def test_spill_on_evict_then_host_restore_hits_and_conserves():
    arena = KVHostArena(64 << 20)
    engine = _small_engine(slots=4, num_blocks=10, prefix_cache=True,
                           host_arena=arena)
    host_before = _tier_hits("host")
    spill_before = _spill_bytes("spill")
    restore_before = _spill_bytes("restore")
    oracle = _park_then_evict(engine)
    assert engine.stats_prefix["evictions"] == 1
    assert engine.stats_prefix["spills"] == 1
    assert len(arena) == 1
    assert _spill_bytes("spill") > spill_before

    # Resubmit: the prefix map misses (evicted) but the arena restores —
    # a HOST-tier hit, tokens byte-identical to the fault-free oracle.
    r2 = engine.submit(PROMPT, 8)
    assert r2 is not None
    _assert_conserved(engine)
    engine.run_until_drained()
    _assert_conserved(engine)
    assert engine.result(r2) == oracle
    assert engine.stats_prefix["host_hits"] == 1
    assert _tier_hits("host") == host_before + 1
    assert _spill_bytes("restore") > restore_before
    # The restored block is mapped again: a THIRD submit hits in HBM.
    hbm_before = _tier_hits("hbm")
    r3 = engine.submit(PROMPT, 8)
    assert r3 is not None
    engine.run_until_drained()
    assert engine.result(r3) == oracle
    assert _tier_hits("hbm") == hbm_before + 1
    _assert_conserved(engine)


def test_backpressure_rollback_parks_restored_block_and_conserves():
    """The new hazard path: a host-tier restore mid-walk allocates a block,
    then a LATER allocation fails — the rollback must unpin the restored
    block into the LRU (not leak it, not free it while mapped)."""
    arena = KVHostArena(64 << 20)
    engine = _small_engine(slots=6, num_blocks=10, prefix_cache=True,
                           host_arena=arena)
    oracle = _park_then_evict(engine)
    assert len(arena) == 1
    # Occupy the pool: 4 + 2 + 1 live blocks on top of 2 parked -> free=0.
    h1 = engine.submit(np.full((24,), 21, np.int32), 40)     # 4 blocks
    h2 = engine.submit(np.arange(40, 48, dtype=np.int32), 24)  # 2 blocks
    h3 = engine.submit(np.arange(50, 58, dtype=np.int32), 8)   # 1 block
    assert h1 is not None and h2 is not None and h3 is not None
    assert engine.pool_accounting()["free"] == 0
    _assert_conserved(engine)

    # PROMPT needs 4 blocks: the walk restores its block from the arena
    # (evicting a parked block to make room), then the 3-block suffix
    # allocation fails -> full rollback, admission refused.
    refused = engine.submit(PROMPT, 40)
    assert refused is None
    _assert_conserved(engine)
    # The restored block survived the rollback PARKED and still mapped —
    # after the pool drains, the same prompt hits it in the HBM tier.
    assert engine.pool_accounting()["parked"] >= 1
    engine.run_until_drained()
    _assert_conserved(engine)
    hbm_before = engine.stats_prefix["hit_blocks"]
    r = engine.submit(PROMPT, 8)
    assert r is not None
    engine.run_until_drained()
    assert engine.result(r) == oracle
    assert engine.stats_prefix["hit_blocks"] == hbm_before + 1
    assert engine.stats_prefix["host_hits"] == 0  # restore preceded refusal
    _assert_conserved(engine)


def test_arena_full_drop_degrades_to_recompute_and_conserves():
    """An arena too small for even one block: the spill is dropped (counted),
    eviction proceeds, and the resubmitted prompt recomputes — a miss, with
    tokens still byte-identical."""
    arena = KVHostArena(64)  # smaller than any block payload
    engine = _small_engine(slots=4, num_blocks=10, prefix_cache=True,
                           host_arena=arena)
    misses = lambda: metrics.REGISTRY.counter_value(  # noqa: E731
        "serving_prefix_cache_misses_total", {"engine": "paged"})
    oracle = _park_then_evict(engine)
    assert engine.stats_prefix["evictions"] == 1
    assert engine.stats_prefix["spills"] == 0
    assert arena.stats()["drops"] == 1 and len(arena) == 0
    m0 = misses()
    r2 = engine.submit(PROMPT, 8)
    assert r2 is not None
    engine.run_until_drained()
    assert engine.result(r2) == oracle
    assert engine.stats_prefix["host_hits"] == 0
    assert misses() == m0 + 1
    _assert_conserved(engine)


# ---------------------------------------------------------------------------
# Tentpole (b): the fetch_prefix wire leg + the engine's remote tier


def _synth_blocks(n: int):
    """n digest->arrays entries of deterministic float32 payloads."""
    out = {}
    for i in range(n):
        rng = np.random.default_rng(i)
        out[bytes([i]) * 16] = {
            "k": rng.standard_normal((1, 16, 2, 16)).astype(np.float32),
            "v": rng.standard_normal((1, 16, 2, 16)).astype(np.float32),
        }
    return out


def test_fetch_prefix_roundtrip_and_contiguity(kv_server):
    entries = _synth_blocks(3)
    arena = KVHostArena(64 << 20)
    for d, arrays in entries.items():
        assert arena.put(d, arrays)
    kv_server.serve_prefixes(arena.get)
    ep = ("127.0.0.1", kv_server.port)
    d0, d1, d2 = entries
    got = kt.fetch_prefix(ep, [d0, d1, d2])
    assert set(got) == {d0, d1, d2}
    for d in got:
        np.testing.assert_array_equal(got[d]["k"], entries[d]["k"])
        np.testing.assert_array_equal(got[d]["v"], entries[d]["v"])
    # Digest-chain contiguity: the peer serves the contiguous prefix it
    # holds and STOPS at the first miss — a gap never yields later blocks
    # (they would be unusable: block i+1's digest commits to block i).
    assert set(kt.fetch_prefix(ep, [d0, b"\x77" * 16, d2])) == {d0}
    # Nothing held -> explicit empty, not an error.
    assert kt.fetch_prefix(ep, [b"\x55" * 16]) == {}


def test_fetch_prefix_without_provider_is_empty(kv_server):
    assert kt.fetch_prefix(("127.0.0.1", kv_server.port), [b"\x01" * 16]) == {}


def test_remote_source_skips_dead_peer_and_opens_breaker(kv_server):
    entries = _synth_blocks(1)
    (digest, arrays), = entries.items()
    arena = KVHostArena(64 << 20)
    arena.put(digest, arrays)
    kv_server.serve_prefixes(arena.get)
    # Dead candidate first: the source must fail over to the live sibling.
    src = kt.RemotePrefixSource(
        endpoints=[("127.0.0.1", 1), ("127.0.0.1", kv_server.port)],
        timeout=0.2, failure_threshold=1,
    )
    got = src.fetch([digest])
    assert set(got) == {digest}
    # threshold=1: the dead peer's breaker opened on that first failure —
    # the next fetch skips it without dialing (fetch still succeeds).
    assert not src._breakers["127.0.0.1:1"].allow()
    assert set(src.fetch([digest])) == {digest}
    assert src.fetch([]) == {}


def test_remote_fetch_tier_restores_and_matches_oracle(kv_server):
    """Full cross-instance path: sibling A spills into its arena and serves
    it over the KV wire; engine B (no arena) admits the same prompt via a
    REMOTE-tier hit with byte-identical tokens."""
    arena_a = KVHostArena(64 << 20)
    a = _small_engine(slots=4, num_blocks=10, prefix_cache=True,
                      host_arena=arena_a)
    oracle = _park_then_evict(a)
    assert len(arena_a) == 1
    kv_server.serve_prefixes(arena_a.get)

    remote_before = _tier_hits("remote")
    src = kt.RemotePrefixSource(endpoints=[("127.0.0.1", kv_server.port)])
    b = _small_engine(slots=4, num_blocks=10, prefix_cache=True,
                      remote_prefix=src)
    r = b.submit(PROMPT, 8)
    assert r is not None
    _assert_conserved(b)
    b.run_until_drained()
    assert b.result(r) == oracle
    assert b.stats_prefix["remote_hits"] == 1
    assert _tier_hits("remote") == remote_before + 1
    _assert_conserved(b)


# ---------------------------------------------------------------------------
# Satellite: chaos on the sibling-fetch leg — torn fetch NEVER tears the
# cache; it degrades to recompute with byte-identical token streams.


def _sibling_rig(kv_server):
    arena_a = KVHostArena(64 << 20)
    a = _small_engine(slots=4, num_blocks=10, prefix_cache=True,
                      host_arena=arena_a)
    oracle = _park_then_evict(a)
    kv_server.serve_prefixes(arena_a.get)
    src = kt.RemotePrefixSource(endpoints=[("127.0.0.1", kv_server.port)])
    b = _small_engine(slots=4, num_blocks=10, prefix_cache=True,
                      remote_prefix=src)
    return b, oracle


def test_chaos_torn_fetch_falls_back_to_recompute(armed, kv_server):
    """drop:2 tears BOTH fetch attempts (the retry re-serves the whole
    stream): the engine must recompute the prefix — a miss, byte-identical
    tokens, the pool conserved, and no leaked inflight-chunk gauge."""
    b, oracle = _sibling_rig(kv_server)
    misses = lambda: metrics.REGISTRY.counter_value(  # noqa: E731
        "serving_prefix_cache_misses_total", {"engine": "paged"})
    m0 = misses()
    armed("kv.stream.recv_chunk", "drop:2")
    r = b.submit(PROMPT, 8)
    assert r is not None
    b.run_until_drained()
    assert b.result(r) == oracle
    assert b.stats_prefix["remote_hits"] == 0
    assert misses() == m0 + 1
    _assert_conserved(b)
    # The server side released every unacked chunk of the torn streams.
    assert metrics.REGISTRY.gauge_value(
        "serving_kv_stream_inflight_chunks") in (None, 0.0)


def test_chaos_single_drop_retries_whole_stream_then_hits(armed, kv_server):
    """drop:1 tears only the first attempt: the retry replays the stream
    from chunk 0 and the admission still lands a REMOTE-tier hit."""
    b, oracle = _sibling_rig(kv_server)
    armed("kv.stream.recv_chunk", "drop:1")
    r = b.submit(PROMPT, 8)
    assert r is not None
    b.run_until_drained()
    assert b.result(r) == oracle
    assert b.stats_prefix["remote_hits"] == 1
    _assert_conserved(b)


def test_chaos_paced_fetch_leg_stays_byte_identical(armed, kv_server):
    """pace: on the serving leg (a DCN-like slow link) delays but never
    corrupts: the fetch completes as a remote hit with identical tokens."""
    b, oracle = _sibling_rig(kv_server)
    armed("kv.stream.send_chunk", "pace:50")
    r = b.submit(PROMPT, 8)
    assert r is not None
    b.run_until_drained()
    assert b.result(r) == oracle
    assert b.stats_prefix["remote_hits"] == 1
    _assert_conserved(b)


def test_chaos_fetch_site_fault_degrades_to_recompute(armed, kv_server):
    """The bare kv.prefix.fetch raising point: every fetch attempt dies
    before dialing — fetch() absorbs it and the engine recomputes."""
    b, oracle = _sibling_rig(kv_server)
    armed("kv.prefix.fetch", "fail_n_times:4:OSError")
    r = b.submit(PROMPT, 8)
    assert r is not None
    b.run_until_drained()
    assert b.result(r) == oracle
    assert b.stats_prefix["remote_hits"] == 0
    _assert_conserved(b)


# ---------------------------------------------------------------------------
# Satellite: /debug/prefixes advertisement + the fleet digest index


def test_debug_prefixes_endpoint_auth_and_limit():
    from lws_tpu.runtime.telemetry import TelemetryServer

    kv_host_arena.register_prefix_source(
        "test-src",
        lambda: {"block_size": 16,
                 "digests": [b"\xaa" * 16, b"\xbb" * 16],
                 "arena_digests": [b"\xcc" * 16]},
    )
    kv_host_arena.register_fetch_port(12345)
    server = TelemetryServer(port=0, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/prefixes", timeout=10)
        assert err.value.code == 401  # bearer-gated like every debug surface
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/debug/prefixes?limit=abc",
                    headers={"Authorization": "Bearer s3cret"},
                ), timeout=10)
        assert err.value.code == 400  # parse_limit parity with /debug/*
        with urllib.request.urlopen(
            urllib.request.Request(
                f"{base}/debug/prefixes?limit=16",
                headers={"Authorization": "Bearer s3cret"},
            ), timeout=10,
        ) as resp:
            body = json.loads(resp.read().decode())
        assert (b"\xaa" * 16).hex() in body["digests"]
        assert (b"\xcc" * 16).hex() in body["arena_digests"]
        assert body["kv_port"] == 12345
    finally:
        server.stop()
        kv_host_arena.unregister_prefix_source("test-src")
        kv_host_arena.register_fetch_port(None)


def test_dead_prefix_source_is_pruned():
    kv_host_arena.register_prefix_source("dead-src", lambda: None)
    out = kv_host_arena.debug_prefixes()
    assert "dead-src" not in kv_host_arena._PREFIX_SOURCES
    assert isinstance(out["digests"], list)


def test_fleet_prefix_index_merges_and_prefers_arena_tier():
    """The FleetCollector folds /debug/prefixes advertisements into a
    digest -> (instance, host, kv_port) index; for a digest present both
    HBM-resident and arena-backed, the arena copy wins (it's the one the
    default fetch provider actually serves)."""
    from lws_tpu.api.pod import Container, EnvVar, Pod, PodPhase, PodSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.telemetry import TelemetryServer

    both = b"\xd0" * 16  # advertised in BOTH tiers
    kv_host_arena.register_prefix_source(
        "fleet-src",
        lambda: {"block_size": 16, "digests": [both, b"\xd1" * 16],
                 "arena_digests": [both, b"\xd2" * 16]},
    )
    kv_host_arena.register_fetch_port(7070)
    worker = TelemetryServer(port=0)
    worker.start()
    cp = ControlPlane()
    try:
        pod = cp.store.create(Pod(
            meta=new_meta("pfx-w0"),
            spec=PodSpec(containers=[Container(
                name="w", command=["sleep", "1"],
                env=[EnvVar("LWS_TPU_METRICS_PORT", str(worker.port))],
            )]),
        ))
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = "127.0.0.1"
        cp.store.update_status(pod)
        index = cp.fleet.collect_prefix_index()
        assert index["instances"] == 1
        digests = index["digests"]
        assert digests[(b"\xd1" * 16).hex()]["tier"] == "hbm"
        assert digests[(b"\xd2" * 16).hex()]["tier"] == "host"
        assert digests[both.hex()]["tier"] == "host"  # arena copy wins
        entry = digests[both.hex()]
        assert entry["instance"] == "pfx-w0"
        assert (entry["host"], entry["port"]) == ("127.0.0.1", 7070)
        # The RemotePrefixSource-shaped closure resolves the same snapshot.
        lookup = cp.fleet.prefix_lookup()
        assert lookup(both.hex()) == ("127.0.0.1", 7070)
        assert lookup("ff" * 16) is None
    finally:
        worker.stop()
        kv_host_arena.unregister_prefix_source("fleet-src")
        kv_host_arena.register_fetch_port(None)


def test_engine_registers_prefix_snapshot_weakly():
    """A prefix-cached engine self-registers its digest snapshot; once the
    engine is collected the provider answers None and is pruned."""
    arena = KVHostArena(64 << 20)
    engine = _small_engine(slots=2, num_blocks=10, prefix_cache=True,
                           host_arena=arena)
    name = engine._prefix_source_name
    assert name in kv_host_arena._PREFIX_SOURCES
    engine.submit(PROMPT, 8)
    engine.run_until_drained()
    snap = kv_host_arena._PREFIX_SOURCES[name]()
    assert snap is not None and len(snap["digests"]) == 1
    del engine
    import gc

    gc.collect()
    kv_host_arena.debug_prefixes()
    assert name not in kv_host_arena._PREFIX_SOURCES


# ---------------------------------------------------------------------------
# Satellite: `lws-tpu top --by-tier` renders the hierarchy breakdown


TIERED_EXPOSITION = """\
# HELP serving_requests_total x
# TYPE serving_requests_total counter
serving_requests_total{engine="paged",instance="w0"} 20.0
serving_requests_total{engine="paged",instance="w1"} 10.0
# HELP serving_prefix_cache_hits_total x
# TYPE serving_prefix_cache_hits_total counter
serving_prefix_cache_hits_total{engine="paged",instance="w0",tier="hbm"} 6.0
serving_prefix_cache_hits_total{engine="paged",instance="w0",tier="host"} 3.0
serving_prefix_cache_hits_total{engine="paged",instance="w0",tier="remote"} 1.0
serving_prefix_cache_hits_total{engine="paged",instance="w1"} 5.0
# HELP serving_prefix_cache_misses_total x
# TYPE serving_prefix_cache_misses_total counter
serving_prefix_cache_misses_total{engine="paged",instance="w0"} 10.0
serving_prefix_cache_misses_total{engine="paged",instance="w1"} 5.0
"""


def test_top_by_tier_splits_pfx_and_keeps_aggregate():
    from lws_tpu.cli import _top_rows, render_top
    from lws_tpu.core.metrics import parse_exposition

    fams = parse_exposition(TIERED_EXPOSITION)
    rows = _top_rows(fams)
    w0 = rows[("w0", "paged")]
    # Aggregate PFX survives the tier split; per-tier fields ride along.
    assert w0["pfx_hits"] == 10.0
    assert (w0["pfx_hits_hbm"], w0["pfx_hits_host"], w0["pfx_hits_remote"]) \
        == (6.0, 3.0, 1.0)
    # Legacy tier-less series (older worker mid-rollout) folds as hbm.
    w1 = rows[("w1", "paged")]
    assert w1["pfx_hits"] == 5.0 and w1["pfx_hits_hbm"] == 5.0

    plain = render_top(fams)
    assert "PFX%" in plain and "h%" not in plain
    tiered = render_top(fams, by_tier=True)
    header = tiered.splitlines()[1]
    assert "h%" in header and "H%" in header and "R%" in header
    w0_row = next(l for l in tiered.splitlines() if l.startswith("w0"))
    # 20 lookups: 10 hits = 50% PFX, split 30% hbm / 15% host / 5% remote.
    for cell in ("50%", "30%", "15%", "5%"):
        assert cell in w0_row, (cell, w0_row)
    w1_row = next(l for l in tiered.splitlines() if l.startswith("w1"))
    assert "50%" in w1_row and "0%" in w1_row
