"""Continuous profiling + capacity accounting plane (ISSUE 7): the
span-tagged stack sampler, KV-pool/HBM occupancy gauges, the
`/debug/profile` surfaces (worker + API server + fleet merge), the
flight-recorder profile embed, `lws-tpu profile`, and the paged-engine
block-conservation regression.

Sampling is driven deterministically where an assertion depends on WHERE a
sample lands: `sample_once(frames=...)` takes injected frame dicts, and the
span-attribution tests park a real thread inside the span being attributed
before sampling it — no statistical flakes."""

import json
import sys
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.core import metrics, profile, trace
from lws_tpu.core.profile import (
    StackSampler,
    fold_by_span,
    merge_collapsed,
    top_frames,
)
from tests.test_dns_metrics import parse_exposition

T0 = 1000.0


def _parked_thread(body_name: str, setup=None):
    """Start a thread parked inside `setup()` (a context manager factory,
    e.g. a span or phase tag) until released; returns (thread, entered,
    release). The parked frames are what sample_once sees."""
    import contextlib

    entered = threading.Event()
    release = threading.Event()

    def body():
        ctx = setup() if setup is not None else contextlib.nullcontext()
        with ctx:
            entered.set()
            release.wait(10)

    t = threading.Thread(target=body, name=body_name, daemon=True)
    t.start()
    assert entered.wait(10)
    return t, entered, release


# ---------------------------------------------------------------------------
# StackSampler unit behavior


def test_sampler_folds_thread_stacks_and_excludes_itself():
    sampler = StackSampler(hz=997.0)
    t, _, release = _parked_thread("park-plain")
    try:
        frames = {t.ident: sys._current_frames()[t.ident]}
        n = sampler.sample_once(frames=frames)
        assert n == 1
        snap = sampler.snapshot()
        assert snap["samples"] == 1 and snap["hz"] == 997.0
        (stack, count), = snap["stacks"]
        assert count == 1
        assert "threading:" in stack  # the Event.wait frames fold in
        # The caller's own thread is excluded even when its frame rides in.
        own = sys._current_frames()[threading.get_ident()]
        assert sampler.sample_once(frames={threading.get_ident(): own}) == 0
    finally:
        release.set()
        t.join()


def test_sampler_tags_samples_with_span_stack():
    enabled = trace.TRACER.enabled
    trace.TRACER.enabled = True
    sampler = StackSampler()
    t, _, release = _parked_thread(
        "park-span", setup=lambda: trace.span("serve.decode_consume", steps=1)
    )
    try:
        frames = {t.ident: sys._current_frames()[t.ident]}
        assert sampler.sample_once(frames=frames) == 1
        (stack, _), = sampler.snapshot()["stacks"]
        assert stack.startswith("span:serve.decode_consume;")
    finally:
        release.set()
        t.join()
        trace.TRACER.enabled = enabled


def test_sampler_tags_samples_with_phase_tags():
    sampler = StackSampler()
    t, _, release = _parked_thread(
        "park-phase", setup=lambda: profile.phase("unit.phase")
    )
    try:
        frames = {t.ident: sys._current_frames()[t.ident]}
        assert sampler.sample_once(frames=frames) == 1
        (stack, _), = sampler.snapshot()["stacks"]
        assert stack.startswith("span:unit.phase;")
        release.set()
        t.join()
        # The tag popped with the context: a fresh sample is untagged.
        assert profile.phase_names(t.ident) == []
    finally:
        release.set()


def test_sampler_bounded_table_drops_and_counts():
    sampler = StackSampler(max_stacks=1)
    t1, _, r1 = _parked_thread("park-a")
    t2, _, r2 = _parked_thread(
        "park-b", setup=lambda: profile.phase("unit.bound")
    )
    try:
        before = metrics.REGISTRY.counter_value("lws_profile_stacks_dropped_total")
        all_frames = sys._current_frames()
        assert sampler.sample_once(frames={t1.ident: all_frames[t1.ident]}) == 1
        # A NOVEL stack past the cap is dropped and counted; the known one
        # keeps counting.
        sampler.sample_once(frames={t2.ident: all_frames[t2.ident]})
        sampler.sample_once(frames={t1.ident: all_frames[t1.ident]})
        snap = sampler.snapshot()
        assert len(snap["stacks"]) == 1 and snap["dropped_stacks"] == 1
        assert snap["stacks"][0][1] == 2
        after = metrics.REGISTRY.counter_value("lws_profile_stacks_dropped_total")
        assert after == before + 1
    finally:
        r1.set(), r2.set()
        t1.join(), t2.join()


def test_sampler_threaded_mode_samples_and_stops():
    sampler = StackSampler(hz=500.0)
    t, _, release = _parked_thread("park-live")
    try:
        sampler.start()
        assert sampler.running
        import time

        deadline = time.monotonic() + 10
        while sampler.snapshot()["samples"] == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sampler.snapshot()["samples"] > 0
    finally:
        sampler.stop()
        release.set()
        t.join()
    assert not sampler.running
    # collapsed() is flamegraph.pl input: "frame;frame count" lines.
    for line in sampler.collapsed().splitlines():
        stack, count = line.rsplit(" ", 1)
        assert ";" in stack or ":" in stack
        assert int(count) > 0


def test_fold_helpers_and_merge_collapsed():
    stacks = [
        ["span:serve.decode_consume;mod:f;mod:g", 3],
        ["span:serve.request;span:serve.decode_consume;mod:f;mod:h", 2],
        ["mod:f;mod:g", 5],
    ]
    # Innermost span tag wins (the phase actually executing).
    assert fold_by_span(stacks) == {"serve.decode_consume": 5, "-": 5}
    assert top_frames(stacks) == {"mod:g": 8, "mod:h": 2}
    merged = merge_collapsed([
        ({"instance": "w0", "role": "decode"}, {"stacks": stacks[:1]}),
        ({"instance": "cp"}, {"stacks": stacks[2:]}),
    ])
    lines = merged.splitlines()
    assert lines[0] == "instance:w0;role:decode;span:serve.decode_consume;mod:f;mod:g 3"
    assert lines[1] == "instance:cp;mod:f;mod:g 5"


def test_record_device_memory_is_cpu_safe():
    # On the CPU test backend allocator stats are absent: the refresh must
    # be a quiet no-op, never a scrape-handler exception.
    stats = profile.record_device_memory()
    assert isinstance(stats, list)
    for d in stats:
        assert set(d) == {"device", "in_use", "limit", "peak"}


# ---------------------------------------------------------------------------
# Flight recorder integration: every dump ships a profile snapshot


def test_watchdog_dump_embeds_profile_snapshot():
    from lws_tpu.core.flightrecorder import FlightRecorder, StallRule, Watchdog

    # Ensure the process PROFILER holds at least one stack to embed.
    t, _, release = _parked_thread("park-dump")
    try:
        frames = {t.ident: sys._current_frames()[t.ident]}
        profile.PROFILER.sample_once(frames=frames)
    finally:
        release.set()
        t.join()
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=[
        StallRule("decode_ring_stall", "decode_ring:*", stall_after_s=5.0)
    ])
    fr.beat("decode_ring:paged", progress=1, depth=2, now=T0)
    assert "decode_ring_stall" in wd.check_now(now=T0 + 30)
    dump = wd.last_dump
    assert dump["profile"]["samples"] > 0
    assert dump["profile"]["stacks"], "dump carries no collapsed stacks"
    json.dumps(dump)  # the bundle stays JSON-serializable with the embed


# ---------------------------------------------------------------------------
# Paged engine capacity accounting: gauges + block conservation


def _small_engine(**kw):
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return PagedBatchEngine(cfg, params, max_len=64, block_size=16, **kw)


def _assert_conserved(engine):
    """The conservation invariant, computed INDEPENDENTLY of the engine's
    own accounting: free, parked, and request-held block sets must
    partition [1, num_blocks) — and the gauges must agree."""
    free = set(engine._free_blocks)
    parked = set(engine._lru)
    live = set()
    for req in engine._active.values():
        live |= set(req.blocks)
    assert not free & parked, "block in free list AND parked LRU"
    assert not free & live, "block in free list AND held by a request"
    assert not parked & live, "block parked AND held by a request"
    assert free | parked | live == set(range(1, engine.num_blocks)), \
        "pool blocks leaked or double-counted"
    acct = engine.pool_accounting()
    assert acct["free"] == len(free) and acct["parked"] == len(parked)
    assert acct["live"] == len(live)
    assert acct["free"] + acct["live"] + acct["parked"] == engine.num_blocks - 1


def _gauge(state):
    return metrics.REGISTRY.gauge_value(
        "serving_kv_pool_blocks", {"engine": "paged", "state": state}
    )


def test_paged_block_accounting_conserved_across_prefix_lifecycle():
    """The ISSUE's pinned regression: free + live + parked == num_blocks - 1
    across prefix-cache admission, LRU parking, eviction-under-pressure, and
    backpressure rollback (the paged_engine.py pin-before-alloc path whose
    naive pre-check would double-count LRU-parked hit blocks)."""
    engine = _small_engine(slots=4, num_blocks=10, prefix_cache=True)
    _assert_conserved(engine)
    prompt = np.arange(1, 25, dtype=np.int32)  # 24 tokens: 1 shareable block

    # Admission + completion parks the shareable block in the LRU.
    rid = engine.submit(prompt, 8)
    assert rid is not None
    _assert_conserved(engine)
    engine.run_until_drained()
    _assert_conserved(engine)
    assert engine.pool_accounting()["parked"] == 1

    # A second admission HITS the parked block (pin from LRU) — the :805
    # hazard path: pins must roll into live exactly once.
    rid2 = engine.submit(prompt, 8)
    assert rid2 is not None
    assert engine.stats_prefix["hit_blocks"] == 1
    _assert_conserved(engine)

    # Backpressure: keep admitting prefix-hitting 4-block requests until the
    # pool refuses one — the refusal path must roll the hit-block pins back
    # (the pin-before-alloc shape whose pre-check double-count this pins).
    refused = engine.submit(np.arange(1, 25, dtype=np.int32), 40)  # 4 blocks
    while refused is not None:
        _assert_conserved(engine)
        refused = engine.submit(np.arange(1, 25, dtype=np.int32), 40)
    assert refused is None
    _assert_conserved(engine)
    engine.run_until_drained()
    _assert_conserved(engine)

    # Eviction under pressure: distinct prompts park distinct prefix blocks,
    # then a fill admission forces LRU eviction.
    for seed in (3, 5, 7):
        p = np.full((24,), seed, dtype=np.int32)
        r = engine.submit(p, 8)
        assert r is not None
        engine.run_until_drained()
        _assert_conserved(engine)
    evictions_before = engine.stats_prefix["evictions"]
    filled = []
    r = engine.submit(np.arange(30, 54, dtype=np.int32), 40)
    while r is not None:
        filled.append(r)
        _assert_conserved(engine)
        r = engine.submit(np.arange(30, 54, dtype=np.int32), 40)
    assert engine.stats_prefix["evictions"] > evictions_before
    _assert_conserved(engine)
    engine.run_until_drained()
    _assert_conserved(engine)

    # The gauges agree with the final accounting (and sum to the pool).
    acct = engine.pool_accounting()
    assert _gauge("free") == acct["free"]
    assert _gauge("live") == acct["live"]
    assert _gauge("parked") == acct["parked"]
    assert _gauge("free") + _gauge("live") + _gauge("parked") == 9.0


def test_paged_block_accounting_survives_pipeline_rollback():
    """discard() abandons in-flight chunks without committing — block
    ownership must be unaffected (blocks travel with requests, never with
    chunks), and the drain after the rollback still conserves."""
    engine = _small_engine(slots=2, pipeline_depth=2)
    rid = engine.submit(np.arange(1, 25, dtype=np.int32), 8)
    assert rid is not None
    engine.step_n(2)  # a chunk rides the ring, unconsumed
    assert len(engine._pipeline) >= 1
    engine._pipeline.discard()
    _assert_conserved(engine)
    engine.run_until_drained()
    _assert_conserved(engine)
    assert engine.pool_accounting()["live"] == 0


def test_prefix_cache_hit_miss_evict_counters():
    reg = metrics.REGISTRY
    labels = {"engine": "paged"}

    def hits():
        # Hits split by tier since the spill hierarchy landed; the pool-only
        # engine here lands every hit in the hbm tier, but sum all three so
        # this test pins the AGGREGATE contract.
        return sum(
            reg.counter_value("serving_prefix_cache_hits_total",
                              {"engine": "paged", "tier": t})
            for t in ("hbm", "host", "remote"))

    h0 = hits()
    hbm0 = reg.counter_value("serving_prefix_cache_hits_total",
                             {"engine": "paged", "tier": "hbm"})
    m0 = reg.counter_value("serving_prefix_cache_misses_total", labels)
    engine = _small_engine(slots=2, num_blocks=8, prefix_cache=True)
    prompt = np.arange(1, 25, dtype=np.int32)
    engine.submit(prompt, 8)
    engine.run_until_drained()
    # First sight of the prefix: its one shareable block was a miss.
    assert reg.counter_value("serving_prefix_cache_misses_total", labels) == m0 + 1
    engine.submit(prompt, 8)
    engine.run_until_drained()
    assert hits() == h0 + 1
    assert reg.counter_value("serving_prefix_cache_hits_total",
                             {"engine": "paged", "tier": "hbm"}) == hbm0 + 1
    e0 = reg.counter_value("serving_prefix_cache_evictions_total", labels)
    # Pressure the pool so an allocation must reclaim the parked block:
    # 7 allocatable, 1 parked. A 4-block fill leaves 2 free; a 3-block
    # admission then needs the parked block — eviction.
    assert engine.submit(np.full((24,), 9, dtype=np.int32), 40) is not None
    assert engine.submit(np.full((24,), 11, dtype=np.int32), 24) is not None
    assert reg.counter_value(
        "serving_prefix_cache_evictions_total", labels) == e0 + 1
    assert engine.stats_prefix["evictions"] == 1
    engine.run_until_drained()


def test_batch_engine_reports_slot_occupancy():
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.batch_engine import BatchEngine

    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    engine = BatchEngine(cfg, params, slots=2, max_len=64)
    engine.submit(np.arange(1, 9, dtype=np.int32), 4)
    assert metrics.REGISTRY.gauge_value(
        "serving_active_slots", {"engine": "batch"}) == 1.0
    engine.run_until_drained()
    assert metrics.REGISTRY.gauge_value(
        "serving_active_slots", {"engine": "batch"}) == 0.0


# ---------------------------------------------------------------------------
# /debug/profile HTTP surfaces: validation + auth parity


def test_worker_debug_profile_validation_and_formats():
    from lws_tpu.runtime.telemetry import TelemetryServer

    profile.PROFILER.clear()
    t, _, release = _parked_thread("park-http")
    try:
        frames = {t.ident: sys._current_frames()[t.ident]}
        profile.PROFILER.sample_once(frames=frames)
    finally:
        release.set()
        t.join()
    server = TelemetryServer(port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        for bad in ("?limit=abc", "?limit=-5", "?limit=1.5", "?format=xml",
                    "?limit=3&format=svg"):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/debug/profile{bad}", timeout=10)
            assert err.value.code == 400, bad
        with urllib.request.urlopen(f"{base}/debug/profile?limit=8", timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["samples"] >= 1 and body["stacks"]
        with urllib.request.urlopen(
            f"{base}/debug/profile?format=collapsed", timeout=10
        ) as resp:
            text = resp.read().decode()
            assert resp.headers.get("Content-Type") == "text/plain"
        assert text.strip() and text.splitlines()[0].rsplit(" ", 1)[1].isdigit()
    finally:
        server.stop()


def test_worker_debug_profile_token_parity():
    from lws_tpu.runtime.telemetry import TelemetryServer

    server = TelemetryServer(port=0, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/profile", timeout=10)
        assert err.value.code == 401
        req = urllib.request.Request(
            f"{base}/debug/profile",
            headers={"Authorization": "Bearer s3cret"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
    finally:
        server.stop()


def test_api_server_debug_profile_validation_and_auth_parity():
    from lws_tpu.core.auth import TokenAuth, TokenEntry
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        for path in ("/debug/profile", "/debug/profile/fleet"):
            for bad in ("?limit=zz", "?format=flame"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(f"{base}{path}{bad}", timeout=10)
                assert err.value.code == 400, (path, bad)
            with urllib.request.urlopen(f"{base}{path}?limit=4", timeout=10) as resp:
                assert resp.status == 200
    finally:
        api.stop()
    # Same bearer gating as every other /debug/* endpoint.
    auth = TokenAuth([TokenEntry("tok123", "admin", "admin")])
    api = ApiServer(cp, port=0, auth=auth)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/profile", timeout=10)
        assert err.value.code == 401
        req = urllib.request.Request(
            f"{base}/debug/profile",
            headers={"Authorization": "Bearer tok123"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
    finally:
        api.stop()


# ---------------------------------------------------------------------------
# lws-tpu profile renderer + CLI


PROFILE_SNAP = {
    "enabled": True, "hz": 67.0, "samples": 10, "dropped_stacks": 0,
    "stacks": [
        ["span:serve.decode_consume;mod:f;mod:g", 6],
        ["mod:f;mod:idle", 4],
    ],
}


def test_render_profile_tables():
    from lws_tpu.cli import render_profile

    frame = render_profile([("w0", PROFILE_SNAP)], top_n=5)
    assert "PROFILE  instances=1  samples=10  sampling=on" in frame
    span_row = next(l for l in frame.splitlines() if "serve.decode_consume" in l)
    assert span_row.startswith("w0") and "60%" in span_row
    assert "TOP OF STACK" in frame
    top_row = next(l for l in frame.splitlines() if "mod:g" in l)
    assert "6" in top_row and "60%" in top_row


def test_cmd_profile_one_shot_and_fleet(capsys):
    from lws_tpu import cli
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    profile.PROFILER.clear()
    t, _, release = _parked_thread("park-cli")
    try:
        frames = {t.ident: sys._current_frames()[t.ident]}
        profile.PROFILER.sample_once(frames=frames)
    finally:
        release.set()
        t.join()
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        assert cli.main(["profile", "--server", f"127.0.0.1:{api.port}"]) == 0
        out = capsys.readouterr().out
        assert "PROFILE" in out and "TOP OF STACK" in out
        assert "threading:" in out  # the parked thread's frames fold in
        assert cli.main(
            ["profile", "--fleet", "--server", f"127.0.0.1:{api.port}"]
        ) == 0
        out = capsys.readouterr().out
        assert "control-plane" in out  # the CP's own profile rides the merge
        assert cli.main(
            ["profile", "--collapsed", "--server", f"127.0.0.1:{api.port}"]
        ) == 0
        out = capsys.readouterr().out
        assert out.splitlines()[0].rsplit(" ", 1)[1].isdigit()
    finally:
        api.stop()


def test_top_renders_kv_occupancy_and_prefix_hit_rate():
    from lws_tpu.cli import _top_rows, render_top
    from lws_tpu.core.metrics import parse_exposition as parse_prod

    exposition = """\
# HELP serving_requests_total x
# TYPE serving_requests_total counter
serving_requests_total{engine="paged",instance="w0"} 10.0
# HELP serving_kv_pool_blocks x
# TYPE serving_kv_pool_blocks gauge
serving_kv_pool_blocks{engine="paged",instance="w0",state="free"} 2.0
serving_kv_pool_blocks{engine="paged",instance="w0",state="live"} 6.0
serving_kv_pool_blocks{engine="paged",instance="w0",state="parked"} 0.0
# HELP serving_prefix_cache_hits_total x
# TYPE serving_prefix_cache_hits_total counter
serving_prefix_cache_hits_total{engine="paged",instance="w0"} 3.0
# HELP serving_prefix_cache_misses_total x
# TYPE serving_prefix_cache_misses_total counter
serving_prefix_cache_misses_total{engine="paged",instance="w0"} 1.0
"""
    fams = parse_prod(exposition)
    rows = _top_rows(fams)
    r = rows[("w0", "paged")]
    assert r["kv_live"] == 6.0 and r["kv_free"] == 2.0
    assert r["pfx_hits"] == 3.0 and r["pfx_misses"] == 1.0
    frame = render_top(fams)
    assert "KV%" in frame and "PFX%" in frame
    row = next(l for l in frame.splitlines() if l.startswith("w0"))
    assert "75%" in row  # 6 live / 8 pool, and 3/4 prefix hits



# ---------------------------------------------------------------------------
# End-to-end: decode workload + sampler + worker /debug/profile + fleet
# merge + pool-gauge conservation on the merged exposition (the ISSUE's
# acceptance proof).


def _make_worker_pod(name: str, port: int, role: str | None = None):
    from lws_tpu.api.pod import Container, EnvVar, Pod, PodSpec
    from lws_tpu.core.store import new_meta

    pod = Pod(
        meta=new_meta(name),
        spec=PodSpec(containers=[Container(
            name="w",
            command=["sleep", "1"],
            env=[EnvVar("LWS_TPU_METRICS_PORT", str(port))],
        )]),
    )
    if role is not None:
        from lws_tpu.api import disagg

        pod.meta.labels[disagg.DS_ROLE_LABEL_KEY] = role
    return pod


def test_profile_plane_end_to_end():
    from lws_tpu.api.pod import PodPhase
    from lws_tpu.core.flightrecorder import FlightRecorder, StallRule, Watchdog
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer
    from lws_tpu.runtime.telemetry import TelemetryServer

    enabled, rate = trace.TRACER.enabled, trace.TRACER.sample_rate
    trace.TRACER.enabled, trace.TRACER.sample_rate = True, 1.0
    profile.PROFILER.clear()
    profile.PROFILER.hz = 499.0
    engine = _small_engine(slots=2, num_blocks=9, pipeline_depth=2)
    worker = TelemetryServer(port=0)
    worker.start()
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        # --- a decode workload with the sampler ON --------------------------
        profile.PROFILER.start()
        rid = engine.submit(np.arange(1, 25, dtype=np.int32), 24)
        assert rid is not None
        for _ in range(4):
            engine.step_n(4)
        # Deterministic serve.decode_consume attribution: a chunk whose
        # commit parks inside the consume span while we sample it — the
        # sampler never has to win a race with a microsecond window.
        entered, release = threading.Event(), threading.Event()

        def slow_commit(host):
            entered.set()
            release.wait(10)

        engine._pipeline.push(0, np.zeros((0, engine.slots), np.int32),
                              slow_commit)
        flusher = threading.Thread(target=engine._pipeline.flush, daemon=True)
        flusher.start()
        assert entered.wait(10)
        frames = dict(sys._current_frames())
        # Sample the parked consume repeatedly: its count must rank above
        # the hz-loop's one-off noise stacks in every limit-truncated view
        # (the worker endpoint, the fleet merge, the watchdog dump).
        for _ in range(50):
            assert profile.PROFILER.sample_once(
                frames={flusher.ident: frames[flusher.ident]}
            ) == 1
        release.set()
        flusher.join(10)
        engine.run_until_drained()
        profile.PROFILER.stop()
        snap = profile.PROFILER.snapshot()
        assert snap["samples"] > 0
        consume_stacks = [
            s for s, _ in snap["stacks"]
            if s.split(";")[0] == "span:serve.decode_consume"
        ]
        assert consume_stacks, "no stack attributed to serve.decode_consume"

        # --- (a) the worker's /debug/profile serves those stacks ------------
        base = f"http://127.0.0.1:{worker.port}"
        with urllib.request.urlopen(f"{base}/debug/profile", timeout=10) as resp:
            via_worker = json.loads(resp.read().decode())
        assert any(
            s.split(";")[0] == "span:serve.decode_consume"
            for s, _ in via_worker["stacks"]
        )

        # --- (b) a tripped watchdog's dump embeds the profile ---------------
        fr = FlightRecorder()
        wd = Watchdog(recorder=fr, rules=[
            StallRule("decode_ring_stall", "decode_ring:*", stall_after_s=5.0)
        ])
        fr.beat("decode_ring:paged", progress=1, depth=1, now=T0)
        assert "decode_ring_stall" in wd.check_now(now=T0 + 60)
        assert any(
            s.split(";")[0] == "span:serve.decode_consume"
            for s, _ in wd.last_dump["profile"]["stacks"]
        ), "the stall dump does not ship the window's profile"

        # --- fleet wiring: pod -> scrape -> merged surfaces ------------------
        pod = cp.store.create(_make_worker_pod("prof-w0", worker.port,
                                               role="decode"))
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = "127.0.0.1"
        cp.store.update_status(pod)

        # Keep one request live so the pool gauges show live > 0 on the
        # merged exposition.
        rid2 = engine.submit(np.arange(1, 25, dtype=np.int32), 24)
        assert rid2 is not None

        # (a, fleet) /debug/profile/fleet carries the worker's span-tagged
        # stacks under its instance label, both JSON and collapsed.
        api_base = f"http://127.0.0.1:{api.port}"
        with urllib.request.urlopen(
            f"{api_base}/debug/profile/fleet", timeout=10
        ) as resp:
            fleet_profiles = json.loads(resp.read().decode())
        by_instance = {
            e["labels"]["instance"]: e["profile"]
            for e in fleet_profiles["instances"]
        }
        assert {"control-plane", "prof-w0"} <= set(by_instance)
        assert by_instance["prof-w0"]["samples"] > 0
        assert any(
            s.split(";")[0] == "span:serve.decode_consume"
            for s, _ in by_instance["prof-w0"]["stacks"]
        )
        with urllib.request.urlopen(
            f"{api_base}/debug/profile/fleet?format=collapsed", timeout=10
        ) as resp:
            collapsed = resp.read().decode()
        assert any(
            line.startswith("instance:prof-w0;role:decode;")
            for line in collapsed.splitlines()
        )

        # --- (c) pool-state conservation on the MERGED fleet exposition -----
        merged = cp.fleet.render_fleet(force=True)
        fams = parse_exposition(merged)
        states = {
            labels["state"]: v
            for _, labels, v in fams["serving_kv_pool_blocks"]["samples"]
            if labels.get("instance") == "prof-w0"
        }
        assert set(states) == {"free", "live", "parked"}
        assert sum(states.values()) == engine.num_blocks - 1
        assert states["live"] > 0
        engine.run_until_drained()
    finally:
        profile.PROFILER.stop()
        api.stop()
        worker.stop()
        trace.TRACER.enabled, trace.TRACER.sample_rate = enabled, rate
