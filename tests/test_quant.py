"""int8 weight quantization: numerical parity and serving integration.

Reference anchor: the reference's serving-density story is workload-side
(vLLM quantization flags, docs/examples/vllm/TPU/lws.yaml); here the compute
plane is native, so quantized weights are a framework feature with tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.models.llama import LlamaConfig, forward, init_params
from lws_tpu.models.quant import (
    QuantizedArray,
    dequantize_array,
    embed_lookup,
    matmul,
    quantize_array,
    quantize_params,
    quantized_bytes,
)


def small_cfg(**kw):
    defaults = dict(
        vocab_size=128,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=64,
        dtype=jnp.float32,
        param_dtype=jnp.float32,
        remat=False,
    )
    defaults.update(kw)
    return LlamaConfig(**defaults)


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(0), (32, 48))
    qa = quantize_array(w)
    back = dequantize_array(qa, jnp.float32)
    # Symmetric int8: max error is scale/2 = amax/254 per column.
    col_amax = np.max(np.abs(np.asarray(w)), axis=0)
    assert np.all(np.abs(np.asarray(back - w)) <= col_amax / 254 + 1e-7)


def test_quantized_matmul_matches_scaled_dequant():
    """(x @ q) * scale must equal x @ dequant(q) exactly (per-output-channel
    scales commute with the contraction)."""
    x = jax.random.normal(jax.random.key(1), (4, 32))
    w = jax.random.normal(jax.random.key(2), (32, 48))
    qa = quantize_array(w)
    got = matmul(x, qa, jnp.float32)
    want = x @ dequantize_array(qa, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_embed_lookup_quantized():
    table = jax.random.normal(jax.random.key(3), (16, 8))
    qa = quantize_array(table, contract_axis=-1)
    toks = jnp.array([[0, 5, 15]])
    got = embed_lookup(qa, toks, jnp.float32)
    want = dequantize_array(qa, jnp.float32, contract_axis=-1)[toks]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("moe", [False, True])
def test_forward_parity_quantized(moe):
    """Quantized forward tracks the f32 forward: same top-1 tokens for a
    generic random model, logits close in normalized terms."""
    cfg = small_cfg(n_experts=4 if moe else 0, top_k=2)
    params = init_params(cfg, jax.random.key(0))
    qparams = quantize_params(params)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits_f, _ = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    logits_q, _ = jax.jit(lambda p, t: forward(p, t, cfg))(qparams, tokens)
    lf, lq = np.asarray(logits_f), np.asarray(logits_q)
    # Normalized error small and argmax agreement high.
    rel = np.abs(lq - lf).mean() / (np.abs(lf).mean() + 1e-9)
    assert rel < 0.05, rel
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree > 0.9, agree


def test_quantized_bytes_counts_actual_widths():
    cfg = small_cfg()
    params = init_params(cfg, jax.random.key(0))
    full = quantized_bytes(params)
    quant = quantized_bytes(quantize_params(params))
    # f32 -> int8 + f32 scales: better than 3x smaller for these shapes.
    assert quant < full / 3


def test_engine_decode_with_quantized_weights():
    """End-to-end: Engine prefill + decode_n runs on quantized weights and
    int8 KV together; the on-device scan loop produces EXACTLY the same
    greedy tokens as chained single decode steps on the same quantized
    model (internal consistency of the two decode paths)."""
    from lws_tpu.serving import Engine

    cfg = small_cfg(kv_quant=True)
    params = init_params(cfg, jax.random.key(0))
    qparams = quantize_params(params)
    prompt = jax.random.randint(jax.random.key(2), (2, 8), 0, cfg.vocab_size)

    eng_q = Engine(cfg, qparams, batch_size=2, max_len=32)
    tok, cache = eng_q.prefill(prompt)
    tok_n, cache_n, toks = eng_q.decode_n(tok, cache, 4)
    assert toks.shape == (2, 4)
    assert int(cache_n.pos) == 8 + 4

    # Same engine, single-step path: greedy tokens must match the scan
    # path token for token.
    tok2, cache2 = eng_q.prefill(prompt)
    np.testing.assert_array_equal(np.asarray(tok2), np.asarray(tok))
    singles = []
    for _ in range(4):
        tok2, cache2 = eng_q.decode(tok2, cache2)
        singles.append(np.asarray(tok2))
    np.testing.assert_array_equal(np.stack(singles, axis=1), np.asarray(toks))


def test_quantized_params_scan_path():
    """Quantized layer stacks flow through the lax.scan layer loop (pytree
    slicing of QuantizedArray leaves)."""
    cfg = small_cfg(unroll_cached_layers=False)
    qparams = quantize_params(init_params(cfg, jax.random.key(0)))
    lp = jax.tree.map(lambda a: a[0], qparams["layers"])
    assert isinstance(lp["wq"], QuantizedArray)
    assert lp["wq"].q.shape == (cfg.d_model, cfg.n_heads * cfg.head_dim)
    tokens = jnp.zeros((1, 4), jnp.int32)
    logits, _ = jax.jit(lambda p, t: forward(p, t, cfg))(qparams, tokens)
    assert logits.shape == (1, 4, cfg.vocab_size)
