"""Instrumented-lock race harness (lws_tpu.testing) driven against the
three shared-state hot spots the vet tentpole names: the decode dispatch
ring, the KV server backlog/counters, and the FleetCollector
single-flight cache.

Each surface gets a clean run (real locks, thread churn, detector must
stay silent) and the pipeline additionally gets the SEEDED MUTATION run:
the `with self._lock:` discipline of serving/pipeline.py is simulated
away by swapping the instance lock for NullLock, and the detector must
deterministically report the race — lockset detection needs two threads
with no common lock, not a lucky interleaving.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from lws_tpu.core import flightrecorder
from lws_tpu.core.metrics import MetricsRegistry
from lws_tpu.serving import kv_transport
from lws_tpu.serving.pipeline import DecodePipeline
from lws_tpu.testing import (
    InstrumentedLock,
    NullLock,
    RaceDetector,
    guarded_fields,
)


def _churn(workers, n_threads=None):
    """Run worker callables on threads behind a start barrier; re-raise
    the first worker exception unless the worker opted out."""
    threads = []
    errors = []
    barrier = threading.Barrier(len(workers))

    def wrap(fn):
        def run():
            barrier.wait(timeout=10)
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — collected for the caller
                errors.append(e)

        return run

    for fn in workers:
        t = threading.Thread(target=wrap(fn), daemon=True)
        threads.append(t)
        t.start()
    for t in threads:
        t.join(timeout=30)
    return errors


# ---------------------------------------------------------------------------
# Decode dispatch ring


def _pipe_with_detector(lock):
    det = RaceDetector()
    pipe = DecodePipeline(depth=4, engine="racetest")
    pipe._lock = lock
    det.watch(pipe, {"_ring", "stats"}, name="DecodePipeline")
    return det, pipe


def test_dispatch_ring_churn_is_clean_with_real_lock():
    """Producer pushes chunks while a consumer flushes and polls: the
    RLock discipline serving/pipeline.py ships must keep every ring/stats
    access covered — the detector stays silent."""
    det, pipe = _pipe_with_detector(
        InstrumentedLock("pipe._lock", threading.RLock())
    )

    def producer():
        for i in range(200):
            pipe.push(1, np.array([i]), lambda h: None)

    def consumer():
        for _ in range(200):
            pipe.flush()
            len(pipe)
            pipe.inflight_steps()

    errors = _churn([producer, consumer])
    assert not errors, errors
    pipe.flush()
    det.assert_clean()
    stats = pipe.stats
    assert stats["consumed"] + stats["discarded"] == stats["dispatched"]
    assert len(pipe) == 0


def test_seeded_lock_removal_in_pipeline_is_caught():
    """The seeded mutation: delete serving/pipeline.py's lock discipline
    (simulated by swapping the instance lock for NullLock) and the same
    churn must DETERMINISTICALLY produce a race report — two threads
    touched ring/stats with provably no common lock held, which the
    lockset algorithm flags regardless of interleaving luck."""
    det, pipe = _pipe_with_detector(NullLock())

    def producer():
        for i in range(200):
            try:
                pipe.push(1, np.array([i]), lambda h: None)
            except Exception:  # noqa: BLE001 — the genuine corruption the mutation invites
                pass

    def consumer():
        for _ in range(200):
            try:
                pipe.flush()
            except Exception:  # noqa: BLE001 — ditto: detection, not survival, is under test
                pass
            len(pipe)

    _churn([producer, consumer])
    races = det.races()
    assert races, "lock-removal mutation went undetected"
    racy_fields = {r["field"] for r in races}
    assert racy_fields & {"_ring", "stats"}, races


def test_detector_ignores_single_thread_and_guarded_access():
    """No false positives: single-threaded mutation is the init phase,
    and two threads sharing one InstrumentedLock never race."""
    det = RaceDetector()
    pipe = DecodePipeline(depth=2, engine="racetest-st")
    pipe._lock = InstrumentedLock("st._lock", threading.RLock())
    det.watch(pipe, {"_ring", "stats"}, name="single")
    for i in range(50):
        pipe.push(1, np.array([i]), lambda h: None)
    pipe.flush()
    det.assert_clean()


# ---------------------------------------------------------------------------
# Static↔dynamic bridge: the runtime harness reads the SAME `# guarded-by`
# annotations the vet lock pass enforces — one annotation source, two
# checkers, no drift.


def test_bridge_annotation_grammar_is_shared_with_vet():
    """lws_tpu cannot import tools.vet (shipped code must not depend on
    dev tooling), so testing.py restates the guarded-by regex; this pin
    keeps the two grammars byte-identical."""
    import lws_tpu.testing as testing
    from tools.vet import core as vet_core

    assert testing.GUARDED_BY_RE.pattern == vet_core.GUARDED_BY_RE.pattern


def test_bridge_reads_same_guarded_map_as_vet_pass():
    """guarded_fields(DecodePipeline) must equal what the vet lock pass
    itself collects from serving/pipeline.py — asserted against the
    pass's OWN class collector, not a hand-kept expectation."""
    import lws_tpu.serving.pipeline as pipeline_mod
    from pathlib import Path

    from tools.vet import locks as vet_locks
    from tools.vet.core import Module

    dynamic = guarded_fields(DecodePipeline)
    assert dynamic, "DecodePipeline lost its guarded-by annotations"

    mod = Module(Path(pipeline_mod.__file__))
    assert mod.tree is not None
    static = None
    import ast

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == "DecodePipeline":
            static = vet_locks._ClassInfo(mod, node.name, node).guarded
    assert static == dynamic, (static, dynamic)


def test_watch_guarded_derives_fields_from_annotations():
    """watch_guarded needs no hand-kept field list: it instruments the
    annotated fields (clean churn stays silent with the real lock) and the
    seeded NullLock mutation is still caught on those same fields."""
    det = RaceDetector()
    pipe = DecodePipeline(depth=4, engine="racetest-bridge")
    guarded = det.watch_guarded(pipe, name="DecodePipelineBridge")
    assert guarded == {"_ring": "_lock", "stats": "_lock"}
    assert isinstance(pipe._lock, InstrumentedLock)

    def producer():
        for i in range(100):
            pipe.push(1, np.array([i]), lambda h: None)

    def consumer():
        for _ in range(100):
            pipe.flush()
            len(pipe)

    errors = _churn([producer, consumer])
    assert not errors, errors
    det.assert_clean()

    # Seeded mutation on the SAME annotation-derived watch set.
    det2 = RaceDetector()
    pipe2 = DecodePipeline(depth=4, engine="racetest-bridge2")
    pipe2._lock = NullLock()
    det2.watch_guarded(pipe2, name="DecodePipelineBridgeMut")

    def producer2():
        for i in range(200):
            try:
                pipe2.push(1, np.array([i]), lambda h: None)
            except Exception:  # noqa: BLE001 — corruption invited by the mutation
                pass

    def consumer2():
        for _ in range(200):
            try:
                pipe2.flush()
            except Exception:  # noqa: BLE001 — ditto
                pass
            len(pipe2)

    _churn([producer2, consumer2])
    assert {r["field"] for r in det2.races()} & set(guarded), det2.races()


# ---------------------------------------------------------------------------
# KV server backlog + delivery counters


@pytest.mark.parametrize("mutate", [False, True])
def test_kv_server_counter_discipline(mutate):
    """Concurrent pull_bundle clients drive the per-connection server
    threads through the delivery counters. With the shipped _counts_lock
    the detector is silent AND the count is exact; with the lock seeded
    away (NullLock) the detector reports the race."""
    server = kv_transport.KVServer(port=0, host="127.0.0.1")
    det = RaceDetector()
    try:
        server._counts_lock = (
            NullLock() if mutate
            else InstrumentedLock("kv._counts_lock")
        )
        det.watch(
            server, {"bundles_delivered", "results_served"}, name="KVServer"
        )
        n_bundles = 24
        for i in range(n_bundles):
            server.offer_bundle({"id": f"b{i}"}, b"payload")
        endpoint = ("127.0.0.1", server.port)

        def puller():
            while True:
                got = kv_transport.pull_bundle(endpoint, timeout=0.05)
                if got is None:
                    return

        errors = _churn([puller, puller, puller])
        assert not errors, errors
        if mutate:
            assert any(
                r["field"] == "bundles_delivered" for r in det.races()
            ), det.races()
        else:
            det.assert_clean()
            assert server.delivery_counts()[0] == n_bundles
    finally:
        server.close()


def test_kv_server_result_eviction_still_single_delivery():
    """Regression guard for the counter-lock change: double-pulling one
    result id still delivers exactly once (pop-under-lock contract)."""
    server = kv_transport.KVServer(port=0, host="127.0.0.1")
    try:
        server.post_result("r1", {"id": "r1"}, b"tokens")
        endpoint = ("127.0.0.1", server.port)
        delivered = []

        def puller():
            got = kv_transport.pull_result(endpoint, "r1")
            if got is not None:
                delivered.append(got)

        errors = _churn([puller, puller])
        assert not errors, errors
        assert len(delivered) == 1
        assert server.delivery_counts()[1] == 1
    finally:
        server.close()


# ---------------------------------------------------------------------------
# FleetCollector single-flight cache


class _EmptyStore:
    def list(self, kind):
        return []


def _collector():
    from lws_tpu.runtime.fleet import FleetCollector

    reg = MetricsRegistry()
    reg.inc("racetest_control_total")
    fc = FleetCollector(
        _EmptyStore(), control_registries=(reg,),
        cache_ttl_s=0.0, metrics_registry=reg,
    )
    fc._lock = InstrumentedLock("fleet._lock")
    fc._refill_lock = InstrumentedLock("fleet._refill_lock")
    return fc


def test_fleet_single_flight_cache_churn_is_clean():
    """render_fleet refills race scrape-failure bookkeeping across
    threads: cache fields and the _failing set must stay lock-covered."""
    det = RaceDetector()
    fc = _collector()
    det.watch(fc, {"_shard_cache", "_failing"}, name="FleetCollector")

    def renderer():
        for _ in range(20):
            text = fc.render_fleet()
            assert "racetest_control_total" in text

    def failer():
        for i in range(40):
            fc._scrape_target({"instance": "w-dead"}, "127.0.0.1", 1)

    errors = _churn([renderer, renderer, failer])
    assert not errors, errors
    det.assert_clean()


def test_fleet_failing_edge_records_once_under_concurrency():
    """Regression test for the fleet fix: N concurrent scrape failures
    for one instance record exactly ONE healthy->failing ring event (the
    unguarded set allowed double edges — and could corrupt the set)."""
    fc = _collector()
    flightrecorder.RECORDER.clear()
    barrier = threading.Barrier(4)
    real_scrape = fc._scrape_one

    def dead_scrape(host, port):
        barrier.wait(timeout=10)  # maximize overlap on the edge transition
        raise OSError("connection refused")

    fc._scrape_one = dead_scrape
    try:
        errors = _churn([
            lambda: fc._scrape_target({"instance": "w-edge"}, "127.0.0.1", 1)
        ] * 4)
        assert not errors, errors
    finally:
        fc._scrape_one = real_scrape
    events = [
        e for e in flightrecorder.RECORDER.events()
        if e["kind"] == "fleet_scrape_error" and e.get("instance") == "w-edge"
    ]
    assert len(events) == 1, events
