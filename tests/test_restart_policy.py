"""All-or-nothing restart semantics (≈ SURVEY §3.5 + KEP-820 budget)."""

from lws_tpu.api import contract
from lws_tpu.api.pod import PodPhase
from lws_tpu.api.types import CONDITION_FAILED, RestartPolicy
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, condition_status, lws_pods, restart_pod_container


def uids(cp, lws_name):
    return {p.meta.name: p.meta.uid for p in lws_pods(cp.store, lws_name)}


def test_recreate_group_on_pod_restart():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(3).build())
    cp.run_until_stable()
    before = uids(cp, "sample")

    restart_pod_container(cp.store, "default", "sample-0-2")
    cp.run_until_stable()

    after = uids(cp, "sample")
    assert set(after) == set(before)
    # The recreated group satisfies the full promised contract again.
    from lws_tpu.testing import assert_valid_lws
    assert_valid_lws(cp.store, "sample")
    # Whole group 0 recreated (new uids), group 1 untouched.
    for name in ("sample-0", "sample-0-1", "sample-0-2"):
        assert after[name] != before[name], name
    for name in ("sample-1", "sample-1-1", "sample-1-2"):
        assert after[name] == before[name], name
    assert "RecreateGroup" in [e.reason for e in cp.recorder.events]


def test_leader_restart_recreates_group():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    before = uids(cp, "sample")
    restart_pod_container(cp.store, "default", "sample-0")
    cp.run_until_stable()
    after = uids(cp, "sample")
    assert after["sample-0"] != before["sample-0"]
    assert after["sample-0-1"] != before["sample-0-1"]


def test_none_policy_keeps_group():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(3).restart_policy(RestartPolicy.NONE).build())
    cp.run_until_stable()
    before = uids(cp, "sample")
    restart_pod_container(cp.store, "default", "sample-0-1")
    cp.run_until_stable()
    assert uids(cp, "sample") == before


def test_recreate_after_start_waits_for_pending():
    cp = ControlPlane()  # manual readiness: all pods stay Pending
    cp.create(
        LWSBuilder().replicas(1).size(3).restart_policy(RestartPolicy.RECREATE_GROUP_AFTER_START).build()
    )
    cp.run_until_stable()
    before = uids(cp, "sample")

    # Restart while a group member is still Pending: skipped.
    restart_pod_container(cp.store, "default", "sample-0-1")
    cp.run_until_stable()
    assert uids(cp, "sample") == before

    # Once all pods started, the same restart triggers recreation.
    for pod in lws_pods(cp.store, "sample"):
        fresh = cp.store.get("Pod", "default", pod.meta.name)
        fresh.status.phase = PodPhase.RUNNING
        cp.store.update_status(fresh)
    restart_pod_container(cp.store, "default", "sample-0-1")
    cp.run_until_stable()
    after = uids(cp, "sample")
    assert after["sample-0"] != before["sample-0"]


def test_restart_budget_fail_fast():
    cp = ControlPlane(auto_ready=True)
    cp.create(
        LWSBuilder()
        .replicas(1)
        .size(2)
        .annotation(contract.MAX_GROUP_RESTARTS_ANNOTATION_KEY, "2")
        .build()
    )
    cp.run_until_stable()

    for i in range(2):
        restart_pod_container(cp.store, "default", "sample-0-1")
        cp.run_until_stable()

    before = uids(cp, "sample")
    # Third failure: budget exhausted, no recreation, LWS goes Failed.
    restart_pod_container(cp.store, "default", "sample-0-1")
    cp.run_until_stable()
    assert uids(cp, "sample") == before
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert condition_status(lws, CONDITION_FAILED) is True
