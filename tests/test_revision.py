"""Revision history semantics (parity with pkg/utils/revision tests)."""

from lws_tpu.api.types import (
    LeaderWorkerSet,
    LeaderWorkerSetSpec,
    LeaderWorkerTemplate,
    NetworkConfig,
    SubdomainPolicy,
)
from lws_tpu.api.pod import Container, PodSpec, PodTemplateSpec
from lws_tpu.core.store import Store, new_meta
from lws_tpu.utils import revision as rev


def make_lws(name="sample", image="img:v1", size=4):
    return LeaderWorkerSet(
        meta=new_meta(name),
        spec=LeaderWorkerSetSpec(
            replicas=2,
            leader_worker_template=LeaderWorkerTemplate(
                worker_template=PodTemplateSpec(spec=PodSpec(containers=[Container(image=image)])),
                size=size,
            ),
        ),
    )


def test_hash_stable_and_sensitive():
    a, b = make_lws(), make_lws()
    assert rev.hash_revision_data(rev.revision_data(a)) == rev.hash_revision_data(rev.revision_data(b))
    c = make_lws(image="img:v2")
    assert rev.hash_revision_data(rev.revision_data(a)) != rev.hash_revision_data(rev.revision_data(c))


def test_replicas_change_does_not_change_revision():
    a = make_lws()
    b = make_lws()
    b.spec.replicas = 99
    assert rev.hash_revision_data(rev.revision_data(a)) == rev.hash_revision_data(rev.revision_data(b))


def test_get_or_create_idempotent():
    store = Store()
    lws = store.create(make_lws())
    r1 = rev.get_or_create_current_revision(store, lws)
    r2 = rev.get_or_create_current_revision(store, lws)
    assert r1.meta.name == r2.meta.name
    assert len(store.list("ControllerRevision")) == 1


def test_apply_revision_restores_template():
    store = Store()
    lws = store.create(make_lws(image="img:v1"))
    r1 = rev.get_or_create_current_revision(store, lws)
    lws.spec.leader_worker_template.worker_template.spec.containers[0].image = "img:v2"
    lws.spec.network_config = NetworkConfig(subdomain_policy=SubdomainPolicy.UNIQUE_PER_REPLICA)
    assert not rev.equal_revision(lws, r1)
    restored = rev.apply_revision(lws, r1)
    assert restored.spec.leader_worker_template.worker_template.spec.containers[0].image == "img:v1"
    assert restored.spec.network_config is None
    assert rev.equal_revision(restored, r1)


def test_truncate_keeps_current():
    store = Store()
    lws = store.create(make_lws(image="img:v1"))
    r1 = rev.get_or_create_current_revision(store, lws)
    lws.spec.leader_worker_template.worker_template.spec.containers[0].image = "img:v2"
    lws = store.update(lws)
    r2 = rev.get_or_create_current_revision(store, lws)
    assert r2.revision == 2
    assert len(store.list("ControllerRevision")) == 2
    rev.truncate_revisions(store, lws, rev.get_revision_key(r2))
    remaining = store.list("ControllerRevision")
    assert len(remaining) == 1
    assert remaining[0].meta.name == r2.meta.name
