"""Rolling update machinery (≈ test/integration leaderworkerset_test.go update
tables): group-by-group updates from the highest index, maxSurge bursting and
reclaim, partition staging, conditions, revision truncation."""

from lws_tpu.api.types import (
    CONDITION_AVAILABLE,
    CONDITION_UPDATE_IN_PROGRESS,
)
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import (
    LWSBuilder,
    assert_valid_lws,
    condition_status,
    make_all_groups_ready,
)


def image_of(cp, pod_name):
    return cp.store.get("Pod", "default", pod_name).spec.containers[0].image


def update_image(cp, name, image):
    lws = cp.store.get("LeaderWorkerSet", "default", name)
    for c in lws.spec.leader_worker_template.worker_template.spec.containers:
        c.image = image
    cp.store.update(lws)


def settle_and_make_ready(cp, name="sample", max_rounds=60):
    """Drive the rollout to completion, the test playing kubelet (SURVEY §4.2)."""
    make_all_groups_ready(cp, name, max_rounds=max_rounds)


def test_rolling_update_replaces_all_groups():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(4).size(2).image("img:v1").build())
    settle_and_make_ready(cp)

    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert condition_status(lws, CONDITION_UPDATE_IN_PROGRESS) is True
    # First step: only the highest-index group is being updated.
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.update_strategy.partition == 3

    settle_and_make_ready(cp)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 4
    assert lws.status.ready_replicas == 4
    assert condition_status(lws, CONDITION_AVAILABLE) is True
    assert condition_status(lws, CONDITION_UPDATE_IN_PROGRESS) is False
    for name in ("sample-0", "sample-1", "sample-2", "sample-3", "sample-0-1", "sample-3-1"):
        assert image_of(cp, name) == "img:v2", name
    # Every promised field holds on the post-update groups.
    assert_valid_lws(cp.store, "sample")
    # Old revision truncated once update is done.
    assert len(cp.store.list("ControllerRevision")) == 1
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.update_strategy.partition == 0
    assert gs.spec.replicas == 4


def test_rolling_update_respects_max_unavailable_budget():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(4).size(2).image("img:v1").rollout(max_unavailable=2).build())
    settle_and_make_ready(cp)
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.update_strategy.partition == 2  # two groups at once
    settle_and_make_ready(cp)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 4


def test_rolling_update_with_surge_bursts_and_reclaims():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).image("img:v1").rollout(max_unavailable=1, max_surge=1).build())
    settle_and_make_ready(cp)

    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()
    # Burst replica appears immediately, built from the NEW template.
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.replicas == 3
    assert cp.store.try_get("Pod", "default", "sample-2") is not None

    settle_and_make_ready(cp)
    # Update done: surge reclaimed, back to 2 groups, all on v2.
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.replicas == 2
    assert cp.store.try_get("Pod", "default", "sample-2") is None
    assert image_of(cp, "sample-0") == "img:v2"
    assert image_of(cp, "sample-1") == "img:v2"
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2
    assert condition_status(lws, CONDITION_AVAILABLE) is True


def test_partition_stages_the_rollout():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(4).size(2).image("img:v1").rollout(partition=2).build())
    settle_and_make_ready(cp)

    update_image(cp, "sample", "img:v2")
    settle_and_make_ready(cp)

    # Only groups >= partition updated.
    assert image_of(cp, "sample-0") == "img:v1"
    assert image_of(cp, "sample-1") == "img:v1"
    assert image_of(cp, "sample-2") == "img:v2"
    assert image_of(cp, "sample-3") == "img:v2"
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2
    assert condition_status(lws, CONDITION_AVAILABLE) is True
    # Update not "done" while partition > 0: both revisions retained.
    assert len(cp.store.list("ControllerRevision")) == 2

    # Dropping partition to 0 finishes the rollout.
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.rollout_strategy.rolling_update_configuration.partition = 0
    cp.store.update(lws)
    settle_and_make_ready(cp)
    assert image_of(cp, "sample-0") == "img:v2"
    assert len(cp.store.list("ControllerRevision")) == 1


def test_scale_up_during_rolling_update_uses_new_template():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).image("img:v1").build())
    settle_and_make_ready(cp)

    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()
    # Scale up mid-update: new replicas come up with the new template.
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 4
    cp.store.update(lws)
    settle_and_make_ready(cp)

    for name in ("sample-0", "sample-1", "sample-2", "sample-3"):
        assert image_of(cp, name) == "img:v2", name
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 4
    assert lws.status.ready_replicas == 4


def test_replicas_only_change_is_not_an_update():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    settle_and_make_ready(cp)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 3
    cp.store.update(lws)
    cp.run_until_stable()
    fetched = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert condition_status(fetched, CONDITION_UPDATE_IN_PROGRESS) in (None, False)
    assert len(cp.store.list("ControllerRevision")) == 1


def test_rollout_recovers_when_all_replicas_unready():
    """Regression: a rollout starting with crash-looping (never-ready) groups
    must still replace them — deleting an already-unavailable pod consumes no
    budget (ref leaderworkerset_controller.go:660-669 escape hatch)."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).image("img:bad").build())
    cp.run_until_stable()  # pods exist but stay Pending/not-ready

    update_image(cp, "sample", "img:fixed")
    settle_and_make_ready(cp)

    for name in ("sample-0", "sample-1", "sample-0-1", "sample-1-1"):
        assert image_of(cp, name) == "img:fixed", name
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2
