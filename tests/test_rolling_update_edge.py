"""Rolling-update edge tables (≈ the wider maxSurge/maxUnavailable/partition
combinations of test/integration/controllers/leaderworkerset_test.go)."""

import pytest

from lws_tpu.api import contract
from lws_tpu.api.types import CONDITION_AVAILABLE
from lws_tpu.core.store import AdmissionError
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, condition_status, lws_pods
from tests.test_rolling_update import image_of, settle_and_make_ready, update_image


def test_percentage_budgets():
    # 50% of 4 -> maxUnavailable 2; 25% -> surge ceil(1).
    cp = ControlPlane()
    cp.create(
        LWSBuilder().replicas(4).size(2).image("img:v1")
        .rollout(max_unavailable="50%", max_surge="25%").build()
    )
    settle_and_make_ready(cp)
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.replicas == 5  # surge ceil(25% of 4) = 1
    settle_and_make_ready(cp)
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.replicas == 4
    for i in range(4):
        assert image_of(cp, f"sample-{i}") == "img:v2"


def test_surge_with_partition_keeps_burst_until_done():
    """Partition + maxSurge: burst replicas remain until the partition is
    reset (ref RollingUpdateConfiguration docs: 'bursted replicas will keep
    remaining until ... the partition field is reset to 0')."""
    cp = ControlPlane()
    cp.create(
        LWSBuilder().replicas(4).size(2).image("img:v1")
        .rollout(max_unavailable=1, max_surge=1, partition=2).build()
    )
    settle_and_make_ready(cp)
    update_image(cp, "sample", "img:v2")
    settle_and_make_ready(cp)

    assert image_of(cp, "sample-2") == "img:v2"
    assert image_of(cp, "sample-3") == "img:v2"
    assert image_of(cp, "sample-0") == "img:v1"
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert condition_status(lws, CONDITION_AVAILABLE) is True

    lws.spec.rollout_strategy.rolling_update_configuration.partition = 0
    cp.store.update(lws)
    settle_and_make_ready(cp)
    for i in range(4):
        assert image_of(cp, f"sample-{i}") == "img:v2"
    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.spec.replicas == 4
    assert gs.spec.update_strategy.partition == 0


def test_scale_down_during_rolling_update():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(4).size(2).image("img:v1").build())
    settle_and_make_ready(cp)
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 2
    cp.store.update(lws)
    settle_and_make_ready(cp)
    assert len(lws_pods(cp.store, "sample")) == 4  # 2 groups x size 2
    for name in ("sample-0", "sample-1"):
        assert image_of(cp, name) == "img:v2"
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2
    assert condition_status(lws, CONDITION_AVAILABLE) is True


def test_size_change_is_a_rolling_update():
    """Changing size is a template change: groups are rebuilt group-by-group
    with the new worker count."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    settle_and_make_ready(cp)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.leader_worker_template.size = 3
    cp.store.update(lws)
    settle_and_make_ready(cp)
    pods = sorted(p.meta.name for p in lws_pods(cp.store, "sample"))
    assert pods == ["sample-0", "sample-0-1", "sample-0-2", "sample-1", "sample-1-1", "sample-1-2"]
    for p in lws_pods(cp.store, "sample"):
        assert p.meta.annotations[contract.SIZE_ANNOTATION_KEY] == "3"


def test_both_zero_budgets_rejected():
    cp = ControlPlane()
    with pytest.raises(AdmissionError):
        cp.create(LWSBuilder().rollout(max_unavailable=0, max_surge=0).build())


def test_replicas_zero_with_percent_budgets():
    cp = ControlPlane(auto_ready=True)
    cp.create(
        LWSBuilder().replicas(0).size(2).rollout(max_unavailable="50%", max_surge="50%").build()
    )
    cp.run_until_stable()
    assert lws_pods(cp.store, "sample") == []
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 2
    cp.store.update(lws)
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 4
