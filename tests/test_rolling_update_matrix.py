"""Table-driven rolling-update matrix (≈ test/integration/controllers/
leaderworkerset_test.go:631-2500): every maxSurge x maxUnavailable x
partition x scale-up/down/to-zero x mid-update-replica-change combination
the reference treats as the spec, as step sequences with intermediate
partition / replica-count / condition checkpoints.

The test plays kubelet (SURVEY §4.2): the control plane creates pods, the
table flips their readiness group by group and asserts the controller's
rolling-update parameters after each transition.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import pytest

from lws_tpu.api.types import (
    CONDITION_AVAILABLE,
    CONDITION_PROGRESSING,
    CONDITION_UPDATE_IN_PROGRESS,
)
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import (
    LWSBuilder,
    condition_status,
    make_all_groups_ready,
    make_group_ready,
    set_pod_not_ready,
)

NAME = "sample"


# ---------------------------------------------------------------------------
# Step DSL


@dataclass
class Step:
    """One update step: run `do`, settle the control plane, assert `expect`."""

    do: Callable[[ControlPlane], None]
    expect: dict = field(default_factory=dict)
    note: str = ""


@dataclass
class Case:
    name: str
    build: Callable[[], object]  # -> LeaderWorkerSet
    steps: list[Step]


# -- actions ----------------------------------------------------------------


def ready_all(cp: ControlPlane) -> None:
    make_all_groups_ready(cp, NAME, max_rounds=60)


def ready_groups(*groups: int):
    def act(cp: ControlPlane) -> None:
        for g in groups:
            make_group_ready(cp.store, NAME, g)
            cp.run_until_stable()

    return act


def update_image(img: str):
    def act(cp: ControlPlane) -> None:
        lws = cp.store.get("LeaderWorkerSet", "default", NAME)
        for c in lws.spec.leader_worker_template.worker_template.spec.containers:
            c.image = img
        cp.store.update(lws)

    return act


def set_replicas(n: int):
    def act(cp: ControlPlane) -> None:
        lws = cp.store.get("LeaderWorkerSet", "default", NAME)
        lws.spec.replicas = n
        cp.store.update(lws)

    return act


def update_image_and_replicas(img: str, n: int):
    def act(cp: ControlPlane) -> None:
        lws = cp.store.get("LeaderWorkerSet", "default", NAME)
        for c in lws.spec.leader_worker_template.worker_template.spec.containers:
            c.image = img
        lws.spec.replicas = n
        cp.store.update(lws)

    return act


def set_partition(n: int):
    def act(cp: ControlPlane) -> None:
        lws = cp.store.get("LeaderWorkerSet", "default", NAME)
        lws.spec.rollout_strategy.rolling_update_configuration.partition = n
        cp.store.update(lws)

    return act


def resize(size: int):
    def act(cp: ControlPlane) -> None:
        lws = cp.store.get("LeaderWorkerSet", "default", NAME)
        lws.spec.leader_worker_template.size = size
        cp.store.update(lws)

    return act


def group_not_ready(group: int):
    def act(cp: ControlPlane) -> None:
        set_pod_not_ready(cp.store, "default", f"{NAME}-{group}")

    return act


def nothing(cp: ControlPlane) -> None:
    pass


def seq(*actions):
    def act(cp: ControlPlane) -> None:
        for a in actions:
            a(cp)
            cp.run_until_stable()

    return act


# -- assertions -------------------------------------------------------------


def check(cp: ControlPlane, expect: dict, ctx: str) -> None:
    lws = cp.store.get("LeaderWorkerSet", "default", NAME)
    gs = cp.store.try_get("GroupSet", "default", NAME)
    if any(k in expect for k in ("partition", "gs_replicas")):
        assert gs is not None, f"{ctx}: GroupSet missing"
    if "partition" in expect:
        assert gs.spec.update_strategy.partition == expect["partition"], (
            f"{ctx}: partition {gs.spec.update_strategy.partition} != {expect['partition']}"
        )
    if "gs_replicas" in expect:
        assert gs.spec.replicas == expect["gs_replicas"], (
            f"{ctx}: gs replicas {gs.spec.replicas} != {expect['gs_replicas']}"
        )
    if "ready" in expect:
        assert lws.status.ready_replicas == expect["ready"], (
            f"{ctx}: ready {lws.status.ready_replicas} != {expect['ready']}"
        )
    if "updated" in expect:
        assert lws.status.updated_replicas == expect["updated"], (
            f"{ctx}: updated {lws.status.updated_replicas} != {expect['updated']}"
        )
    if "available" in expect:
        assert condition_status(lws, CONDITION_AVAILABLE) is expect["available"], (
            f"{ctx}: available != {expect['available']}"
        )
    if "progressing" in expect:
        assert condition_status(lws, CONDITION_PROGRESSING) is expect["progressing"], (
            f"{ctx}: progressing != {expect['progressing']}"
        )
    if "updating" in expect:
        got = condition_status(lws, CONDITION_UPDATE_IN_PROGRESS)
        want = expect["updating"]
        ok = (got is want) or (want is False and got is None)
        assert ok, f"{ctx}: update-in-progress {got} != {want}"
    if "images" in expect:
        for g, img in expect["images"].items():
            pod = cp.store.get("Pod", "default", f"{NAME}-{g}")
            got = pod.spec.containers[0].image
            assert got == img, f"{ctx}: group {g} image {got} != {img}"
    if "revisions" in expect:
        got = len(cp.store.list("ControllerRevision"))
        assert got == expect["revisions"], f"{ctx}: revisions {got} != {expect['revisions']}"
    if "pods" in expect:
        leaders = [
            p for p in cp.store.list("Pod")
            if p.meta.name.startswith(f"{NAME}-") and p.meta.name.count("-") == 1
        ]
        assert len(leaders) == expect["pods"], (
            f"{ctx}: leader pods {len(leaders)} != {expect['pods']}"
        )
    if "group_size" in expect:
        for g, size in expect["group_size"].items():
            group_pods = [
                p for p in cp.store.list("Pod")
                if p.meta.name == f"{NAME}-{g}"
                or p.meta.name.startswith(f"{NAME}-{g}-")
            ]
            assert len(group_pods) == size, (
                f"{ctx}: group {g} has {len(group_pods)} pods != {size}"
            )


# ---------------------------------------------------------------------------
# The matrix (case names track the reference entries; line refs are to
# test/integration/controllers/leaderworkerset_test.go)


CASES = [
    # :631 leaderTemplate changed with default strategy (maxU=1): one group
    # at a time from the top index.
    Case(
        "default_strategy_one_by_one",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").build(),
        [
            Step(ready_all, dict(partition=0, gs_replicas=4, ready=4, updated=4, available=True)),
            # Group 3 is recreated on the new template immediately (the
            # control plane integrates the statefulset-controller role, so
            # `updated` counts the fresh unready pod the moment it exists).
            Step(update_image("v2"), dict(partition=3, ready=3, updated=1, updating=True, progressing=True)),
            Step(ready_groups(3), dict(partition=2, ready=3, updated=2)),
            Step(ready_groups(2), dict(partition=1, ready=3, updated=3)),
            Step(ready_groups(1), dict(partition=0, ready=3, updated=4)),
            Step(
                ready_groups(0),
                dict(partition=0, ready=4, updated=4, available=True, updating=False, revisions=1,
                     images={0: "v2", 1: "v2", 2: "v2", 3: "v2"}),
            ),
        ],
    ),
    # :729 workerTemplate changed with maxUnavailable=2: two at a time.
    Case(
        "max_unavailable_2_two_by_two",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=2).build(),
        [
            Step(ready_all, dict(partition=0, ready=4, updated=4, available=True)),
            Step(update_image("v2"), dict(partition=2, ready=2, updated=2, updating=True)),
            Step(ready_groups(3, 2), dict(partition=0, ready=2, updated=4)),
            Step(
                ready_groups(1, 0),
                dict(partition=0, ready=4, updated=4, available=True, updating=False),
            ),
        ],
    ),
    # :807 maxUnavailable greater than replicas: everything at once.
    Case(
        "max_unavailable_exceeds_replicas",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=10).build(),
        [
            Step(ready_all, dict(partition=0, ready=4, updated=4)),
            Step(update_image("v2"), dict(partition=0, ready=0, updated=4, updating=True)),
            Step(
                ready_groups(3, 2, 1, 0),
                dict(partition=0, ready=4, updated=4, available=True, updating=False),
            ),
        ],
    ),
    # :856 both worker template and replicas changed in one update.
    Case(
        "template_and_replicas_together",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=2).build(),
        [
            Step(ready_all, dict(ready=4, updated=4)),
            # New groups 4,5 come up on the new template immediately; old
            # 0-3 roll two at a time.
            Step(update_image_and_replicas("v2", 6), dict(gs_replicas=6, partition=4, ready=4,
                                                          updated=2, updating=True,
                                                          images={4: "v2", 5: "v2"})),
            Step(ready_groups(5, 4, 3, 2), dict(partition=0, ready=4, updated=6)),
            Step(
                ready_groups(1, 0),
                dict(partition=0, ready=6, updated=6, available=True, updating=False,
                     revisions=1, images={0: "v2", 3: "v2", 5: "v2"}),
            ),
        ],
    ),
    # :916 replicas increase during rolling update.
    Case(
        "replicas_increase_mid_update",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").build(),
        [
            Step(ready_all, dict(ready=4, updated=4)),
            Step(update_image("v2"), dict(partition=3, ready=3, updated=1, updating=True)),
            Step(ready_groups(3), dict(partition=2, ready=3, updated=2)),
            # Scale 4 -> 6 mid-update: new groups use the new template; the
            # partition holds while the fresh groups come up.
            Step(set_replicas(6), dict(gs_replicas=6, partition=2, updated=4, updating=True,
                                       images={4: "v2", 5: "v2"})),
            Step(ready_groups(5, 4), dict(partition=2, ready=5, updated=4)),
            Step(
                ready_groups(2, 1, 0),
                dict(partition=0, ready=6, updated=6, available=True, updating=False),
            ),
        ],
    ),
    # :1008 replicas decrease during rolling update.
    Case(
        "replicas_decrease_mid_update",
        lambda: LWSBuilder().replicas(6).size(2).image("v1").build(),
        [
            Step(ready_all, dict(ready=6, updated=6)),
            Step(update_image("v2"), dict(partition=5, ready=5, updated=1, updating=True)),
            Step(ready_groups(5), dict(partition=4, ready=5, updated=2)),
            # Scale 6 -> 3 mid-update: groups 3-5 torn down; partition clamps
            # into the surviving range.
            Step(set_replicas(3), dict(gs_replicas=3, partition=2, ready=2, updated=1,
                                       updating=True, pods=3)),
            Step(
                ready_groups(2, 1, 0),
                dict(partition=0, ready=3, updated=3, available=True, updating=False, pods=3),
            ),
        ],
    ),
    # :1088 maxUnavailable=0 with maxSurge=1: zero-downtime one-by-one via a
    # surge group; burst reclaimed at the end.
    Case(
        "maxU0_surge1_zero_downtime",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=0, max_surge=1).build(),
        [
            Step(ready_all, dict(partition=0, gs_replicas=4, ready=4, updated=4, available=True)),
            # Surge group 4 appears (new template); nothing old is torn down.
            Step(update_image("v2"), dict(gs_replicas=5, partition=4, ready=4, updated=1,
                                          updating=True, images={4: "v2"})),
            # Zero downtime: ready never drops below the 4 configured replicas.
            Step(ready_groups(4), dict(partition=3, ready=4, updated=2)),
            Step(ready_groups(3), dict(partition=2, ready=4, updated=3)),
            Step(ready_groups(2), dict(partition=1, ready=4, updated=4)),
            Step(ready_groups(1), dict(partition=0, ready=4, updated=5)),
            Step(
                ready_groups(0),
                dict(partition=0, gs_replicas=4, ready=4, updated=4, available=True,
                     updating=False, pods=4),
            ),
        ],
    ),
    # :1326 maxUnavailable=1 AND maxSurge=1 together.
    Case(
        "maxU1_surge1",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=1, max_surge=1).build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            # Surge to 5; budget allows updating 2 at once (1 unavail + 1 surge).
            Step(update_image("v2"), dict(gs_replicas=5, partition=3, ready=3, updated=2,
                                          updating=True)),
            Step(ready_groups(4, 3), dict(partition=1, ready=3, updated=4)),
            Step(ready_groups(2, 1), dict(partition=0, gs_replicas=4)),
            Step(
                ready_groups(0),
                dict(gs_replicas=4, ready=4, updated=4, available=True, updating=False, pods=4),
            ),
        ],
    ),
    # :1404 replicas scaled up while maxSurge is set.
    Case(
        "scale_up_with_surge",
        lambda: LWSBuilder().replicas(2).size(2).image("v1").rollout(max_unavailable=1, max_surge=1).build(),
        [
            Step(ready_all, dict(gs_replicas=2, ready=2)),
            Step(update_image("v2"), dict(gs_replicas=3, partition=1, ready=1, updated=2,
                                          updating=True)),
            Step(set_replicas(4), dict(gs_replicas=5, partition=1, updated=4, updating=True)),
            Step(
                ready_groups(4, 3, 2, 1, 0),
                dict(gs_replicas=4, ready=4, updated=4, available=True, updating=False, pods=4),
            ),
        ],
    ),
    # :1473 replicas scaled down while maxSurge is set.
    Case(
        "scale_down_with_surge",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=1, max_surge=1).build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            Step(update_image("v2"), dict(gs_replicas=5, partition=3, ready=3, updated=2,
                                          updating=True)),
            Step(set_replicas(2), dict(gs_replicas=3, partition=1, ready=1, updated=2,
                                       updating=True, pods=3)),
            Step(
                ready_groups(2, 1, 0),
                dict(gs_replicas=2, ready=2, updated=2, available=True, updating=False, pods=2),
            ),
        ],
    ),
    # :1539 maxSurge greater than replicas: surge is capped at replicas.
    Case(
        "surge_greater_than_replicas",
        lambda: LWSBuilder().replicas(2).size(2).image("v1").rollout(max_unavailable=1, max_surge=4).build(),
        [
            Step(ready_all, dict(gs_replicas=2, ready=2)),
            # Surge is capped: 2 replicas never burst beyond 3 groups here
            # (ref caps surge so old+new stays within replicas+maxSurge and
            # reclaims as the update progresses).
            Step(update_image("v2"), dict(gs_replicas=3, partition=1, ready=1, updated=2,
                                          updating=True)),
            Step(
                ready_groups(2, 1, 0),
                dict(gs_replicas=2, ready=2, updated=2, available=True, updating=False, pods=2),
            ),
        ],
    ),
    # :1609 scale up AND down during one rolling update with maxSurge=2.
    Case(
        "scale_up_and_down_mid_update",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=1, max_surge=2).build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            Step(update_image("v2"), dict(gs_replicas=6, partition=3, ready=3, updated=3,
                                          updating=True)),
            Step(set_replicas(6), dict(gs_replicas=8, partition=3, updated=5, updating=True)),
            Step(ready_groups(7, 6), dict(partition=3, ready=5, updated=5, updating=True)),
            Step(set_replicas(2), dict(gs_replicas=3, partition=1, ready=1, updated=2,
                                       updating=True, pods=3)),
            Step(
                ready_groups(2, 1, 0),
                dict(gs_replicas=2, ready=2, updated=2, available=True, updating=False, pods=2),
            ),
        ],
    ),
    # :1766 multiple rolling updates: a second template change mid-rollout
    # restarts the update against the newest revision.
    Case(
        "second_update_mid_rollout",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=1, max_surge=2).build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            Step(update_image("v2"), dict(gs_replicas=6, partition=3, updated=3, updating=True)),
            Step(ready_groups(5, 4), dict(partition=1, updated=5, updating=True)),
            # Second template change mid-rollout: updated resets against the
            # NEWEST revision; the intermediate v2 revision is retained until
            # the rollout completes.
            Step(update_image("v3"), dict(partition=4, updated=0, updating=True, revisions=3)),
            Step(
                ready_all,
                dict(gs_replicas=4, partition=0, ready=4, updated=4, available=True,
                     updating=False, revisions=1,
                     images={0: "v3", 1: "v3", 2: "v3", 3: "v3"}),
            ),
        ],
    ),
    # :2132 unhealthy pod below the partition mid-update: the rollout still
    # completes (an already-unavailable group consumes no budget).
    Case(
        "unhealthy_below_partition",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=2).build(),
        [
            Step(ready_all, dict(ready=4, updated=4, available=True)),
            Step(group_not_ready(1), dict(ready=3, available=False)),
            # The already-unavailable group 1 consumes one unit of the
            # maxU=2 budget, so only one group tears down at first.
            Step(update_image("v2"), dict(partition=3, ready=2, updated=1, updating=True)),
            Step(
                seq(ready_groups(3, 2), ready_groups(1, 0)),
                dict(partition=0, ready=4, updated=4, available=True, updating=False),
            ),
        ],
    ),
    # :2312 partition staged rollout: only indices >= partition update, both
    # revisions retained while staged; lowering partition completes it.
    Case(
        "partition_staged_then_released",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=1, partition=2).build(),
        [
            Step(ready_all, dict(ready=4, updated=4)),
            Step(update_image("v2"), dict(partition=3, ready=3, updated=1, updating=True)),
            Step(
                ready_groups(3, 2),
                dict(partition=2, ready=4, updated=2, available=True, updating=False,
                     revisions=2, images={0: "v1", 1: "v1", 2: "v2", 3: "v2"}),
            ),
            Step(set_partition(0), dict(partition=1, ready=3, updated=3, updating=True)),
            Step(
                ready_groups(1, 0),
                dict(partition=0, ready=4, updated=4, available=True, updating=False, revisions=1,
                     images={0: "v2", 1: "v2"}),
            ),
        ],
    ),
    # :128/:147 scale to zero and back up (outside an update).
    Case(
        "scale_to_zero_and_back",
        lambda: LWSBuilder().replicas(3).size(2).image("v1").build(),
        [
            Step(ready_all, dict(gs_replicas=3, ready=3)),
            Step(set_replicas(0), dict(gs_replicas=0, ready=0, pods=0)),
            Step(seq(set_replicas(3), ready_groups(0, 1, 2)),
                 dict(gs_replicas=3, ready=3, available=True, pods=3)),
        ],
    ),
    # :1207 maxSurge set with the default maxUnavailable=1.
    Case(
        "surge_with_default_max_unavailable",
        lambda: LWSBuilder().replicas(3).size(2).image("v1").rollout(max_unavailable=1, max_surge=2).build(),
        [
            Step(ready_all, dict(gs_replicas=3, ready=3)),
            # Budget = 1 unavailable + 2 surge: two surge groups plus one
            # torn-down old group update together.
            Step(update_image("v2"), dict(gs_replicas=5, partition=2, ready=2, updated=3,
                                          updating=True)),
            Step(
                seq(ready_groups(4, 3, 2), ready_groups(1, 0)),
                dict(gs_replicas=3, ready=3, updated=3, available=True, updating=False, pods=3),
            ),
        ],
    ),
    # Percentage budgets (ref expresses budgets as intstr percentages —
    # leaderworkerset_webhook.go:129-166; exercised at 3 points per
    # VERDICT r3 #7): 50% of 4 replicas = 2 at a time.
    Case(
        "percent_max_unavailable_50",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable="50%").build(),
        [
            Step(ready_all, dict(ready=4, updated=4)),
            Step(update_image("v2"), dict(partition=2, ready=2, updated=2, updating=True)),
            Step(ready_groups(3, 2), dict(partition=0, ready=2, updated=4)),
            Step(ready_groups(1, 0),
                 dict(partition=0, ready=4, updated=4, available=True, updating=False)),
        ],
    ),
    # 25% of 8 replicas = 2 at a time (floor semantics, never 0: ref rounds
    # maxUnavailable down but the both-zero case is rejected at admission).
    Case(
        "percent_max_unavailable_25_of_8",
        lambda: LWSBuilder().replicas(8).size(2).image("v1").rollout(max_unavailable="25%").build(),
        [
            Step(ready_all, dict(ready=8, updated=8)),
            Step(update_image("v2"), dict(partition=6, ready=6, updated=2, updating=True)),
            Step(ready_groups(7, 6, 5, 4), dict(partition=2, ready=6, updated=6)),
            Step(ready_groups(3, 2, 1, 0),
                 dict(partition=0, ready=8, updated=8, available=True, updating=False)),
        ],
    ),
    # maxSurge as a percentage: 50% of 4 = 2 surge groups (rounded UP per
    # k8s intstr surge semantics), maxU=0 -> zero downtime two-by-two.
    Case(
        "percent_max_surge_50_zero_downtime",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=0, max_surge="50%").build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            Step(update_image("v2"), dict(gs_replicas=6, partition=4, ready=4, updated=2,
                                          updating=True)),
            Step(ready_groups(5, 4), dict(partition=2, ready=4, updated=4)),
            Step(ready_groups(3, 2), dict(partition=0, ready=4, updated=6)),
            Step(ready_groups(1, 0),
                 dict(gs_replicas=4, ready=4, updated=4, available=True, updating=False, pods=4)),
        ],
    ),
    # :2408 partition AND maxSurge together: the surge burst respects the
    # partition floor, and releasing the partition finishes the rollout.
    Case(
        "partition_with_surge",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").rollout(max_unavailable=1, max_surge=1, partition=2).build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            Step(update_image("v2"), dict(gs_replicas=5, partition=3, updating=True)),
            Step(ready_groups(4, 3, 2),
                 dict(partition=2, ready=5, available=True, revisions=2)),
            Step(set_partition(0), dict(updating=True)),
            Step(ready_all,
                 dict(gs_replicas=4, partition=0, ready=4, updated=4, available=True,
                      updating=False, revisions=1, pods=4)),
        ],
    ),
    # :2199 rolling update with NO ready replicas: the stuck-update escape
    # lets the partition advance so the rollout cannot deadlock against its
    # own unavailability budget.
    Case(
        "no_ready_replicas_still_progresses",
        lambda: LWSBuilder().replicas(3).size(2).image("v1").build(),
        [
            Step(ready_all, dict(ready=3)),
            Step(seq(group_not_ready(0), group_not_ready(1), group_not_ready(2)),
                 dict(ready=0, available=False)),
            # All groups already unavailable: tearing down more costs nothing;
            # the update must still advance rather than hold partition=2.
            Step(update_image("v2"), dict(updating=True)),
            Step(ready_groups(2, 1, 0),
                 dict(partition=0, ready=3, updated=3, available=True, updating=False)),
        ],
    ),
    # :166 group size 1: leader-only groups still roll one at a time.
    Case(
        "size_one_groups",
        lambda: LWSBuilder().replicas(3).size(1).image("v1").build(),
        [
            Step(ready_all, dict(gs_replicas=3, ready=3, updated=3)),
            Step(update_image("v2"), dict(partition=2, ready=2, updated=1, updating=True)),
            Step(ready_groups(2, 1, 0),
                 dict(partition=0, ready=3, updated=3, available=True, updating=False)),
        ],
    ),
    # :187 zero replicas: no groups, no pods, still a valid steady state;
    # an update while at zero completes trivially.
    Case(
        "zero_replicas_update_trivially_done",
        lambda: LWSBuilder().replicas(0).size(2).image("v1").build(),
        [
            Step(nothing, dict(gs_replicas=0, pods=0, ready=0)),
            Step(update_image("v2"), dict(gs_replicas=0, pods=0, updating=False, revisions=1)),
            Step(seq(set_replicas(2), ready_groups(0, 1)),
                 dict(gs_replicas=2, ready=2, updated=2, available=True,
                      images={0: "v2", 1: "v2"})),
        ],
    ),
    # :109 plain scale down outside an update.
    Case(
        "scale_down_groups",
        lambda: LWSBuilder().replicas(4).size(2).image("v1").build(),
        [
            Step(ready_all, dict(gs_replicas=4, ready=4)),
            Step(set_replicas(2), dict(gs_replicas=2, ready=2, pods=2, available=True)),
        ],
    ),
    # :2277 resize: changing size mid-life recreates groups at the new size
    # (worker count follows the template revision).
    Case(
        "resize_group_size",
        lambda: LWSBuilder().replicas(2).size(2).image("v1").build(),
        [
            Step(ready_all, dict(ready=2)),
            Step(resize(3), dict(updating=True)),
            Step(ready_all, dict(ready=2, updated=2, available=True, updating=False,
                                 group_size={0: 3, 1: 3})),
        ],
    ),
]


# ---------------------------------------------------------------------------
# Condition-transition sequences (ref :346, :359, :565, :578, :598, :615):
# exact order and exclusivity of Progressing / Available / UpdateInProgress.


def test_condition_initialization_never_sets_update_in_progress():
    """:578 — a brand-new LWS is Progressing, not UpdateInProgress."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", NAME)
    assert condition_status(lws, CONDITION_PROGRESSING) is True
    assert condition_status(lws, CONDITION_UPDATE_IN_PROGRESS) in (None, False)
    assert condition_status(lws, CONDITION_AVAILABLE) in (None, False)


def test_condition_progressing_to_available_to_progressing():
    """:359 — the mutually-exclusive condition machine flips back to
    Progressing when a group degrades, then back to Available."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    make_all_groups_ready(cp, NAME, max_rounds=60)
    lws = cp.store.get("LeaderWorkerSet", "default", NAME)
    assert condition_status(lws, CONDITION_AVAILABLE) is True
    assert condition_status(lws, CONDITION_PROGRESSING) is False

    set_pod_not_ready(cp.store, "default", f"{NAME}-0")
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", NAME)
    assert condition_status(lws, CONDITION_AVAILABLE) is False
    assert condition_status(lws, CONDITION_PROGRESSING) is True

    make_group_ready(cp.store, NAME, 0)
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", NAME)
    assert condition_status(lws, CONDITION_AVAILABLE) is True


def test_condition_events_emitted():
    """:565/:615 — the condition flips surface as events (the reference's
    user-facing trace: GroupsProgressing / AvailableState)."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    make_all_groups_ready(cp, NAME, max_rounds=60)
    reasons = {e.reason for e in cp.recorder.events}
    assert "GroupsProgressing" in reasons, reasons
    assert "AllGroupsReady" in reasons, reasons  # the Available-state event


@pytest.mark.parametrize("case", CASES, ids=[c.name for c in CASES])
def test_rolling_update_matrix(case: Case) -> None:
    from lws_tpu.testing import assert_valid_lws

    cp = ControlPlane()
    cp.create(case.build())
    cp.run_until_stable()
    for i, step in enumerate(case.steps):
        step.do(cp)
        cp.run_until_stable()
        check(cp, step.expect, f"{case.name} step {i}")
    # Whatever state the case ends in, every EXISTING group must satisfy the
    # full promised contract (labels/env/affinities/services/revision links)
    # — the shared declarative validator raises every case's strength at once
    # (≈ validators.go ExpectValidLeaderStatefulSet on each poll).
    assert_valid_lws(cp.store, NAME)
