"""Direct unit tests of the rolling-update pure math — the reference's unit
tier (leaderworkerset_controller_test.go:818-1012 surge tables +
calculateRollingUpdateReplicas), run WITHOUT the harness so each case pins
one function's behavior, not the integration of the stack."""

import pytest

from lws_tpu.api import contract
from lws_tpu.api.groupset import GroupSet, GroupSetSpec, GroupSetUpdateStrategy
from lws_tpu.api.types import (
    LeaderWorkerSet,
    LeaderWorkerSetSpec,
    LeaderWorkerTemplate,
    RollingUpdateConfiguration,
    RolloutStrategy,
)
from lws_tpu.controllers.lws_controller import (
    LWSReconciler,
    ReplicaState,
    calculate_continuous_ready_replicas,
    calculate_lws_unready_replicas,
    calculate_rolling_update_replicas,
    rolling_update_partition,
)
from lws_tpu.core.events import EventRecorder
from lws_tpu.core.store import Store, new_meta


# ---- calculateRollingUpdateReplicas (ref :818-886, table ported) ----------
@pytest.mark.parametrize(
    "name,lws_replicas,max_surge,max_unavailable,unready,want",
    [
        ("keeps surge until maxUnavailable covers unready", 1, 1, 0, 1, 2),
        ("reclaims surge gradually once enough ready", 4, 2, 1, 2, 5),
        ("reclaims before partition zero when permitted", 2, 2, 1, 2, 3),
        ("falls back to desired when all ready", 1, 1, 0, 0, 1),
        ("reclaims when maxUnavailable permits an unready", 1, 1, 1, 1, 1),
        ("does not surge when maxSurge is zero", 3, 0, 0, 1, 3),
    ],
)
def test_calculate_rolling_update_replicas(name, lws_replicas, max_surge,
                                           max_unavailable, unready, want):
    got = calculate_rolling_update_replicas(lws_replicas, max_surge, max_unavailable, unready)
    assert got == want, name


# ---- rollingUpdateParameters surge cases (ref :887-1012, ported) ----------


def make_lws(replicas, max_unavailable, max_surge, partition=0):
    return LeaderWorkerSet(
        meta=new_meta("test-sample"),
        spec=LeaderWorkerSetSpec(
            replicas=replicas,
            leader_worker_template=LeaderWorkerTemplate(size=1),
            rollout_strategy=RolloutStrategy(
                rolling_update_configuration=RollingUpdateConfiguration(
                    partition=partition,
                    max_unavailable=max_unavailable,
                    max_surge=max_surge,
                )
            ),
        ),
    )


def make_gs(replicas, annotation_replicas, partition=0):
    return GroupSet(
        meta=new_meta(
            "test-sample",
            annotations={contract.REPLICAS_ANNOTATION_KEY: str(annotation_replicas)},
        ),
        spec=GroupSetSpec(
            replicas=replicas,
            update_strategy=GroupSetUpdateStrategy(partition=partition),
        ),
    )


def params_for(lws, gs, lws_updated):
    r = LWSReconciler(Store(), EventRecorder())
    return r._rolling_update_parameters(
        lws, gs, "rev-new", lws_updated, leader_pods=[], gs_by_name={}
    )


def test_scale_up_does_not_create_extra_surge():
    """ref :887-928: replicas 2->3 with maxSurge=1 and NO template change
    must scale straight to 3 at partition 0, not 3+surge."""
    lws = make_lws(replicas=3, max_unavailable=0, max_surge=1)
    gs = make_gs(replicas=2, annotation_replicas=2)
    assert params_for(lws, gs, lws_updated=False) == (0, 3)


def test_scale_up_with_template_update_does_not_create_extra_surge():
    """ref :929-970: scale-up arriving WITH a template change partitions at
    the old count (2) and still targets 3, not 3+surge."""
    lws = make_lws(replicas=3, max_unavailable=0, max_surge=1)
    gs = make_gs(replicas=2, annotation_replicas=2)
    assert params_for(lws, gs, lws_updated=True) == (2, 3)


def test_template_update_reclaims_surge_when_allowed():
    """ref :971-1012: maxUnavailable=1 lets the burst stop at replicas+1
    even though maxSurge=2 would allow replicas+2."""
    lws = make_lws(replicas=2, max_unavailable=1, max_surge=2)
    gs = make_gs(replicas=2, annotation_replicas=2)
    assert params_for(lws, gs, lws_updated=True) == (2, 3)


def test_creation_case_no_groupset():
    """Case 1 (ref :258-373): no groupset yet -> partition clamped to the
    spec's, full replicas."""
    lws = make_lws(replicas=4, max_unavailable=1, max_surge=0, partition=2)
    assert params_for(lws, None, lws_updated=False) == (2, 4)


def test_steady_state_case():
    """Case 3: partition 0 and matched replicas -> untouched."""
    lws = make_lws(replicas=3, max_unavailable=1, max_surge=0)
    gs = make_gs(replicas=3, annotation_replicas=3)
    # Steady state never consults replica states (size=1, no pods needed).
    assert params_for(lws, gs, lws_updated=False) == (0, 3)


# ---- partition math (ref :643-708 behaviors) ------------------------------


def S(ready, updated):
    return ReplicaState(ready=ready, updated=updated)


def test_continuous_ready_counts_updated_tail():
    states = [S(True, False), S(True, True), S(True, True)]
    assert calculate_continuous_ready_replicas(states) == 2
    assert calculate_continuous_ready_replicas([S(True, True)] * 3) == 3
    assert calculate_continuous_ready_replicas([S(False, True), S(True, True)]) == 1


def test_lws_unready_counts_missing_and_stale():
    states = [S(True, True), S(False, True), S(True, False)]
    # Only 2 states for 4 replicas: the missing one counts unready too.
    assert calculate_lws_unready_replicas(states, 4) == 3


def test_partition_advances_by_rolling_step():
    """4 replicas, step 1: the highest index updates first; once its state
    is ready+updated the partition moves down one."""
    states = [S(True, False)] * 3 + [S(True, True)]
    assert rolling_update_partition(states, 4, 1, current_partition=3) == 2


def test_partition_monotonic_never_increases():
    states = [S(True, False)] * 4
    assert rolling_update_partition(states, 4, 1, current_partition=2) == 2


def test_partition_accounts_unready_below():
    """An unready replica below the rolling-step floor widens the partition
    so maxUnavailable is respected (ref :650 accounting)."""
    states = [S(False, False), S(True, False), S(True, False), S(True, True)]
    # continuous_ready=1, step=1 -> floor=2; one unready below floor -> 3.
    assert rolling_update_partition(states, 4, 1, current_partition=3) == 3


def test_partition_stuck_update_escape():
    """Continuously not-ready replicas above the floor are skipped so a
    violated maxUnavailable cannot wedge the update (ref :660-673)."""
    states = [S(True, False), S(False, False), S(False, False), S(True, True)]
    got = rolling_update_partition(states, 4, 1, current_partition=3)
    assert got <= 2, got  # escapes past the stuck replicas instead of 3
