"""Rollout intelligence plane (ISSUE 15): the bounded rollout ledger fed by
store/flight-recorder observers, revision-dimension folds over the history
ring, the dry-run canary analyzer (verdict gauges + the edge-triggered
`canary_regression` watchdog feed), the opt-in actuation adapter, the
`/debug/rollout` surface, revision threading through pod env -> SLO series
-> journeys, and the CLI/loadgen renders.

Everything is deterministic: ledgers take injectable clocks, rings ingest
at explicit `now=` stamps, analyzers evaluate at explicit times — no
wall-clock sleeps."""

import json
import urllib.error
import urllib.request

import pytest

from lws_tpu import loadgen, obs
from lws_tpu.api import contract, disagg
from lws_tpu.api.meta import ObjectMeta
from lws_tpu.api.pod import Container, Pod, PodSpec
from lws_tpu.core import slo
from lws_tpu.core.flightrecorder import FlightRecorder, Watchdog, default_rules
from lws_tpu.core.metrics import MetricsRegistry, parse_exposition
from lws_tpu.core.slo import SLORecorder, SLOTargets
from lws_tpu.obs import rollout
from lws_tpu.obs.history import HistoryRing
from lws_tpu.obs.journey import JourneyVault
from lws_tpu.obs.rollout import CanaryAnalyzer, CanaryReport, RolloutLedger
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, make_all_groups_ready
from lws_tpu.utils import revision as revisionutils
from lws_tpu.utils.podutils import add_lws_variables

# Second-scale twins of the SRE burn windows (same thresholds, 1/100th
# wall) — the test rings below span ~195s, covering both tiers.
WINDOWS = tuple(w.scaled(0.05) for w in obs.DEFAULT_BURN_WINDOWS)

TARGETS = {"ttft_s": 1.0, "itl_s": 0.1, "queue_wait_s": 0.5}


def update_image(cp, name, image):
    lws = cp.store.get("LeaderWorkerSet", "default", name)
    for c in lws.spec.leader_worker_template.worker_template.spec.containers:
        c.image = image
    cp.store.update(lws)


def _canary_ring(now_span=195.0):
    """A two-revision ring: baseline r1 delivers every token on time
    (goodput == tokens), canary r2 delivers tokens with ZERO goodput (an
    all-late canary never mints the goodput counter — absence is a 100%
    error series, not a missing signal). r1 carries more tokens, so the
    baseline pick is deterministic."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    acc = 0.0
    for t in (0.0, 90.0, 180.0, now_span):
        acc += 500.0
        cum = MetricsRegistry()
        cum.inc("serving_tokens_total",
                {"engine": "paged", "revision": "r1"}, acc * 2)
        cum.inc("serving_goodput_tokens_total",
                {"engine": "paged", "revision": "r1"}, acc * 2)
        cum.inc("serving_tokens_total",
                {"engine": "paged", "revision": "r2"}, acc)
        ring.ingest(cum.render(), now=t)
    return ring


# ---------------------------------------------------------------------------
# RolloutLedger semantics


def test_ledger_record_capacity_retention_and_counter():
    reg = MetricsRegistry()
    led = RolloutLedger(capacity=3, retention_s=100.0, registry=reg,
                        clock=lambda: 0.0)
    for i in range(5):
        led.record("partition_move", obj=f"LeaderWorkerSet default/s{i}",
                   now=float(i), to_partition=i, skipped=None)
    # Capacity: only the newest 3 survive, oldest first.
    snap = led.snapshot(limit=256, now=4.0)
    assert [e["object"][-2:] for e in snap] == ["s2", "s3", "s4"]
    # None-valued detail is dropped; scalars survive.
    assert snap[-1]["detail"] == {"to_partition": 4}
    assert snap[-1]["revision"] == ""
    # limit picks the NEWEST entries; limit=0 keeps the body bounded.
    assert [e["object"][-2:] for e in led.snapshot(limit=1, now=4.0)] == ["s4"]
    assert led.snapshot(limit=0, now=4.0) == []
    # Retention: entries older than now - retention_s sweep out.
    assert len(led.snapshot(limit=256, now=103.5)) == 1  # only t=4.0 survives
    assert reg.counter_value("lws_rollout_ledger_events_total",
                             {"kind": "partition_move"}) == 5.0
    # window() slices the trailing seconds.
    led.record("scale", obj="x", now=200.0)
    assert [e["kind"] for e in led.window(since_s=1.0, now=200.5)] == ["scale"]
    led.clear()
    assert led.snapshot(limit=256, now=200.0) == []


def test_ledger_tracks_a_real_rolling_update():
    """The store-watch feed, driven by the real controllers: create ->
    roll the image -> the ledger carries creation, per-group revision
    flips, partition staging, progress, and old-pod teardown — each entry
    revision-stamped where the object carries one."""
    cp = ControlPlane()
    reg = MetricsRegistry()
    led = RolloutLedger(registry=reg)
    unsub = cp.store.watch(led.observe_store_event)
    try:
        cp.create(LWSBuilder().replicas(3).size(2).image("img:v1").build())
        make_all_groups_ready(cp, "sample")
        update_image(cp, "sample", "img:v2")
        cp.run_until_stable()
        make_all_groups_ready(cp, "sample")

        entries = led.snapshot(limit=512)
        kinds = {e["kind"] for e in entries}
        assert {"created", "group_created", "pod_created", "revision_flip",
                "partition_move", "rollout_progress",
                "pod_deleted"} <= kinds, kinds
        flips = [e for e in entries if e["kind"] == "revision_flip"]
        assert flips  # the set-level GroupSet flipped to the new template
        for e in flips:
            assert e["revision"] and e["detail"]["from_revision"]
            assert e["revision"] != e["detail"]["from_revision"]
        # Partition staging walked 2 -> 0 (highest group first).
        moves = [e["detail"]["to_partition"] for e in entries
                 if e["kind"] == "partition_move"
                 and e["object"].startswith("GroupSet")]
        assert moves and moves[-1] == 0
        # The counter and the timeline agree.
        assert reg.counter_value("lws_rollout_ledger_events_total",
                                 {"kind": "revision_flip"}) == float(len(flips))
    finally:
        unsub()


def test_ledger_observer_never_breaks_the_store():
    """A garbage event must be swallowed (the observer rides the
    reconcile path's notify loop)."""

    class Junk:
        kind = "LeaderWorkerSet"  # routes to a handler, then explodes

    led = RolloutLedger(registry=MetricsRegistry())
    ev = type("Ev", (), {"type": "ADDED", "obj": Junk()})()
    led.observe_store_event(ev)  # no raise
    assert led.snapshot(limit=16) == []


def test_ledger_recorder_feed_filters_kinds_and_bulky_payloads():
    reg = MetricsRegistry()
    led = RolloutLedger(registry=reg, clock=lambda: 10.0)
    led.observe_recorder_event({
        "kind": "drain_requested", "source": "node/a", "reason": "spot",
        "ts": 1.0, "extra": {"nested": 1},
    })
    led.observe_recorder_event({"kind": "reconcile_tick", "source": "x"})
    led.observe_recorder_event({
        "kind": "canary_regression_fired", "lws": "default/s",
        "revision": "r2", "short_burn": 55.0,
        "error_window": [[0.0, 1.0]] * 64, "ledger_window": [{}] * 32,
    })
    entries = led.snapshot(limit=16, now=10.0)
    assert [e["kind"] for e in entries] == ["drain_requested",
                                           "canary_regression_fired"]
    # Scalars ride along; ts/trace and the bulky windows do not.
    assert entries[0]["detail"] == {"source": "node/a", "reason": "spot"}
    assert entries[0]["object"] == "node/a"
    assert entries[1]["revision"] == "r2"
    assert "error_window" not in entries[1]["detail"]
    assert "ledger_window" not in entries[1]["detail"]
    assert entries[1]["detail"]["short_burn"] == 55.0


# ---------------------------------------------------------------------------
# Revision-dimension folds


def test_revision_folds_over_a_two_revision_ring():
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    for t, tok, good in ((0.0, 100.0, 80.0), (60.0, 200.0, 160.0)):
        cum = MetricsRegistry()
        la = {"engine": "paged", "revision": "rA"}
        cum.inc("serving_tokens_total", la, tok)
        cum.inc("serving_goodput_tokens_total", la, good)
        cum.inc("serving_tokens_total",
                {"engine": "paged", "revision": "rB"}, tok / 2)
        cum.set("serving_slo_attainment", 0.9 if t else 0.95, la)
        cum.inc("serving_spec_tokens_total", {**la, "kind": "drafted"}, tok)
        cum.inc("serving_spec_tokens_total", {**la, "kind": "accepted"}, good)
        cum.inc("serving_prefix_cache_hits_total", la, 30.0 * (1 + (t > 0)))
        cum.inc("serving_prefix_cache_misses_total", la, 10.0 * (1 + (t > 0)))
        cum.observe("serving_ttft_seconds", 3.0, la)
        if t:
            cum.observe("serving_ttft_seconds", 0.2, la)
        ring.ingest(cum.render(), now=t)

    assert rollout.revision_values(ring) == ["rA", "rB"]
    # GOOD%: rA delivered 80 of 100 new tokens on time; rB has no goodput
    # twin at all — that is 100% late, not no-signal.
    assert rollout.revision_good_fraction(ring, "rA") == pytest.approx(0.8)
    assert rollout.revision_good_fraction(ring, "rB") == 0.0
    assert rollout.revision_good_fraction(ring, "rZ") is None
    tokens, span = rollout.revision_samples(ring, "rA")
    assert tokens == pytest.approx(100.0) and span == pytest.approx(60.0)
    assert rollout.revision_samples(ring, "rZ") == (0.0, 0.0)
    att = rollout.revision_attainment(ring, "rA")
    assert att is not None and 0.9 <= att <= 0.95
    assert rollout.revision_attainment(ring, "rB") is None
    assert rollout.revision_spec_fraction(ring, "rA") == pytest.approx(0.8)
    assert rollout.revision_spec_fraction(ring, "rB") is None
    assert rollout.revision_prefix_fraction(ring, "rA") == pytest.approx(0.75)
    q = rollout.revision_quantile(ring, "serving_ttft_seconds_bucket",
                                  0.5, "rA")
    assert q is not None and q > 0.0
    # Engine narrowing: a different engine sees nothing.
    assert rollout.revision_good_fraction(ring, "rA", engine="other") is None


def test_revision_burn_takes_the_worst_instance():
    ring = _canary_ring()
    verdicts = rollout.revision_burn(ring, "r2", 0.99, WINDOWS, now=195.0)
    assert verdicts[0].window == "fast" and verdicts[0].firing
    assert verdicts[0].short_burn >= 14.4
    calm = rollout.revision_burn(ring, "r1", 0.99, WINDOWS, now=195.0)
    assert not calm[0].firing
    assert calm[0].short_burn == pytest.approx(0.0)
    # Unseen revision: every tier present, nothing evaluable.
    empty = rollout.revision_burn(ring, "rZ", 0.99, WINDOWS, now=195.0)
    assert len(empty) == len(WINDOWS)
    assert all(v.short_burn is None and not v.firing for v in empty)


# ---------------------------------------------------------------------------
# CanaryAnalyzer: guards, verdicts, alert feed


def test_canary_no_data_is_not_promote():
    """A thin canary holds — NEVER promotes — until the min-sample and
    min-duration guards pass, and the verdict gauge says 0 (hold)."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    cum = MetricsRegistry()
    cum.inc("serving_tokens_total", {"engine": "paged", "revision": "r9"}, 10.0)
    cum.inc("serving_goodput_tokens_total",
            {"engine": "paged", "revision": "r9"}, 10.0)
    ring.ingest(cum.render(), now=0.0)
    reg = MetricsRegistry()
    an = CanaryAnalyzer(ring, lws="default/s", attainment_target=0.99,
                        windows=WINDOWS, min_samples=50.0,
                        min_duration_s=60.0, delta=2.0,
                        registry=reg, recorder=FlightRecorder())
    report = an.evaluate(now=1.0)
    v = report.verdicts["r9"]
    assert v.verdict == "hold"
    assert v.reason.startswith("insufficient data")
    assert report.baseline == ""  # nothing judgeable, no incumbent
    assert reg.gauge_value("lws_rollout_canary_verdict",
                           {"lws": "default/s", "revision": "r9"}) == 0.0


def test_canary_burning_without_baseline_holds():
    """Every revision burning means the regression is not
    revision-attributable — hold, don't roll back to another bad build."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    acc = 0.0
    for t in (0.0, 90.0, 180.0, 195.0):
        acc += 500.0
        cum = MetricsRegistry()
        cum.inc("serving_tokens_total",
                {"engine": "paged", "revision": "r2"}, acc)
        ring.ingest(cum.render(), now=t)
    an = CanaryAnalyzer(ring, attainment_target=0.99, windows=WINDOWS,
                        min_samples=100.0, min_duration_s=50.0, delta=2.0,
                        registry=MetricsRegistry(), recorder=FlightRecorder())
    report = an.evaluate(now=195.0)
    v = report.verdicts["r2"]
    assert v.verdict == "hold" and v.firing
    assert "not revision-attributable" in v.reason


def test_canary_e2e_rollback_verdict_edge_alert_and_recovery():
    """The PR's end-to-end proof: a degraded canary against a calm
    baseline -> revision-scoped burn diverges -> `rollback` for the canary
    while the baseline stays `promote` -> ONE `canary_regression` watchdog
    alert whose dump embeds the offending revision's error window AND the
    rollout-ledger window -> the ring emptying retires every gauge and
    clears the alert."""
    ring = _canary_ring()
    reg = MetricsRegistry()
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=default_rules())
    rollout.LEDGER.clear()
    try:
        # Seed the process ledger so the alert's evidence window has the
        # control-plane context an operator would expect.
        rollout.LEDGER.record("partition_move",
                              obj="LeaderWorkerSet default/sample",
                              now=190.0, from_partition=3, to_partition=2)
        an = CanaryAnalyzer(ring, lws="default/sample",
                            attainment_target=0.99, windows=WINDOWS,
                            min_samples=100.0, min_duration_s=50.0,
                            delta=2.0, ledger=rollout.LEDGER,
                            registry=reg, recorder=fr)
        report = an.evaluate(now=195.0)
        assert report.baseline == "r1"
        assert report.verdicts["r1"].verdict == "promote"
        rv = report.verdicts["r2"]
        assert rv.verdict == "rollback" and rv.firing
        assert rv.baseline_burn == pytest.approx(0.0)
        assert rv.short_burn >= 14.4
        # Published surfaces: the verdict gauge pair + the burn twin.
        assert reg.gauge_value("lws_rollout_canary_verdict",
                               {"lws": "default/sample",
                                "revision": "r2"}) == -1.0
        assert reg.gauge_value("lws_rollout_canary_verdict",
                               {"lws": "default/sample",
                                "revision": "r1"}) == 1.0
        burn = reg.gauge_value(
            "serving_slo_burn_rate_by_revision",
            {"engine": "paged", "revision": "r2", "window": "fast"})
        assert burn is not None and burn >= 14.4
        # Verdict changes land on the timeline.
        verdict_entries = [e for e in rollout.LEDGER.snapshot(limit=64,
                                                              now=195.0)
                           if e["kind"] == "canary_verdict"]
        assert {e["revision"]: e["detail"]["verdict"]
                for e in verdict_entries} == {"r1": "promote",
                                              "r2": "rollback"}

        # The watchdog fires ONCE per episode, dump carrying the evidence.
        firing = wd.check_now(now=196.0)
        assert "canary_regression" in firing
        dump = wd.last_dump
        assert dump is not None
        assert dump["reason"] == "watchdog:canary_regression"
        assert "rollout" in dump  # every dump embeds the process timeline
        fired = [e for e in dump["events"]
                 if e["kind"] == "canary_regression_fired"]
        assert fired, dump["events"]
        assert fired[0]["revision"] == "r2"
        assert fired[0]["lws"] == "default/sample"
        assert fired[0]["error_window"], fired[0]
        assert all(v >= 0.99 for _, v in fired[0]["error_window"])
        ledger_kinds = [e["kind"] for e in fired[0]["ledger_window"]]
        assert "partition_move" in ledger_kinds
        # Steady firing: no second edge event, no second alert.
        an.evaluate(now=200.0)
        wd.check_now(now=201.0)
        assert len([e for e in fr.events()
                    if e["kind"] == "canary_regression_fired"]) == 1

        # Recovery: the canary's series leave the ring -> gauges retire
        # (a frozen rollback verdict is a phantom incident), alert clears.
        ring.clear()
        report = an.evaluate(now=205.0)
        assert report.verdicts == {}
        assert reg.gauge_value("lws_rollout_canary_verdict",
                               {"lws": "default/sample",
                                "revision": "r2"}) is None
        assert reg.gauge_value(
            "serving_slo_burn_rate_by_revision",
            {"engine": "paged", "revision": "r2", "window": "fast"}) is None
        assert "canary_regression" not in wd.check_now(now=206.0)
    finally:
        rollout.LEDGER.clear()


# ---------------------------------------------------------------------------
# The opt-in actuation adapter


def test_actuation_adapter_pauses_and_rolls_back_mid_update():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(3).size(2).image("img:v1").build())
    make_all_groups_ready(cp, "sample")
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()  # mid-rollout: both revisions exist

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    revs = revisionutils.list_revisions(cp.store, lws)
    assert len(revs) == 2
    old_key = revisionutils.get_revision_key(revs[0])
    new_key = revisionutils.get_revision_key(revs[-1])

    adapter = rollout.RolloutActuationAdapter(cp.store, "default", "sample")
    report = CanaryReport(at=0.0, lws="default/sample", baseline=old_key)
    report.verdicts[new_key] = rollout.RevisionVerdict(
        new_key, "rollback", "fast burn 55.0x vs baseline 0.0x")
    report.verdicts[old_key] = rollout.RevisionVerdict(
        old_key, "promote", "within budget")
    out = adapter.apply(report)
    assert out["acted"] and out["paused"]
    assert out["rolled_back_to"] == old_key
    assert out["offenders"] == [new_key]

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    tpl = lws.spec.leader_worker_template.worker_template
    assert tpl.spec.containers[0].image == "img:v1"
    ru = lws.spec.rollout_strategy.rolling_update_configuration
    assert ru.partition == 0  # rollback releases the pause
    # The stock controller walks the fleet back to v1.
    cp.run_until_stable()
    make_all_groups_ready(cp, "sample")
    for pod in cp.store.list("Pod"):
        assert pod.spec.containers[0].image == "img:v1", pod.meta.name


def test_actuation_adapter_is_inert_without_rollback_or_baseline():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).image("img:v1").build())
    make_all_groups_ready(cp, "sample")
    adapter = rollout.RolloutActuationAdapter(cp.store, "default", "sample")
    # All-promote report: nothing to act on.
    report = CanaryReport(at=0.0, lws="default/sample", baseline="k1")
    report.verdicts["k1"] = rollout.RevisionVerdict("k1", "promote", "ok")
    assert adapter.apply(report) == {"acted": False, "offenders": []}
    # Rollback verdict but NO judged baseline: acting would be a guess.
    report = CanaryReport(at=0.0, lws="default/sample", baseline="")
    report.verdicts["k2"] = rollout.RevisionVerdict("k2", "rollback", "burn")
    out = adapter.apply(report)
    assert out["acted"] is False and out["offenders"] == ["k2"]


# ---------------------------------------------------------------------------
# The /debug/rollout surface + fleet-scrape evaluation


def test_api_server_rollout_endpoint_and_fleet_scrape_evaluation():
    from lws_tpu.runtime.server import ApiServer

    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).image("img:v1").build())
    make_all_groups_ready(cp, "sample")
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        # The harness wired the process ledger to this store: the create
        # above is already on the timeline.
        with urllib.request.urlopen(f"{base}/debug/rollout", timeout=10) as r:
            body = json.loads(r.read().decode())
        assert isinstance(body, list)
        assert any(e["kind"] == "created" for e in body)
        with urllib.request.urlopen(f"{base}/debug/rollout?limit=1",
                                    timeout=10) as r:
            assert len(json.loads(r.read().decode())) == 1
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/rollout?limit=-1",
                                   timeout=10)
        assert err.value.code == 400
        # The fleet scrape evaluates the default analyzer (dry-run, no
        # revision series yet -> no verdicts) without failing the scrape,
        # and its lws target syncs to the store's deployment.
        with urllib.request.urlopen(f"{base}/metrics/fleet", timeout=10) as r:
            assert r.status == 200
        assert rollout.ANALYZER is not None
        assert rollout.ANALYZER.lws == "default/sample"
        # The revision-scoped request index rides the same 400-never-500
        # contract as the other debug surfaces.
        with urllib.request.urlopen(
                f"{base}/debug/requests?revision=zzz", timeout=10) as r:
            assert json.loads(r.read().decode()) == []
    finally:
        api.stop()
        rollout.LEDGER.clear()


# ---------------------------------------------------------------------------
# Revision threading: pod env -> SLO series -> journeys


def _make_pod(labels_extra=None):
    labels = {
        contract.SET_NAME_LABEL_KEY: "lws",
        contract.GROUP_INDEX_LABEL_KEY: "1",
        contract.WORKER_INDEX_LABEL_KEY: "0",
    }
    labels.update(labels_extra or {})
    return Pod(
        meta=ObjectMeta(
            name="lws-1", namespace="ns1", labels=labels,
            annotations={contract.SIZE_ANNOTATION_KEY: "2"},
        ),
        spec=PodSpec(containers=[Container(env=[])], subdomain="svc"),
    )


def test_pod_env_injects_revision_with_ds_precedence():
    pod = _make_pod({contract.REVISION_LABEL_KEY: "tmplhash"})
    add_lws_variables(pod)
    values = {e.name: e.value for e in pod.spec.containers[0].env}
    assert values[contract.LWS_TPU_REVISION] == "tmplhash"
    # The DS per-role revision outranks the template hash — the same
    # precedence the fleet scraper applies to pod labels.
    pod = _make_pod({contract.REVISION_LABEL_KEY: "tmplhash",
                     disagg.DS_REVISION_LABEL_KEY: "dsrev"})
    add_lws_variables(pod)
    values = {e.name: e.value for e in pod.spec.containers[0].env}
    assert values[contract.LWS_TPU_REVISION] == "dsrev"
    # No revision labels: the variable is simply absent (pre-revision
    # series identity preserved).
    pod = _make_pod()
    add_lws_variables(pod)
    assert contract.LWS_TPU_REVISION not in {
        e.name for e in pod.spec.containers[0].env}


def test_slo_recorder_stamps_revision_on_series_and_journeys(monkeypatch):
    reg = MetricsRegistry()
    rec = SLORecorder(targets=SLOTargets(ttft_s=10.0, itl_s=10.0,
                                         queue_wait_s=10.0),
                      registry=reg, revision="r9")
    summaries = []
    rec.journey_sinks.append(summaries.append)
    tl = rec.request("paged", klass="chat")
    tl.queue_wait(0.01)
    tl.first_token(0.1)
    tl.tokens(5, elapsed_s=0.2)
    assert tl.finish()
    labels = {"engine": "paged", "klass": "chat", "revision": "r9"}
    # 6 = the first token + the 5-token tail, all on time.
    assert reg.counter_value("serving_tokens_total", labels) == 6.0
    assert reg.gauge_value("serving_slo_attainment", labels) == 1.0
    assert summaries and summaries[0]["revision"] == "r9"
    # Default: the pod env the webhook injected.
    monkeypatch.setenv(slo.REVISION_ENV, "renv")
    assert SLORecorder(registry=MetricsRegistry()).revision == "renv"
    monkeypatch.delenv(slo.REVISION_ENV)
    assert SLORecorder(registry=MetricsRegistry()).revision == ""


def test_journey_vault_index_filters_by_revision():
    v = JourneyVault(sample_rate=0.0, slowest_k=0, rng=lambda: 1.0,
                     registry=MetricsRegistry())
    v.complete("q-1", engine="paged", ok=False, revision="abc",
               phases={"ttft_s": 2.0}, targets=dict(TARGETS))
    v.complete("q-2", engine="paged", ok=False, revision="def",
               phases={"ttft_s": 3.0}, targets=dict(TARGETS))
    rows = v.index(outcome="all", revision="abc")
    assert [r["id"] for r in rows] == ["q-1"]
    assert rows[0]["revision"] == "abc"
    assert len(v.index(outcome="all")) == 2
    assert v.index(outcome="all", revision="zzz") == []
    assert v.get("q-1")["revision"] == "abc"


# ---------------------------------------------------------------------------
# CLI renders (pure functions over canned state)


def test_render_rollout_table_alerts_and_timeline():
    from lws_tpu.cli import render_rollout

    reg = MetricsRegistry()
    reg.set("lws_rollout_canary_verdict", 1.0,
            {"lws": "default/sample", "revision": "r1"})
    reg.set("lws_rollout_canary_verdict", -1.0,
            {"lws": "default/sample", "revision": "r2"})
    reg.set("serving_slo_burn_rate_by_revision", 55.0,
            {"engine": "paged", "revision": "r2", "window": "fast"})
    reg.inc("serving_tokens_total",
            {"engine": "paged", "revision": "r1"}, 1000.0)
    reg.inc("serving_goodput_tokens_total",
            {"engine": "paged", "revision": "r1"}, 900.0)
    fams = parse_exposition(reg.render())
    entries = [{"at": 1.0, "unix": 0.0, "kind": "partition_move",
                "object": "LeaderWorkerSet default/sample", "revision": "",
                "detail": {"from_partition": 3, "to_partition": 2}}]
    alerts = {"canary_regression": {"series": "canary:default/sample/r2"}}
    out = render_rollout(entries, fams, alerts)
    assert "ROLLOUT  lws=default/sample  revisions=2" in out
    assert "rollback" in out and "promote" in out
    assert "55.0x" in out and "90%" in out
    assert "ALERT canary_regression" in out
    assert "partition_move" in out and "to_partition=2" in out

    empty = render_rollout([], {}, {})
    assert "(no revision-labelled serving series yet)" in empty
    assert "(ledger empty" in empty


def test_render_request_index_carries_revision_column():
    from lws_tpu.cli import render_request_index

    out = render_request_index([
        {"id": "q-1", "outcome": "breached", "klass": "chat",
         "engine": "paged", "ttft_s": 2.0, "total_s": 2.5, "spans": 3,
         "revision": "abcdef123456789", "instance": "pod-0"},
    ])
    assert "REVISION" in out
    assert "abcdef12345" in out  # truncated to the column
    out = render_request_index([{"id": "q-2", "outcome": "retried"}])
    assert out.splitlines()[1].split()[-2] == "-"  # no revision -> dash


# ---------------------------------------------------------------------------
# Loadgen: the canary report block + the revision-bump scenario hook


def test_fold_canary_replays_the_run_and_traces_verdict_flips():
    ring = _canary_ring()
    canary = loadgen.fold_canary(ring, lws="default/sample",
                                 attainment_target=0.99, windows=WINDOWS,
                                 min_samples=100.0, min_duration_s=50.0,
                                 delta=2.0)
    assert canary is not None
    assert canary["baseline"] == "r1"
    assert canary["revisions"]["r2"]["verdict"] == "rollback"
    assert canary["revisions"]["r1"]["verdict"] == "promote"
    # The trace replays run-relative: starts at t=0 (everything holds on
    # thin data), ends with the regression called.
    assert canary["trace"][0]["t"] == 0.0
    assert canary["trace"][-1]["verdicts"]["r2"] == "rollback"
    # No revision-labelled series -> no block at all.
    assert loadgen.fold_canary(HistoryRing(interval_s=0.0,
                                           retention_s=60.0)) is None


def test_render_report_canary_block():
    report = {
        "scenario": "rolling_update", "seed": 1, "horizon_s": 1.5,
        "wall_s": 1.6, "offered_rps": 12.0, "achieved_rps": 11.5,
        "classes": {},
        "all": {"count": 10, "completed": 10, "attainment": 0.9,
                "goodput_fraction": 0.8, "tokens": 60, "good_tokens": 48,
                "ttft_p50": 0.01, "ttft_p95": 0.05, "ttft_p99": 0.06,
                "itl_p50": 0.001, "itl_p95": 0.002, "itl_p99": 0.003},
        "canary": {
            "baseline": "r1",
            "revisions": {
                "r1": {"verdict": "promote", "short_burn": 0.0,
                       "samples": 3000.0, "duration_s": 195.0,
                       "reason": "within budget (fast burn 0.00x)"},
                "r2": {"verdict": "rollback", "short_burn": 100.0,
                       "samples": 1500.0, "duration_s": 195.0,
                       "reason": "fast burn 100.0x vs baseline 0.0x"},
            },
            "trace": [{"t": 0.0, "baseline": "",
                       "verdicts": {"r1": "hold", "r2": "hold"}},
                      {"t": 195.0, "baseline": "r1",
                       "verdicts": {"r1": "promote", "r2": "rollback"}}],
        },
    }
    frame = loadgen.render_report(report)
    assert "CANARY" in frame
    assert "r1*" in frame  # baseline marker
    assert "rollback" in frame and "100.0x" in frame
    assert "canary @195.00s: r1=promote r2=rollback" in frame


def test_revision_bump_stanza_validation():
    spec = loadgen.load_scenario("rolling_update")
    bump = loadgen.revision_bump(spec)
    assert bump == {"at_s": 1.0, "lws": "",
                    "env": {"name": "LWS_TPU_CANARY_STAGE",
                            "value": "canary"}}
    # Absent stanza: None — every pre-existing scenario is bump-free.
    assert loadgen.revision_bump(loadgen.load_scenario("steady_poisson")) \
        is None
    # Defaults fill in; bad shapes fail loudly.
    assert loadgen.revision_bump({"revision_bump": {}})["env"]["name"] == \
        "LWS_TPU_CANARY_STAGE"
    with pytest.raises(ValueError):
        loadgen.revision_bump({"revision_bump": 5})
    with pytest.raises(ValueError):
        loadgen.revision_bump({"revision_bump": {"env": "canary"}})
    # The stanza never touches the schedule: digests are bump-invariant.
    with_bump = dict(spec)
    without = {k: v for k, v in spec.items() if k != "revision_bump"}
    assert loadgen.schedule_digest(loadgen.build_schedule(with_bump, 7)) == \
        loadgen.schedule_digest(loadgen.build_schedule(without, 7))
