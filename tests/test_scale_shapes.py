"""70B-class shape validation WITHOUT allocation (eval_shape only): the
BASELINE north star is Llama-3-70B serving on v5p (ref vLLM-TPU TP=16,
docs/examples/vllm/TPU/lws.yaml:22-34). These tests pin that the sharding
rules actually divide the real 70B shapes — and the honest GQA bound:
the KV cache shards over kv-heads, so serving tp <= n_kv_heads (=8 for
Llama-3-70B); weight-only tp=16 divides fine."""

import jax
import jax.numpy as jnp
import pytest

from lws_tpu.models.llama import (
    LlamaConfig,
    cache_shardings,
    init_cache,
    init_params,
    paged_cache_shardings,
    param_shardings,
)
from lws_tpu.parallel import MeshSpec, build_mesh


def llama70b():
    return LlamaConfig(
        vocab_size=128256, d_model=8192, n_layers=80, n_heads=64,
        n_kv_heads=8, d_ff=28672, max_seq_len=8192,
        dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
    )


def test_70b_param_count():
    cfg = llama70b()
    assert 68e9 < cfg.n_params() < 72e9, cfg.n_params()


def test_70b_param_shardings_divide_at_tp8():
    """Every parameter dim sharded over tp must divide at tp=8 (one v5p
    host's worth of the 16-chip group; 8 = our virtual mesh width)."""
    from jax.sharding import NamedSharding

    cfg = llama70b()
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=8), jax.devices()[:8])
    shapes = jax.eval_shape(lambda: init_params(cfg, jax.random.key(0)))
    specs = param_shardings(cfg)

    def check(path, shape_struct, spec):
        sh = NamedSharding(mesh, spec)
        shard = sh.shard_shape(shape_struct.shape)  # raises if indivisible
        assert all(s >= 1 for s in shard)

    jax.tree_util.tree_map_with_path(check, shapes, specs)


def test_70b_kv_cache_shards_at_tp8_and_rejects_tp16():
    """The serving cache shards kv-heads over tp: tp=8 divides Llama-70B's
    8 KV heads exactly (each shard: 1 kv head); tp=16 cannot — the Engine
    rejects it up front rather than silently replicating (the reference's
    vLLM TP=16 example relies on vLLM duplicating KV heads; this framework
    states the bound instead)."""
    from jax.sharding import NamedSharding

    cfg = llama70b()
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=8), jax.devices()[:8])
    cache_struct = jax.eval_shape(lambda: init_cache(cfg, 16, 8192))
    sh = NamedSharding(mesh, cache_shardings(cfg).k)
    shard = sh.shard_shape(cache_struct.k.shape)
    assert shard[3] == 1  # one kv head per tp shard
    # Full bf16 cache at B=16, T=8192: 2 * 80 * 16 * 8192 * 8 * 128 * 2B = 40 GiB
    # across the group -> ~5 GiB per tp=8 shard. Sanity-pin the arithmetic.
    per_shard_bytes = 2 * (
        shard[0] * shard[1] * shard[2] * shard[3] * shard[4] * 2
    )
    assert per_shard_bytes == pytest.approx(5.4e9, rel=0.05), per_shard_bytes

    from lws_tpu.serving import Engine

    with pytest.raises(ValueError, match="n_kv_heads"):
        # tp=16 via a 8-device mesh is impossible; assert the divisibility
        # check itself (16 > 8 devices, so fake the axis with tp=16 shape
        # check): n_kv_heads=8 % tp=16 != 0 -> Engine must refuse.
        class FakeMesh:
            axis_names = ("dp", "pp", "cp", "tp")

            class devices:  # noqa: N801 — mimic mesh.devices.shape
                shape = (1, 1, 1, 16)

        Engine(cfg, {}, batch_size=1, max_len=128, mesh=FakeMesh())


def test_70b_weight_dims_divide_at_tp16():
    """The docstring's weight-only tp=16 claim, checked arithmetically (no
    16-device mesh needed): every tp-sharded parameter dim of the 70B
    shapes divides 16."""
    cfg = llama70b()
    shapes = jax.eval_shape(lambda: init_params(cfg, jax.random.key(0)))
    specs = param_shardings(cfg)

    def check(path, struct, spec):
        for dim, axis in zip(struct.shape, tuple(spec)):
            if axis == "tp":
                assert dim % 16 == 0, (path, struct.shape, spec)

    jax.tree_util.tree_map_with_path(check, shapes, specs)


def test_70b_paged_pool_shardings_divide_at_tp8():
    from jax.sharding import NamedSharding

    cfg = llama70b()
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=8), jax.devices()[:8])
    # Flagship paged shape scaled to 70B: block 64, 128 slots x 20 blocks.
    num_blocks, bs = 128 * 20 + 1, 64
    kshape = (cfg.n_layers, num_blocks, bs, cfg.n_kv_heads, cfg.head_dim)
    sh = NamedSharding(mesh, paged_cache_shardings(cfg).k)
    shard = sh.shard_shape(kshape)
    assert shard[3] == 1 and shard[1] == num_blocks  # heads split, pool whole
