"""Slice-aware scheduling: exclusive topology, gang admission,
follow-the-leader placement (≈ e2e gang + exclusive placement cases)."""

import pytest

from lws_tpu.api import contract
from lws_tpu.runtime import ControlPlane
from lws_tpu.sched import make_slice_nodes
from lws_tpu.testing import LWSBuilder, lws_pods


def make_cp_with_slices(n_slices=2, topology="2x4", **kw):
    cp = ControlPlane(
        enable_scheduler=True, require_binding=True,
        auto_ready=kw.pop("auto_ready", True),
        scheduler_provider=kw.pop("scheduler_provider", None),
    )
    for s in range(n_slices):
        cp.add_nodes(make_slice_nodes(f"slice-{s}", topology=topology))  # 2 hosts x 4 chips
    return cp


def node_slice(cp, pod_name):
    pod = cp.store.get("Pod", "default", pod_name)
    assert pod.spec.node_name, f"{pod_name} not scheduled"
    node = cp.store.get("Node", "_cluster", pod.spec.node_name)
    return node.meta.labels[contract.NODE_TPU_SLICE_LABEL]


def test_exclusive_topology_one_group_per_slice():
    cp = make_cp_with_slices(n_slices=2)
    cp.create(
        LWSBuilder().replicas(2).size(2).tpu_chips(4).exclusive_topology().build()
    )
    cp.run_until_stable()
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == 4
    # Each group fully on one slice; the two groups on different slices.
    g0 = {node_slice(cp, "sample-0"), node_slice(cp, "sample-0-1")}
    g1 = {node_slice(cp, "sample-1"), node_slice(cp, "sample-1-1")}
    assert len(g0) == 1 and len(g1) == 1
    assert g0 != g1


def test_follow_the_leader_node_selector():
    cp = make_cp_with_slices(n_slices=2)
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).exclusive_topology().build())
    cp.run_until_stable()
    worker_gs = cp.store.get("GroupSet", "default", "sample-0")
    sel = worker_gs.spec.template.spec.node_selector
    assert sel[contract.NODE_TPU_SLICE_LABEL] == node_slice(cp, "sample-0")


def test_chip_capacity_respected():
    cp = make_cp_with_slices(n_slices=1, topology="2x4")  # 2 hosts x 4 chips
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    # Two pods, 4 chips each, one host has only 4: one pod per host.
    p0 = cp.store.get("Pod", "default", "sample-0")
    p1 = cp.store.get("Pod", "default", "sample-0-1")
    assert p0.spec.node_name != p1.spec.node_name


def test_unschedulable_group_stays_pending():
    cp = make_cp_with_slices(n_slices=1, topology="1x4")  # one host, 4 chips
    cp.create(LWSBuilder().replicas(1).size(3).tpu_chips(4).build())
    cp.run_until_stable()
    pods = lws_pods(cp.store, "sample")
    unbound = [p for p in pods if not p.spec.node_name]
    assert unbound, "expected some pods to remain unschedulable"


def test_gang_all_or_nothing():
    # Gang provider: group needs 12 chips but fleet has 8 -> nothing binds.
    cp = make_cp_with_slices(n_slices=1, topology="2x4", scheduler_provider="gang")
    cp.create(LWSBuilder().replicas(1).size(3).tpu_chips(4).build())
    cp.run_until_stable()
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == 3
    assert all(not p.spec.node_name for p in pods), "gang must bind all-or-nothing"
    # PodGroup exists with whole-group min resources.
    groups = cp.store.list("PodGroup")
    assert len(groups) == 1
    assert groups[0].spec.min_member == 3
    assert groups[0].spec.min_resources[contract.TPU_RESOURCE_NAME] == 12


def test_gang_binds_when_feasible():
    cp = make_cp_with_slices(n_slices=1, topology="3x4", scheduler_provider="gang")
    cp.create(LWSBuilder().replicas(1).size(3).tpu_chips(4).build())
    cp.run_until_stable()
    pods = lws_pods(cp.store, "sample")
    assert all(p.spec.node_name for p in pods)
    assert cp.store.list("PodGroup")[0].status.phase == "Running"


def test_gang_leader_ready_reserves_whole_slice():
    """Regression: under LeaderReady min_member=1, the lone leader must still
    reserve a slice that fits the WHOLE group, not greedily grab a small one."""
    from lws_tpu.api.types import StartupPolicy

    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True,
                      scheduler_provider="gang")
    cp.add_nodes(make_slice_nodes("small", topology="1x4"))   # 4 chips
    cp.add_nodes(make_slice_nodes("big", topology="4x4"))     # 16 chips
    cp.create(
        LWSBuilder().replicas(1).size(4).tpu_chips(4).exclusive_topology()
        .startup_policy(StartupPolicy.LEADER_READY).build()
    )
    cp.run_until_stable()
    assert node_slice(cp, "sample-0") == "big"
    # Workers follow onto the same slice and the whole group binds.
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == 4
    assert all(p.spec.node_name for p in pods)
    assert {node_slice(cp, p.meta.name) for p in pods} == {"big"}


def test_node_failure_recreates_group_elsewhere():
    """A slice going NotReady (preemption) fails its pods; the restart policy
    recreates the whole group and it reschedules onto the healthy slice."""
    cp = make_cp_with_slices(n_slices=2, topology="2x4")
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).exclusive_topology().build())
    cp.run_until_stable()
    before_slice = node_slice(cp, "sample-0")

    # Preempt the slice hosting the group.
    for node in cp.store.list("Node"):
        if node.meta.labels[contract.NODE_TPU_SLICE_LABEL] == before_slice:
            node.status.ready = False
            cp.store.update_status(node)
    cp.run_until_stable()

    pods = lws_pods(cp.store, "sample")
    assert len(pods) == 2
    after = {node_slice(cp, p.meta.name) for p in pods}
    assert after == {s for s in ("slice-0", "slice-1") if s != before_slice}
    assert all(p.status.ready for p in pods)
    assert "NodeFailure" in {e.reason for e in cp.recorder.events}


def test_resync_recovers_fresh_control_plane():
    """A brand-new control plane over pre-existing state converges after
    resync (controller restart over live state, SURVEY §5 checkpoint/resume)."""
    cp = make_cp_with_slices(n_slices=1, topology="2x4")
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    # Create drift the old manager never sees, then stand up a NEW control
    # plane sharing the store.
    cp.store.delete("GroupSet", "default", "sample-0")
    cp2 = ControlPlane(
        enable_scheduler=True, auto_ready=True, require_binding=True, store=cp.store
    )
    cp2.resync()
    cp2.run_until_stable()
    assert cp2.store.try_get("GroupSet", "default", "sample-0") is not None
    pods = lws_pods(cp2.store, "sample")
    assert len(pods) == 2 and all(p.status.ready for p in pods)


def test_drain_moves_group_to_other_slice():
    """Operator drain (slice maintenance): cordon + evict fails the node's
    pods; the restart policy recreates their groups on remaining capacity and
    the scheduler avoids the cordoned node."""
    from lws_tpu.api.node import CLUSTER_NAMESPACE
    from lws_tpu.api.pod import PodPhase

    cp = make_cp_with_slices(n_slices=2, topology="2x4")
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).exclusive_topology().build())
    cp.run_until_stable()
    before = node_slice(cp, "sample-0")

    # Drain every node of the hosting slice (the server endpoint does this
    # per node; here we exercise the same store-level operations).
    for node in cp.store.list("Node"):
        if node.meta.labels[contract.NODE_TPU_SLICE_LABEL] != before:
            continue
        node.spec.unschedulable = True
        cp.store.update(node)
        for pod in cp.store.list("Pod"):
            if pod.spec.node_name == node.meta.name and pod.status.phase != PodPhase.FAILED:
                fresh = cp.store.get("Pod", "default", pod.meta.name)
                fresh.status.phase = PodPhase.FAILED
                fresh.status.ready = False
                cp.store.update_status(fresh)
    cp.run_until_stable()
    after = {node_slice(cp, p.meta.name) for p in lws_pods(cp.store, "sample")}
    assert after == {s for s in ("slice-0", "slice-1") if s != before}
    assert all(p.status.ready for p in lws_pods(cp.store, "sample"))
    # Uncordon restores schedulability: a second replica lands on the freed
    # slice (the other slice is already fully occupied).
    for node in cp.store.list("Node"):
        fresh = cp.store.get("Node", CLUSTER_NAMESPACE, node.meta.name)
        fresh.spec.unschedulable = False
        cp.store.update(fresh)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 2
    cp.store.update(lws)
    cp.run_until_stable()
    assert node_slice(cp, "sample-1") == before


def test_fleet_scale_reconciles_stay_linear():
    """VERDICT #7 regression guard: turnup of a large fleet must cost O(R)
    reconciles (observed ~38/group) — a quadratic event fan-out regression
    (e.g. every Node/PodGroup event requeueing all unbound pods) blows well
    past this bound long before it times anything out."""
    replicas, size = 32, 4
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    for i in range(replicas):
        cp.add_nodes(make_slice_nodes(f"slice-{i}", topology=f"{size}x4"))
    cp.create(
        LWSBuilder().replicas(replicas).size(size).tpu_chips(4)
        .exclusive_topology().build()
    )
    reconciles = cp.run_until_stable(max_iterations=1_000_000)
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == replicas * size and all(p.status.ready for p in pods)
    assert all(p.spec.node_name for p in pods)
    assert reconciles < 60 * replicas, reconciles


@pytest.mark.slow
def test_fleet_scale_reconciles_stay_linear_256():
    """The 256-group extension (VERDICT r4 #6): both turnup AND a fleet-wide
    rollout must stay O(R) reconciles at 2x the canonical fleet — the scale
    where the r4 curve fell off super-linearly (rollout 11.4 -> 7.1 groups/s)
    before the owned_by_shared / scheduler-index work."""
    replicas, size = 256, 4
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    for i in range(replicas):
        cp.add_nodes(make_slice_nodes(f"slice-{i}", topology=f"{size}x4"))
    cp.create(
        LWSBuilder().replicas(replicas).size(size).tpu_chips(4)
        .exclusive_topology().build()
    )
    reconciles = cp.run_until_stable(max_iterations=1_000_000)
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == replicas * size and all(p.status.ready for p in pods)
    assert reconciles < 60 * replicas, reconciles  # observed ~38/group

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.leader_worker_template.worker_template.spec.containers[0].image = "worker:v2"
    cp.store.update(lws)
    rollout_reconciles = cp.run_until_stable(max_iterations=1_000_000)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == replicas
    assert rollout_reconciles < 80 * replicas, rollout_reconciles  # ~53/group


def test_bootstrap_affinity_requires_topology_label():
    """First pod of a group (self-affinity bootstrap) must still land on a
    node that CARRIES the topology label — an unlabeled node would pin the
    group to a None domain no peer can ever join."""
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    # A bare node without any slice/topology labels, added first so it sorts
    # ahead; then a labeled slice.
    from lws_tpu.api.node import CLUSTER_NAMESPACE, Node
    from lws_tpu.core.store import new_meta

    bare = Node(meta=new_meta("a-bare-node", namespace=CLUSTER_NAMESPACE))
    bare.spec.capacity[contract.TPU_RESOURCE_NAME] = 8
    bare.status.ready = True
    cp.store.create(bare)
    cp.add_nodes(make_slice_nodes("slice-0", topology="2x4"))
    cp.create(
        LWSBuilder().replicas(1).size(2).tpu_chips(4).exclusive_topology().build()
    )
    cp.run_until_stable()
    pods = lws_pods(cp.store, "sample")
    assert len(pods) == 2
    assert all(p.spec.node_name for p in pods), [p.spec.node_name for p in pods]
    assert {node_slice(cp, p.meta.name) for p in pods} == {"slice-0"}


def test_gang_annotation_change_moves_membership():
    """A pod whose PodGroup annotation changes must leave the old gang's
    bucket (else the old gang's joint assignment can bind an ex-member)."""
    cp = make_cp_with_slices(n_slices=1, topology="2x4", scheduler_provider="gang")
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    sched = cp.scheduler
    (gang_key,) = [g for g in sched._by_gang]
    pod = cp.store.get("Pod", "default", "sample-0-1")
    pod.meta.annotations[contract.POD_GROUP_ANNOTATION_KEY] = "other-gang"
    cp.store.update(pod)
    cp.run_until_stable()
    members = sched._by_gang.get(gang_key, {})
    assert ("Pod", "default", "sample-0-1") not in members
    assert ("Pod", "default", "sample-0-1") in sched._by_gang.get(
        ("default", "other-gang"), {}
    )


def test_external_provider_pods_stay_unbound_by_native_scheduler():
    """ADVICE r2: with enableScheduler:true AND schedulerProvider external,
    pods stamped with a foreign spec.scheduler_name must be left strictly
    alone by the native scheduler — binding is the external scheduler's job
    (done via the API)."""
    cp = make_cp_with_slices(
        n_slices=2, scheduler_provider="external", auto_ready=False
    )
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    pods = lws_pods(cp.store, "sample")
    assert pods, "leader pod should exist"
    assert all(p.spec.scheduler_name == "external" for p in pods)
    assert all(not p.spec.node_name for p in pods), (
        "native scheduler must not bind externally-owned pods"
    )


def test_external_provider_queue_is_per_lws():
    """ADVICE r2: the external provider must read volcano.sh/queue-name per
    call (no shared self.queue mutation) so two LWS with different queues
    can never stamp each other's queue onto a PodGroup."""
    cp = make_cp_with_slices(n_slices=2, scheduler_provider="external", auto_ready=False)
    cp.create(
        LWSBuilder(name="lws-a").replicas(1).size(2).tpu_chips(4)
        .annotation("volcano.sh/queue-name", "queue-a").build()
    )
    cp.create(
        LWSBuilder(name="lws-b").replicas(1).size(2).tpu_chips(4)
        .annotation("volcano.sh/queue-name", "queue-b").build()
    )
    cp.run_until_stable()
    queues = {
        pg.meta.labels[contract.SET_NAME_LABEL_KEY]: pg.spec.queue
        for pg in cp.store.list("PodGroup")
    }
    assert queues == {"lws-a": "queue-a", "lws-b": "queue-b"}, queues
