"""Serving engine correctness: cached decode must reproduce teacher-forced
full-forward greedy decoding exactly."""

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.models import forward, init_params
from lws_tpu.models.llama import LlamaConfig
from lws_tpu.serving import Engine


def tiny_cfg():
    return LlamaConfig(
        vocab_size=101,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=64,
        dtype=jnp.float32,  # exact comparison
        remat=False,
    )


def test_cached_decode_matches_full_forward():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=2, max_len=32)
    prompt = jax.random.randint(jax.random.key(1), (2, 7), 0, cfg.vocab_size).astype(jnp.int32)

    result = engine.generate(prompt, max_new_tokens=8)
    generated = np.asarray(result.tokens)

    # Oracle: greedy via full recompute each step.
    seq = prompt
    expected = []
    for _ in range(8):
        logits, _ = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    expected = np.asarray(jnp.stack(expected, axis=1))

    np.testing.assert_array_equal(generated, expected)


def test_prefill_decode_handoff():
    """The cache returned by prefill is a self-contained pytree — the
    disaggregated handoff unit between prefill and decode roles."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    prefill_engine = Engine(cfg, params, batch_size=1, max_len=32)
    decode_engine = Engine(cfg, params, batch_size=1, max_len=32)

    prompt = jnp.array([[5, 9, 2, 11]], jnp.int32)
    token, cache = prefill_engine.prefill(prompt)
    # Simulate the cross-slice transfer: round-trip through host memory.
    cache_host = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), cache)
    token2, _ = decode_engine.decode(token, cache_host)

    # Same result decoding on the original engine.
    token3, _ = prefill_engine.decode(token, cache)
    np.testing.assert_array_equal(np.asarray(token2), np.asarray(token3))


def test_unrolled_cached_decode_matches_scan():
    """The serving-optimized unrolled layer loop must be numerically identical
    to the scanned path."""
    import dataclasses

    cfg = tiny_cfg()
    cfg_unrolled = dataclasses.replace(cfg, unroll_cached_layers=True)
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([[3, 1, 4, 1, 5]], jnp.int32)

    r1 = Engine(cfg, params, batch_size=1, max_len=16).generate(prompt, 6)
    r2 = Engine(cfg_unrolled, params, batch_size=1, max_len=16).generate(prompt, 6)
    np.testing.assert_array_equal(np.asarray(r1.tokens), np.asarray(r2.tokens))


def test_sampling_modes():
    """Greedy default unchanged; temperature sampling is seed-deterministic
    and varies across seeds; top_k=1 degenerates to greedy."""
    from lws_tpu.serving.engine import SamplingParams

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([[5, 9, 2]], jnp.int32)

    greedy = Engine(cfg, params, batch_size=1, max_len=32).generate(prompt, 6)
    topk1 = Engine(
        cfg, params, batch_size=1, max_len=32,
        sampling=SamplingParams(temperature=0.8, top_k=1),
    ).generate(prompt, 6)
    np.testing.assert_array_equal(np.asarray(greedy.tokens), np.asarray(topk1.tokens))

    s1 = Engine(cfg, params, batch_size=1, max_len=32,
                sampling=SamplingParams(temperature=1.5), seed=7).generate(prompt, 12)
    s1b = Engine(cfg, params, batch_size=1, max_len=32,
                 sampling=SamplingParams(temperature=1.5), seed=7).generate(prompt, 12)
    s2 = Engine(cfg, params, batch_size=1, max_len=32,
                sampling=SamplingParams(temperature=1.5), seed=8).generate(prompt, 12)
    np.testing.assert_array_equal(np.asarray(s1.tokens), np.asarray(s1b.tokens))
    assert not np.array_equal(np.asarray(s1.tokens), np.asarray(s2.tokens))

    nucleus = Engine(cfg, params, batch_size=1, max_len=32,
                     sampling=SamplingParams(temperature=1.0, top_p=0.9), seed=3).generate(prompt, 6)
    assert np.asarray(nucleus.tokens).shape == (1, 6)


def test_int8_kv_cache_close_to_full_precision():
    """kv_quant halves cache bytes; generations must stay faithful: per-token
    quantization error ~1/254 of the dynamic range keeps greedy decoding on
    the full-precision trajectory for a meaningful horizon."""
    import dataclasses

    cfg = tiny_cfg()
    cfg_q = dataclasses.replace(cfg, kv_quant=True)
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([[5, 9, 2, 11, 7]], jnp.int32)

    full = Engine(cfg, params, batch_size=1, max_len=32).generate(prompt, 8)
    quant_engine = Engine(cfg_q, params, batch_size=1, max_len=32)
    assert quant_engine.new_cache().k.dtype == jnp.int8

    # Scales must survive every cache rebuild (prefill AND decode), and the
    # dequantized contents must track the full-precision cache closely.
    from lws_tpu.models.llama import _dequantize_kv

    tok, qcache = quant_engine.prefill(prompt)
    tok, qcache = quant_engine.decode(tok, qcache)
    assert qcache.k_scale is not None and qcache.v_scale is not None
    full_engine2 = Engine(cfg, params, batch_size=1, max_len=32)
    ftok, fcache = full_engine2.prefill(prompt)
    ftok, fcache = full_engine2.decode(ftok, fcache)
    used = 6  # prompt 5 + 1 decoded
    deq = np.asarray(_dequantize_kv(qcache.k, qcache.k_scale, jnp.float32))[:, :, :used]
    ref = np.asarray(fcache.k)[:, :, :used]
    denom = np.abs(ref).max()
    assert np.abs(deq - ref).max() / denom < 0.02, np.abs(deq - ref).max() / denom

    quant = quant_engine.generate(prompt, 8)
    f, q = np.asarray(full.tokens)[0], np.asarray(quant.tokens)[0]
    # The first tokens must agree; later tokens may diverge once a borderline
    # argmax flips (then trajectories legitimately separate).
    assert f[0] == q[0], (f, q)
    agree = 0
    for a, b in zip(f, q):
        if a != b:
            break
        agree += 1
    assert agree >= 4, f"quantized trajectory diverged immediately: {f} vs {q}"


def test_chunked_prefill_matches_full_prefill():
    """Chunked prefill (memory-bounded long-context path) must produce the
    same greedy first token and the same decode trajectory as whole-prompt
    prefill, for both chunk-aligned and padded prompt lengths."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    for S in (32, 40):  # exact multiple of chunk, and padded tail
        engine = Engine(cfg, params, batch_size=2, max_len=64)
        prompt = jax.random.randint(jax.random.key(S), (2, S), 0, cfg.vocab_size).astype(jnp.int32)
        t_full, c_full = engine.prefill(prompt)
        t_chunk, c_chunk = engine.prefill_chunked(prompt, chunk_size=16)
        assert jnp.array_equal(t_full, t_chunk), (S, t_full, t_chunk)
        assert int(c_chunk.pos) == S
        # Decode trajectories stay identical for several steps.
        for _ in range(6):
            t_full, c_full = engine.decode(t_full, c_full)
            t_chunk, c_chunk = engine.decode(t_chunk, c_chunk)
            assert jnp.array_equal(t_full, t_chunk)


def test_chunked_prefill_short_prompt_delegates():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    t, cache = engine.prefill_chunked(prompt, chunk_size=16)
    assert int(cache.pos) == 8 and t.shape == (1,)


def test_chunked_prefill_int8_cache():
    import dataclasses

    cfg = dataclasses.replace(tiny_cfg(), kv_quant=True)
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)
    prompt = jax.random.randint(jax.random.key(7), (1, 40), 0, cfg.vocab_size).astype(jnp.int32)
    t_chunk, c = engine.prefill_chunked(prompt, chunk_size=16)
    t_full, _ = engine.prefill(prompt)
    assert jnp.array_equal(t_chunk, t_full)
    assert c.k_scale is not None


def test_bundle_bytes_scale_with_prompt_length():
    """cache_to_bundle pos-truncates: wire bytes follow the PROMPT length,
    not the prefill engine's max_len reservation (VERDICT r3 next #3)."""
    from lws_tpu.serving.kv_transport import bundle_to_cache, cache_to_bundle

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)

    def bundle_for(plen):
        prompt = jax.random.randint(jax.random.key(2), (1, plen), 0, cfg.vocab_size).astype(jnp.int32)
        token, cache = engine.prefill(prompt)
        return cache_to_bundle(cache, token)

    b8, b32 = bundle_for(8), bundle_for(32)
    # 4x the prompt ~> 4x the KV bytes (npz framing is small at these sizes).
    assert 2.5 * len(b8) < len(b32) < 6 * len(b8), (len(b8), len(b32))
    # And both are far below the full-allocation bundle (64 rows).
    full_rows_estimate = len(b32) * 2  # 32 -> 64 rows
    assert len(b8) < full_rows_estimate / 4

    # Round trip into a DIFFERENT decode budget: prefix pasted, room to run.
    cache, token = bundle_to_cache(b8, max_len=48)
    assert cache.k.shape[2] == 48 and int(cache.pos) == 8
    decode_engine = Engine(cfg, params, batch_size=1, max_len=48)
    tok2, _ = decode_engine.decode(token, cache)
    assert tok2.shape == (1,)


def test_decode_bundle_speculative_leg_exact():
    """The disagg decode worker's speculative leg (--gamma / env
    LWS_TPU_SPEC_GAMMA): _decode_bundle with gamma > 0 must return the SAME
    [B, steps+1] token matrix as the plain decode_n leg — drafting seeds
    from the bundle's running token only (the wire ships K/V, not prompt
    text), and greedy acceptance protects the stream regardless."""
    from lws_tpu.serving.disagg_worker import _decode_bundle
    from lws_tpu.serving.kv_transport import cache_to_bundle

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)
    prompt = jnp.asarray([[5, 9, 2, 11] * 4], jnp.int32)
    token, cache = engine.prefill(prompt)
    payload = cache_to_bundle(cache, token)

    want, _, _ = _decode_bundle(engine, payload, steps=20)
    got, stats, _ = _decode_bundle(engine, payload, steps=20, gamma=4, ngram=3)
    np.testing.assert_array_equal(got, want)
    assert stats["spec_gamma"] == 4


def test_bundle_rejects_too_small_decode_budget():
    import pytest

    from lws_tpu.serving.kv_transport import bundle_to_cache, cache_to_bundle

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)
    prompt = jnp.ones((1, 16), jnp.int32)
    token, cache = engine.prefill(prompt)
    data = cache_to_bundle(cache, token)
    with pytest.raises(ValueError, match="max_len"):
        bundle_to_cache(data, max_len=8)


def test_sharded_prefill_bundle_to_sharded_decode():
    """tp=2 prefill cache -> pos-truncated host bundle -> re-sharded tp=2
    decode cache: tokens identical to the single-device engine end to end
    (the in-process version of the disagg tp handoff e2e)."""
    from lws_tpu.parallel import MeshSpec, build_mesh
    from lws_tpu.serving.kv_transport import bundle_to_cache, cache_to_bundle

    cfg = LlamaConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    prompt = jax.random.randint(jax.random.key(3), (1, 9), 0, cfg.vocab_size).astype(jnp.int32)
    steps = 6

    # Oracle: one single-device engine does prefill + decode.
    single = Engine(cfg, params, batch_size=1, max_len=32)
    want = np.asarray(single.generate(prompt, max_new_tokens=steps + 1).tokens)

    mesh_a = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    mesh_b = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[2:4])
    prefill_eng = Engine(cfg, params, batch_size=1, max_len=32, mesh=mesh_a)
    decode_eng = Engine(cfg, params, batch_size=1, max_len=32, mesh=mesh_b)

    token, cache = prefill_eng.prefill(prompt)
    assert cache.k.sharding.spec[3] == "tp"
    data = cache_to_bundle(cache, token)  # host gather + pos truncate
    cache2, token2 = bundle_to_cache(data, max_len=32)
    cache2 = jax.device_put(cache2, decode_eng._cache_shardings)
    token2, cache2, toks = decode_eng.decode_n(token2, cache2, steps)
    got = np.concatenate([np.asarray(token)[:, None], np.asarray(toks)], axis=1)
    np.testing.assert_array_equal(got, want)


def test_speculative_decoding_exact_and_accepting():
    """n-gram speculative decoding must be EXACT vs greedy generate() —
    acceptance only keeps tokens equal to the model's own argmax chain — and
    on a repetitive prompt it must actually accept drafts (fewer dispatches
    than tokens)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)

    # Repetitive prompt: the n-gram lookup should find matches.
    pattern = [5, 9, 2, 11]
    prompt = jnp.asarray([pattern * 4], jnp.int32)  # 16 tokens
    want = engine.generate(prompt, max_new_tokens=24)
    got = engine.generate_speculative(prompt, max_new_tokens=24, gamma=6, ngram=3)
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(want.tokens))
    assert got.spec_stats["dispatches"] < 23, got.spec_stats
    assert got.spec_stats["accepted"] > 0

    # Non-repetitive prompt: still exact (drafts mostly rejected).
    prompt2 = jax.random.randint(jax.random.key(7), (1, 12), 0, cfg.vocab_size).astype(jnp.int32)
    want2 = engine.generate(prompt2, max_new_tokens=16)
    got2 = engine.generate_speculative(prompt2, max_new_tokens=16, gamma=4, ngram=2)
    np.testing.assert_array_equal(np.asarray(got2.tokens), np.asarray(want2.tokens))


def test_speculative_decoding_near_max_len():
    """The verify run must never overrun max_len: near the boundary the
    engine finishes with single decode steps, still exact."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=32)
    prompt = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6] * 2], jnp.int32)  # 16 tokens
    want = engine.generate(prompt, max_new_tokens=16)
    got = engine.generate_speculative(prompt, max_new_tokens=16, gamma=8, ngram=3)
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(want.tokens))


def test_speculative_decoding_sync_loop_exact():
    """pipeline_depth=0 (the strictly synchronous ring) must emit the same
    stream as the default overlapped loop — pipelining reorders host
    consumption, never device math."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.asarray([[5, 9, 2, 11] * 4], jnp.int32)
    e_sync = Engine(cfg, params, batch_size=1, max_len=64, pipeline_depth=0)
    e_pipe = Engine(cfg, params, batch_size=1, max_len=64, pipeline_depth=2)
    want = e_sync.generate_speculative(prompt, max_new_tokens=24, gamma=6)
    got = e_pipe.generate_speculative(prompt, max_new_tokens=24, gamma=6)
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(want.tokens))
    assert got.spec_stats["accepted"] == want.spec_stats["accepted"]


def test_decode_speculative_matches_decode_n():
    """The disagg decode leg's primitive: decode_speculative must continue a
    prefilled cache byte-identically to decode_n (greedy acceptance keeps
    only the model's own argmax chain), with and without a prompt context
    seeding the drafting history."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=64)
    prompt = jnp.asarray([[5, 9, 2, 11] * 4], jnp.int32)  # 16 tokens
    steps = 24

    token, cache = engine.prefill(prompt)
    _, _, want = engine.decode_n(token, cache, steps)
    want = np.asarray(want)

    for context in (prompt[0], None):
        token2, cache2 = engine.prefill(prompt)
        _, _, got = engine.decode_speculative(
            token2, cache2, steps, gamma=4, ngram=3,
            pos=int(prompt.shape[1]), context=context,
        )
        np.testing.assert_array_equal(got, want)


def test_decode_speculative_near_max_len_exact_count():
    """Regression: the pipelined single-step tail must produce EXACTLY
    `steps` tokens and never append K/V past max_len — an in-flight-blind
    tail loop over-dispatched by up to pipeline_depth steps (returning 9
    tokens for steps=7 with cache.pos past max_len)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, batch_size=1, max_len=32, pipeline_depth=2)
    prompt = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6] * 3], jnp.int32)  # 24 tokens
    token, cache = engine.prefill(prompt)
    _, _, want = engine.decode_n(token, cache, 7)

    token2, cache2 = engine.prefill(prompt)
    _, cache2, got = engine.decode_speculative(
        token2, cache2, 7, gamma=4, ngram=3, pos=24, context=prompt[0],
    )
    assert got.shape == (1, 7), got.shape
    assert int(cache2.pos) <= 32, int(cache2.pos)
    np.testing.assert_array_equal(got, np.asarray(want))
