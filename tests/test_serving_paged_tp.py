"""TP x paged x continuous batching composed in ONE engine (VERDICT r3 next
#2): PagedBatchEngine(mesh=...) shards params + K/V pools (+ scale pools)
over 'tp' while block tables stay replicated — token-identical to the
single-device paged engine on the virtual 8-device CPU platform, including
with int8 KV pools and through the pallas kernel (interpret mode) whose
shard_map wrapper runs each tp shard on its local kv-heads pool slice.
This is the 70B-class llm-d serving shape (BASELINE #3/#5; ref vLLM-TPU
TP=16, /root/reference/docs/examples/vllm/TPU/lws.yaml:30-34)."""


import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models import init_params
from lws_tpu.models.llama import LlamaConfig
from lws_tpu.parallel import MeshSpec, build_mesh
from lws_tpu.serving.batch_engine import BatchEngine
from lws_tpu.serving.engine import Engine
from lws_tpu.serving.paged_engine import PagedBatchEngine


def tiny_cfg(**kw):
    base = dict(
        # vocab divisible by tp: the embed table shards P("tp", None).
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    base.update(kw)
    return LlamaConfig(**base)


PROMPTS = [
    np.array([5, 9, 2], np.int32),
    np.array([7, 7, 1, 4, 11, 3], np.int32),
    np.array([3, 30, 60], np.int32),
]


def run_paged(cfg, params, mesh=None, block_size=8, max_len=32):
    engine = PagedBatchEngine(
        cfg, params, slots=3, max_len=max_len, block_size=block_size, mesh=mesh
    )
    rids = [engine.submit(p, max_new_tokens=6) for p in PROMPTS]
    assert all(r is not None for r in rids)
    engine.run_until_drained()
    return [engine.result(r) for r in rids], engine


@pytest.mark.parametrize("kv_quant", [False, True])
def test_paged_tp_matches_single_device(kv_quant):
    cfg = tiny_cfg(kv_quant=kv_quant)
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    want, _ = run_paged(cfg, params)
    got, engine = run_paged(cfg, params, mesh=mesh)
    assert got == want
    # The pools really are sharded: kv-heads dim split over tp.
    assert engine.cache.k.sharding.spec[3] == "tp", engine.cache.k.sharding.spec
    shard = engine.cache.k.sharding.shard_shape(engine.cache.k.shape)
    assert shard[3] == cfg.n_kv_heads // 2
    if kv_quant:
        assert engine.cache.k_scale.sharding.spec[3] == "tp"


@pytest.mark.parametrize("kv_quant", [False, True])
def test_paged_tp_kernel_interpret_matches(monkeypatch, kv_quant):
    """The pallas kernel path under tp: shard_map manual over 'tp' gives each
    shard its local heads slice of the pool; interpret mode runs the real
    kernel logic on CPU. Tokens must match the single-device kernel run."""
    monkeypatch.setenv("LWS_TPU_PAGED_ATTN", "interpret")
    cfg = tiny_cfg(kv_quant=kv_quant)
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    want, _ = run_paged(cfg, params)
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    got, _ = run_paged(cfg, params, mesh=mesh)
    assert got == want


def test_paged_tp_with_dp_axis_present():
    """A (dp=2, tp=2) mesh: pools replicate over dp (blocks are randomly
    indexed — dp is the replica-level axis) and shard over tp."""
    cfg = tiny_cfg()
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    want, _ = run_paged(cfg, params)
    mesh = build_mesh(MeshSpec(dp=2, pp=1, cp=1, tp=2), jax.devices()[:4])
    got, engine = run_paged(cfg, params, mesh=mesh)
    assert got == want
    spec = engine.cache.k.sharding.spec
    assert "tp" in spec and "dp" not in spec


def test_paged_tp_rejects_indivisible_heads():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=8), jax.devices()[:8])
    with pytest.raises(ValueError, match="n_kv_heads"):
        PagedBatchEngine(cfg, params, slots=2, max_len=32, block_size=8, mesh=mesh)


def test_batch_engine_int8_kv_matches_isolated():
    """BatchEngine now accepts kv_quant (the last density guard is gone):
    staggered int8-KV continuous batching reproduces the isolated int8-KV
    Engine exactly."""
    cfg = tiny_cfg(kv_quant=True)
    params = init_params(cfg, jax.random.key(0))
    engine = BatchEngine(cfg, params, slots=3, max_len=32)

    a = engine.submit(PROMPTS[0], max_new_tokens=8)
    for _ in range(3):
        engine.step()
    b = engine.submit(PROMPTS[1], max_new_tokens=6)
    engine.run_until_drained()

    def oracle(prompt, n):
        e = Engine(cfg, params, batch_size=1, max_len=32)
        r = e.generate(np.asarray(prompt).reshape(1, -1), max_new_tokens=n)
        return list(np.asarray(r.tokens)[0])

    assert engine.result(a) == oracle(PROMPTS[0], 8)
    assert engine.result(b) == oracle(PROMPTS[1], 6)


def test_kernel_failure_falls_back_to_xla(monkeypatch):
    """Paged-kernel safety (first hardware contact happens in a serving
    engine): a kernel that fails to trace/compile must not crash the engine —
    the step rebuilds on the XLA gather path, stats record the downgrade,
    and tokens are identical."""
    monkeypatch.setenv("LWS_TPU_PAGED_ATTN", "interpret")  # kernel path on CPU
    cfg = tiny_cfg()
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    want, good = run_paged(cfg, params)
    assert good.stats["attention_path"] == "kernel"

    import lws_tpu.ops.paged_attention as pa

    def boom(*a, **k):
        raise RuntimeError("injected kernel failure")

    monkeypatch.setattr(pa, "paged_decode_attention", boom)
    got, engine = run_paged(cfg, params)
    assert engine.stats["attention_path"] == "xla_fallback"
    assert "injected kernel failure" in engine.stats["kernel_error"]
    assert got == want
