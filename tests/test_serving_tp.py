"""TP-sharded serving engine (VERDICT r3 #3): params + KV cache sharded over
'tp' on a Mesh, decode under GSPMD — token-identical to the single-device
engine on the virtual 8-device CPU platform. The north-star this unlocks is
70B-class serving where the model cannot exist on one chip (BASELINE #3; ref
vLLM-TPU TP=16, docs/examples/vllm/TPU/lws.yaml:22-34)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.parallel import MeshSpec, build_mesh
from lws_tpu.serving import Engine


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return cfg, params


def prompt(cfg, batch=2, n=24):
    return jax.random.randint(
        jax.random.key(1), (batch, n), 0, cfg.vocab_size
    ).astype(jnp.int32)


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_engine_matches_single_device(model, tp):
    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=tp), jax.devices()[:tp])
    single = Engine(cfg, params, batch_size=2, max_len=64)
    sharded = Engine(cfg, params, batch_size=2, max_len=64, mesh=mesh)
    p = prompt(cfg)
    r_single = single.generate(p, max_new_tokens=16)
    r_sharded = sharded.generate(p, max_new_tokens=16)
    np.testing.assert_array_equal(
        np.asarray(r_single.tokens), np.asarray(r_sharded.tokens)
    )
    # The cache really is sharded: kv-heads dim split over tp.
    _, cache = sharded.prefill(p)
    k_shard = cache.k.sharding
    assert k_shard.spec[3] == "tp", k_shard.spec
    shard_shape = k_shard.shard_shape(cache.k.shape)
    assert shard_shape[3] == cfg.n_kv_heads // tp


def test_tp_engine_decode_n_stays_sharded(model):
    """decode_n must keep the cache on its shardings across the scan (a
    reshard per step would silently serialize through one device)."""
    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=4), jax.devices()[:4])
    eng = Engine(cfg, params, batch_size=2, max_len=64, mesh=mesh)
    token, cache = eng.prefill(prompt(cfg))
    token, cache, toks = eng.decode_n(token, cache, 8)
    assert cache.k.sharding.spec[3] == "tp"
    assert toks.shape == (2, 8)


def test_tp_engine_dp_axis(model):
    """A (dp=2, tp=2) mesh: batch shards over dp, heads over tp."""
    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=2, pp=1, cp=1, tp=2), jax.devices()[:4])
    single = Engine(cfg, params, batch_size=2, max_len=64)
    eng = Engine(cfg, params, batch_size=2, max_len=64, mesh=mesh)
    p = prompt(cfg)
    np.testing.assert_array_equal(
        np.asarray(single.generate(p, max_new_tokens=8).tokens),
        np.asarray(eng.generate(p, max_new_tokens=8).tokens),
    )
    _, cache = eng.prefill(p)
    assert cache.k.sharding.spec[1] == "dp" and cache.k.sharding.spec[3] == "tp"


def test_tp_engine_rejects_indivisible_heads(model):
    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=8), jax.devices()[:8])
    with pytest.raises(ValueError, match="n_kv_heads"):
        Engine(cfg, params, batch_size=2, max_len=64, mesh=mesh)


def test_tp_engine_kv_quant(model):
    """int8 KV composes with TP sharding: scale pools shard with their
    values."""
    import dataclasses

    cfg, _ = model
    cfg8 = dataclasses.replace(cfg, kv_quant=True)
    params = jax.jit(lambda: init_params(cfg8, jax.random.key(0)))()
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    single = Engine(cfg8, params, batch_size=2, max_len=64)
    sharded = Engine(cfg8, params, batch_size=2, max_len=64, mesh=mesh)
    p = prompt(cfg)
    np.testing.assert_array_equal(
        np.asarray(single.generate(p, max_new_tokens=8).tokens),
        np.asarray(sharded.generate(p, max_new_tokens=8).tokens),
    )
    _, cache = sharded.prefill(p)
    assert cache.k_scale.sharding.spec[3] == "tp"


def test_tp_engine_warm_compile_donates(model):
    """The warm-up AOT compile must carry the real shardings: a sharding-less
    lowering builds a different executable whose cache donation can't alias
    (doubled HBM traffic on the TP path) and warms nothing."""
    import warnings

    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    eng = Engine(cfg, params, batch_size=2, max_len=64, mesh=mesh)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        eng.generate(prompt(cfg), max_new_tokens=16)
    donated = [x for x in w if "donated" in str(x.message).lower()]
    assert not donated, [str(x.message) for x in donated]


def test_tp_engine_speculative_decoding_exact(model):
    """Speculative decoding under a tp mesh: verify-pass cache stays on its
    shardings, tokens exact vs the single-device greedy engine."""
    cfg, params = model
    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    single = Engine(cfg, params, batch_size=1, max_len=64)
    sharded = Engine(cfg, params, batch_size=1, max_len=64, mesh=mesh)
    p = jnp.asarray([[5, 9, 2, 11] * 4], jnp.int32)
    want = single.generate(p, max_new_tokens=20)
    got = sharded.generate_speculative(p, max_new_tokens=20, gamma=6, ngram=3)
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(want.tokens))
