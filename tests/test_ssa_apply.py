"""Server-side apply with per-field managers (VERDICT r4 missing #1 — the
one reference capability the framework previously did not match).

The reference server-side-applies its derived objects with fieldManager
"lws" + force ownership (leaderworkerset_controller.go:375-411), which lets
an external controller durably co-own DISJOINT fields of the same object.
Store.apply implements the same contract: per-leaf-path ownership recorded
in meta.managed_fields, FieldManagerConflict (HTTP 409) without force,
ownership transfer with it, k8s unset-is-delete for abandoned fields, and
apply-as-no-op when nothing changes. The LWS controller's leader-groupset
write now goes through it, so the co-ownership test below exercises the
REAL reconcile loop, not a synthetic applier.
"""

import pytest

from lws_tpu.core.store import FieldManagerConflict, Store
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder


TMPL = {
    "size": 2,
    "worker_template": {"spec": {"containers": [{"name": "w", "image": "i:v1"}]}},
}


def test_apply_creates_and_records_ownership():
    s = Store()
    obj = s.apply(
        "LeaderWorkerSet", "default", "demo",
        {"spec": {"replicas": 3, "leader_worker_template": TMPL}},
        field_manager="a",
    )
    assert obj.spec.replicas == 3
    assert ["spec", "replicas"] in obj.meta.managed_fields["a"]


def test_conflict_requires_force_and_force_transfers():
    s = Store()
    s.apply("LeaderWorkerSet", "default", "demo",
            {"spec": {"replicas": 3, "leader_worker_template": TMPL}},
            field_manager="a")
    with pytest.raises(FieldManagerConflict) as e:
        s.apply("LeaderWorkerSet", "default", "demo",
                {"spec": {"replicas": 5}}, field_manager="b")
    assert e.value.conflicts == [(("spec", "replicas"), "a")]
    obj = s.apply("LeaderWorkerSet", "default", "demo",
                  {"spec": {"replicas": 5}}, field_manager="b", force=True)
    assert obj.spec.replicas == 5
    assert ["spec", "replicas"] in obj.meta.managed_fields["b"]
    assert ["spec", "replicas"] not in obj.meta.managed_fields["a"]


def test_disjoint_managers_coexist_and_unset_deletes():
    s = Store()
    s.apply("LeaderWorkerSet", "default", "demo",
            {"spec": {"replicas": 3, "leader_worker_template": TMPL},
             "meta": {"labels": {"app": "x"}}}, field_manager="a")
    s.apply("LeaderWorkerSet", "default", "demo",
            {"meta": {"annotations": {"team": "ml"}}}, field_manager="ext")
    # a re-applies WITHOUT the label: its abandoned field is removed (k8s
    # unset-is-delete); ext's annotation is untouched.
    obj = s.apply("LeaderWorkerSet", "default", "demo",
                  {"spec": {"replicas": 3, "leader_worker_template": TMPL}},
                  field_manager="a")
    assert "app" not in obj.meta.labels
    assert obj.meta.annotations["team"] == "ml"


def test_shape_mismatch_cannot_bypass_ownership():
    """Applying None/a scalar OVER a dict subtree that contains another
    manager's leaf (or a dict UNDER another's scalar leaf) must conflict —
    exact-path matching alone would let it silently delete the field."""
    s = Store()
    s.apply("LeaderWorkerSet", "default", "demo",
            {"spec": {"replicas": 3, "leader_worker_template": TMPL}},
            field_manager="a")
    s.apply("LeaderWorkerSet", "default", "demo",
            {"meta": {"annotations": {"team": "ml"}}}, field_manager="ext")
    with pytest.raises(FieldManagerConflict):
        s.apply("LeaderWorkerSet", "default", "demo",
                {"meta": {"annotations": None}}, field_manager="b")
    obj = s.get("LeaderWorkerSet", "default", "demo")
    assert obj.meta.annotations["team"] == "ml"
    # Force still works and transfers the whole subtree's ownership.
    obj = s.apply("LeaderWorkerSet", "default", "demo",
                  {"meta": {"annotations": {}}}, field_manager="b", force=True)
    assert obj.meta.annotations == {}
    assert "ext" not in obj.meta.managed_fields


def test_refining_own_leaf_does_not_delete_it():
    """{} -> {"app": "x"} refines the manager's own leaf into a deeper one;
    the unset-is-delete pass must not treat the old ancestor path as
    abandoned and delete the value just applied."""
    s = Store()
    s.apply("LeaderWorkerSet", "default", "demo",
            {"spec": {"replicas": 1, "leader_worker_template": TMPL},
             "meta": {"labels": {}}}, field_manager="a")
    obj = s.apply("LeaderWorkerSet", "default", "demo",
                  {"spec": {"replicas": 1, "leader_worker_template": TMPL},
                   "meta": {"labels": {"app": "x"}}}, field_manager="a")
    assert obj.meta.labels == {"app": "x"}, obj.meta.labels


def test_noop_apply_commits_nothing():
    s = Store()
    fields = {"spec": {"replicas": 3, "leader_worker_template": TMPL}}
    rv = s.apply("LeaderWorkerSet", "default", "demo", fields,
                 field_manager="a").meta.resource_version
    events = []
    s.watch(events.append)
    obj = s.apply("LeaderWorkerSet", "default", "demo", fields, field_manager="a")
    assert obj.meta.resource_version == rv
    assert events == []


def test_plain_update_preserves_managed_fields():
    s = Store()
    s.apply("LeaderWorkerSet", "default", "demo",
            {"spec": {"replicas": 3, "leader_worker_template": TMPL}},
            field_manager="a")
    fresh = s.get("LeaderWorkerSet", "default", "demo")
    fresh.meta.managed_fields = {}  # a fresh desired-state object wouldn't carry it
    fresh.spec.replicas = 4
    stored = s.update(fresh)
    assert "a" in stored.meta.managed_fields


def test_external_manager_coowns_controller_derived_groupset():
    """The reference's whole point: an external controller applies its own
    annotation on the LWS-derived leader groupset; the LWS controller keeps
    reconciling (incl. a full rolling update) with fieldManager "lws" and
    the external field SURVIVES every pass."""
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()

    cp.store.apply(
        "GroupSet", "default", "sample",
        {"meta": {"annotations": {"ext.io/budget": "gold"}}},
        field_manager="ext-controller",
    )
    # Trigger a real rollout: the controller rewrites the groupset spec.
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.leader_worker_template.worker_template.spec.containers[0].image = "worker:v2"
    cp.store.update(lws)
    cp.run_until_stable()

    gs = cp.store.get("GroupSet", "default", "sample")
    assert gs.meta.annotations["ext.io/budget"] == "gold"  # survived the rollout
    assert gs.spec.template.spec.containers[0].image == "worker:v2"
    assert "lws" in gs.meta.managed_fields and "ext-controller" in gs.meta.managed_fields
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.updated_replicas == 2

    # And the controller's own fields are PROTECTED: an external apply to a
    # controller-owned field conflicts without force.
    with pytest.raises(FieldManagerConflict):
        cp.store.apply(
            "GroupSet", "default", "sample",
            {"meta": {"annotations": {"ext.io/budget": "gold"}},
             "spec": {"replicas": 7}},
            field_manager="ext-controller",
        )


def test_null_for_container_field_is_rejected_before_commit():
    """{\"meta\": {\"labels\": null}} must 400, not commit labels=None and
    crash the label indexer mid-write (store-corruption regression)."""
    from lws_tpu.core.store import AdmissionError

    s = Store()
    s.apply("LeaderWorkerSet", "default", "demo",
            {"spec": {"replicas": 1, "leader_worker_template": TMPL}},
            field_manager="a")
    rv = s.get("LeaderWorkerSet", "default", "demo").meta.resource_version
    with pytest.raises(AdmissionError):
        s.apply("LeaderWorkerSet", "default", "demo",
                {"meta": {"labels": None}}, field_manager="a", force=True)
    obj = s.get("LeaderWorkerSet", "default", "demo")
    assert obj.meta.resource_version == rv  # nothing committed
    assert isinstance(obj.meta.labels, dict)


def test_apply_survives_concurrent_delete():
    """A delete landing between apply's read and write must re-enter the
    loop and take the create branch, not escape as NotFoundError (the
    LWS-teardown race against the reconciler's own apply)."""
    s = Store()
    fields = {"spec": {"replicas": 1, "leader_worker_template": TMPL}}
    s.apply("LeaderWorkerSet", "default", "demo", fields, field_manager="a")

    real_update = s.update
    state = {"deleted": False}

    def delete_then_update(obj):
        if not state["deleted"]:
            state["deleted"] = True
            s.delete("LeaderWorkerSet", "default", "demo")
        return real_update(obj)

    s.update = delete_then_update
    try:
        obj = s.apply("LeaderWorkerSet", "default", "demo",
                      {"spec": {"replicas": 2, "leader_worker_template": TMPL}},
                      field_manager="a")
    finally:
        s.update = real_update
    assert obj.spec.replicas == 2  # recreated through the create branch


def test_managed_fields_survive_wal_failover(tmp_path):
    """SSA ownership is cluster state: after a kill -9 and WAL replay on a
    fresh store, the co-ownership records (and so conflict protection) must
    be exactly what was acknowledged before the crash."""
    from lws_tpu.core.wal import StateDir

    store = Store()
    sd = StateDir(str(tmp_path))
    sd.acquire()
    sd.attach(store)
    store.apply("LeaderWorkerSet", "default", "demo",
                {"spec": {"replicas": 3, "leader_worker_template": TMPL}},
                field_manager="a")
    store.apply("LeaderWorkerSet", "default", "demo",
                {"meta": {"annotations": {"team": "ml"}}}, field_manager="ext")
    sd.close()

    store2 = Store()
    sd2 = StateDir(str(tmp_path))
    sd2.acquire()
    sd2.attach(store2)
    obj = store2.get("LeaderWorkerSet", "default", "demo")
    assert ["spec", "replicas"] in obj.meta.managed_fields["a"]
    assert ["meta", "annotations", "team"] in obj.meta.managed_fields["ext"]
    with pytest.raises(FieldManagerConflict):
        store2.apply("LeaderWorkerSet", "default", "demo",
                     {"spec": {"replicas": 9}}, field_manager="b")
    sd2.close()


def test_http_apply_roundtrip_and_409(tmp_path):
    from lws_tpu.client import ApiError, RemoteClient
    from lws_tpu.runtime.server import ApiServer

    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        client = RemoteClient(f"http://127.0.0.1:{api.port}")
        out = client.server_side_apply(
            "LeaderWorkerSet", "default", "web",
            {"spec": {"replicas": 2, "leader_worker_template": TMPL}},
            field_manager="cli",
        )
        assert out["spec"]["replicas"] == 2
        with pytest.raises(ApiError) as e:
            client.server_side_apply(
                "LeaderWorkerSet", "default", "web",
                {"spec": {"replicas": 9}}, field_manager="other",
            )
        assert e.value.code == 409
        out = client.server_side_apply(
            "LeaderWorkerSet", "default", "web",
            {"spec": {"replicas": 9}}, field_manager="other", force=True,
        )
        assert out["spec"]["replicas"] == 9
    finally:
        api.stop()
