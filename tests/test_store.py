"""Core store semantics: versioning, conflicts, cascade GC, watches, admission."""

import pytest

from lws_tpu.api.pod import Pod
from lws_tpu.api.groupset import GroupSet
from lws_tpu.core.store import (
    AdmissionError,
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
    new_meta,
)


def make_pod(name, **kw):
    return Pod(meta=new_meta(name, **kw))


def test_create_assigns_identity():
    store = Store()
    pod = store.create(make_pod("p0"))
    assert pod.meta.uid
    assert pod.meta.resource_version > 0
    assert pod.meta.generation == 1
    with pytest.raises(AlreadyExistsError):
        store.create(make_pod("p0"))


def test_isolation_no_aliasing():
    store = Store()
    pod = store.create(make_pod("p0"))
    pod.meta.labels["mutated"] = "yes"
    fetched = store.get("Pod", "default", "p0")
    assert "mutated" not in fetched.meta.labels


def test_optimistic_concurrency():
    store = Store()
    pod = store.create(make_pod("p0"))
    first = store.get("Pod", "default", "p0")
    second = store.get("Pod", "default", "p0")
    first.meta.labels["a"] = "1"
    store.update(first)
    second.meta.labels["b"] = "2"
    with pytest.raises(ConflictError):
        store.update(second)


def test_generation_bumps_on_spec_change_only():
    store = Store()
    pod = store.create(make_pod("p0"))
    pod.status.ready = True
    pod = store.update_status(pod)
    assert pod.meta.generation == 1
    pod.spec.subdomain = "svc"
    pod = store.update(pod)
    assert pod.meta.generation == 2


def test_status_update_preserves_spec():
    store = Store()
    pod = store.create(make_pod("p0"))
    stale = store.get("Pod", "default", "p0")
    pod.spec.subdomain = "svc"
    pod = store.update(pod)
    pod.status.ready = True
    updated = store.update_status(pod)
    assert updated.spec.subdomain == "svc"
    assert updated.status.ready


def test_cascade_delete():
    store = Store()
    gs = store.create(GroupSet(meta=new_meta("leader")))
    child = store.create(Pod(meta=new_meta("leader-0", owners=[gs])))
    grandchild = store.create(GroupSet(meta=new_meta("leader-0-workers", owners=[child])))
    store.create(Pod(meta=new_meta("leader-0-workers-1", owners=[grandchild])))
    store.delete("GroupSet", "default", "leader")
    assert store.list("Pod") == []
    assert store.list("GroupSet") == []


def test_watch_events():
    store = Store()
    events = []
    store.watch(lambda e: events.append((e.type, e.obj.meta.name)))
    pod = store.create(make_pod("p0"))
    pod.spec.subdomain = "s"
    store.update(pod)
    store.delete("Pod", "default", "p0")
    assert events == [("ADDED", "p0"), ("MODIFIED", "p0"), ("DELETED", "p0")]


def test_admission_mutate_and_validate():
    store = Store()

    def mutator(obj, old):
        obj.meta.labels["mutated"] = "true"

    def validator(obj, old):
        if obj.meta.name == "bad":
            raise AdmissionError("bad name")

    store.register_mutator("Pod", mutator)
    store.register_validator("Pod", validator)
    pod = store.create(make_pod("ok"))
    assert pod.meta.labels["mutated"] == "true"
    with pytest.raises(AdmissionError):
        store.create(make_pod("bad"))


def test_missing_get():
    store = Store()
    with pytest.raises(NotFoundError):
        store.get("Pod", "default", "nope")
    assert store.try_get("Pod", "default", "nope") is None


def test_watch_events_delivered_in_commit_order_across_threads():
    """Concurrent writers must never deliver watch events out of commit
    order (the apiserver/client-go per-object resourceVersion guarantee):
    events are enqueued under the store lock and drained FIFO."""
    import threading

    store = Store()
    seen = []
    seen_lock = threading.Lock()

    def on_event(ev):
        with seen_lock:
            seen.append((ev.obj.meta.name, ev.obj.meta.resource_version))

    store.watch(on_event)

    names = [f"p{i}" for i in range(8)]
    for n in names:
        store.create(make_pod(n))

    def writer(name):
        for _ in range(50):
            while True:
                try:
                    pod = store.get("Pod", "default", name)
                    pod.meta.annotations["n"] = str(pod.meta.resource_version)
                    store.update(pod)
                    break
                except ConflictError:
                    continue

    threads = [threading.Thread(target=writer, args=(n,)) for n in names]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Per-object: resource_versions strictly increase in delivery order.
    per_obj = {}
    for name, rv in seen:
        assert per_obj.get(name, 0) < rv, f"stale event for {name}: {rv}"
        per_obj[name] = rv
    # Globally: delivery order equals commit order (rv assignment order).
    rvs = [rv for _, rv in seen]
    assert rvs == sorted(rvs)


def test_watcher_writing_to_store_keeps_order():
    """A watcher that writes back into the store (re-entrant dispatch) must
    still see FIFO delivery, not a deadlock."""
    store = Store()
    seen = []

    def on_event(ev):
        seen.append((ev.type, ev.obj.meta.name))
        if ev.obj.meta.name == "trigger" and ev.type == "ADDED":
            store.create(make_pod("cascade"))

    store.watch(on_event)
    store.create(make_pod("trigger"))
    assert seen == [("ADDED", "trigger"), ("ADDED", "cascade")]


def test_nested_event_reaches_all_watchers_after_trigger():
    """A watcher that writes in reaction to an event must not cause LATER
    watchers to see the consequence before the trigger: the nested write only
    enqueues; the outer drain finishes delivering the trigger first."""
    store = Store()
    w1_seen, w2_seen = [], []

    def w1(ev):
        w1_seen.append(ev.obj.meta.name)
        if ev.obj.meta.name == "trigger":
            store.create(make_pod("cascade"))

    def w2(ev):
        w2_seen.append(ev.obj.meta.name)

    store.watch(w1)
    store.watch(w2)
    store.create(make_pod("trigger"))
    assert w1_seen == ["trigger", "cascade"]
    assert w2_seen == ["trigger", "cascade"]


def test_admission_hook_writing_to_store_does_not_deadlock():
    """A mutator that writes a side object (nested write under the store
    lock) must neither deadlock nor deliver events out of commit order."""
    store = Store()
    seen = []
    store.watch(lambda ev: seen.append(ev.obj.meta.name))

    def mutator(obj, old):
        if old is None and obj.meta.name == "main":
            store.create(make_pod("side"))

    store.register_mutator("Pod", mutator)
    store.create(make_pod("main"))
    # The side object commits first (inside main's admission), so its event
    # is first in commit order.
    assert seen == ["side", "main"]
    assert store.try_get("Pod", "default", "side") is not None
