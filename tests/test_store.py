"""Core store semantics: versioning, conflicts, cascade GC, watches, admission."""

import pytest

from lws_tpu.api.pod import Pod
from lws_tpu.api.groupset import GroupSet
from lws_tpu.core.store import (
    AdmissionError,
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
    new_meta,
)


def make_pod(name, **kw):
    return Pod(meta=new_meta(name, **kw))


def test_create_assigns_identity():
    store = Store()
    pod = store.create(make_pod("p0"))
    assert pod.meta.uid
    assert pod.meta.resource_version > 0
    assert pod.meta.generation == 1
    with pytest.raises(AlreadyExistsError):
        store.create(make_pod("p0"))


def test_isolation_no_aliasing():
    store = Store()
    pod = store.create(make_pod("p0"))
    pod.meta.labels["mutated"] = "yes"
    fetched = store.get("Pod", "default", "p0")
    assert "mutated" not in fetched.meta.labels


def test_optimistic_concurrency():
    store = Store()
    pod = store.create(make_pod("p0"))
    first = store.get("Pod", "default", "p0")
    second = store.get("Pod", "default", "p0")
    first.meta.labels["a"] = "1"
    store.update(first)
    second.meta.labels["b"] = "2"
    with pytest.raises(ConflictError):
        store.update(second)


def test_generation_bumps_on_spec_change_only():
    store = Store()
    pod = store.create(make_pod("p0"))
    pod.status.ready = True
    pod = store.update_status(pod)
    assert pod.meta.generation == 1
    pod.spec.subdomain = "svc"
    pod = store.update(pod)
    assert pod.meta.generation == 2


def test_status_update_preserves_spec():
    store = Store()
    pod = store.create(make_pod("p0"))
    stale = store.get("Pod", "default", "p0")
    pod.spec.subdomain = "svc"
    pod = store.update(pod)
    pod.status.ready = True
    updated = store.update_status(pod)
    assert updated.spec.subdomain == "svc"
    assert updated.status.ready


def test_cascade_delete():
    store = Store()
    gs = store.create(GroupSet(meta=new_meta("leader")))
    child = store.create(Pod(meta=new_meta("leader-0", owners=[gs])))
    grandchild = store.create(GroupSet(meta=new_meta("leader-0-workers", owners=[child])))
    store.create(Pod(meta=new_meta("leader-0-workers-1", owners=[grandchild])))
    store.delete("GroupSet", "default", "leader")
    assert store.list("Pod") == []
    assert store.list("GroupSet") == []


def test_watch_events():
    store = Store()
    events = []
    store.watch(lambda e: events.append((e.type, e.obj.meta.name)))
    pod = store.create(make_pod("p0"))
    pod.spec.subdomain = "s"
    store.update(pod)
    store.delete("Pod", "default", "p0")
    assert events == [("ADDED", "p0"), ("MODIFIED", "p0"), ("DELETED", "p0")]


def test_admission_mutate_and_validate():
    store = Store()

    def mutator(obj, old):
        obj.meta.labels["mutated"] = "true"

    def validator(obj, old):
        if obj.meta.name == "bad":
            raise AdmissionError("bad name")

    store.register_mutator("Pod", mutator)
    store.register_validator("Pod", validator)
    pod = store.create(make_pod("ok"))
    assert pod.meta.labels["mutated"] == "true"
    with pytest.raises(AdmissionError):
        store.create(make_pod("bad"))


def test_missing_get():
    store = Store()
    with pytest.raises(NotFoundError):
        store.get("Pod", "default", "nope")
    assert store.try_get("Pod", "default", "nope") is None
