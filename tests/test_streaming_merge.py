"""Streaming exposition merge: byte-equivalence against the dict-based
oracle, and shard-failure isolation.

The /metrics/fleet surface renders shard-by-shard through
`metrics.StreamingMerger` with peak memory O(largest shard); the dict-based
`merge_expositions` remains the oracle. These tests pin the contract the
tentpole rests on: for ANY parser-valid source set the streamed
concatenation is BYTE-identical to the oracle's output — across exemplars,
awkward label values, HELP/TYPE dedup, and cardinality-cap drops — and a
malformed shard costs that shard, not the fleet view.
"""

from __future__ import annotations

import random

import pytest

from lws_tpu.core import metrics
from lws_tpu.core.metrics import (
    DROPPED_METRIC,
    MetricsRegistry,
    StreamingMerger,
    merge_expositions,
    parse_exposition,
)

# ---------------------------------------------------------------------------
# Deterministic exposition generator (property-style: many seeds, same code
# path a worker's registry render takes — generated through a REAL registry
# so the inputs are exactly what production shards look like).

_FAMILIES = (
    ("serving_requests_total", "counter"),
    ("serving_active_slots", "gauge"),
    ("serving_ttft_seconds", "histogram"),
    ("zz_custom_total", "counter"),
    ("aa_first_total", "counter"),
)

# Awkward-but-legal label values: spaces and quotes never render (the
# registry writes values verbatim inside quotes), but dots, slashes,
# colons, dashes, and backslashes all appear in pod names, image refs, and
# file paths — the parse/render round trip must keep them byte-stable.
_VALUES = ("paged", "a.b-c", "ns/pod-0", "rev:12", "w\\x", "chat", "")


def _random_source(rng: random.Random, i: int) -> tuple[dict, str]:
    reg = MetricsRegistry(max_label_sets=64)
    for _ in range(rng.randrange(1, 12)):
        fam, kind = _FAMILIES[rng.randrange(len(_FAMILIES))]
        labels = {}
        for k in ("engine", "klass", "path")[: rng.randrange(3)]:
            labels[k] = _VALUES[rng.randrange(len(_VALUES))]
        if kind == "counter":
            reg.inc(fam, labels, float(rng.randrange(1, 100)))
        elif kind == "gauge":
            reg.set(fam, rng.random() * 10, labels)
        else:
            exemplar = None
            if rng.random() < 0.5:
                exemplar = {"trace_id": f"t{i}-{rng.randrange(999)}"}
            reg.observe(fam, rng.random() * 2, labels, exemplar=exemplar)
    extra = {"instance": f"w{i}"}
    if rng.random() < 0.5:
        extra["role"] = "prefill" if rng.random() < 0.5 else "decode"
    return extra, reg.render()


def _stream(sources, **kw) -> str:
    return "".join(StreamingMerger(**kw).merge(sources))


@pytest.mark.parametrize("seed", range(8))
def test_streaming_merge_matches_oracle_bytes(seed):
    rng = random.Random(f"merge:{seed}")
    sources = [_random_source(rng, i) for i in range(rng.randrange(1, 7))]
    assert _stream(sources) == merge_expositions(sources)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("cap", [1, 2, 512])
def test_streaming_merge_matches_oracle_under_cardinality_cap(seed, cap):
    """Cap drops are the hard case: the drop counter family renders LAST
    and its admission order is the oracle's source order, not k-way walk
    order."""
    rng = random.Random(f"cap:{seed}")
    sources = [_random_source(rng, i) for i in range(rng.randrange(2, 6))]
    assert (_stream(sources, max_label_sets=cap)
            == merge_expositions(sources, max_label_sets=cap))


@pytest.mark.parametrize("seed", range(4))
def test_streaming_merge_of_merge_outputs_matches_oracle(seed):
    """The fleet path re-merges per-shard MERGE OUTPUTS (which may carry
    their own trailing drop-counter families) — the exact two-tier shape
    /metrics/fleet streams."""
    rng = random.Random(f"tier:{seed}")
    shards = []
    for s in range(3):
        members = [_random_source(rng, s * 10 + i) for i in range(3)]
        shards.append(({}, merge_expositions(members, max_label_sets=2)))
    assert _stream(shards) == merge_expositions(shards)
    assert DROPPED_METRIC in _stream(shards)  # the case actually exercised


def test_streaming_merge_dedups_help_and_type_blocks():
    srcs = [({"instance": f"w{i}"},
             "# HELP zz_custom_total custom\n# TYPE zz_custom_total counter\n"
             "zz_custom_total 1.0\n") for i in range(4)]
    out = _stream(srcs)
    assert out == merge_expositions(srcs)
    assert out.count("# TYPE zz_custom_total") == 1
    assert out.count("# HELP zz_custom_total") == 1
    assert out.count('zz_custom_total{instance="w2"} 1.0') == 1


def test_streaming_merge_preserves_exemplars_and_escapish_values():
    reg = MetricsRegistry()
    reg.observe("serving_ttft_seconds", 0.07,
                {"engine": "paged", "path": "a\\b/c.d:e"},
                exemplar={"trace_id": "abc123"})
    srcs = [({"instance": "w0"}, reg.render())]
    out = _stream(srcs)
    assert out == merge_expositions(srcs)
    assert "# {" in out and "abc123" in out
    assert 'path="a\\b/c.d:e"' in out
    # And the merged text stays parser-valid end to end.
    fams = parse_exposition(out)
    assert "serving_ttft_seconds" in fams


def test_uncapped_root_merge_matches_oracle_above_default_cap():
    """The fleet root is UNCAPPED in both merge paths (shards cap
    upstream): at 1,000 instances a capped root would drop real workers.
    merge_expositions(max_label_sets=None) must mirror the streaming
    default past the 512 default cap."""
    srcs = [({"instance": f"w{i:04d}"}, "serving_requests_total 1.0\n")
            for i in range(600)]
    uncapped = _stream(srcs)
    assert uncapped == merge_expositions(srcs, max_label_sets=None)
    assert uncapped.count("serving_requests_total{") == 600
    assert DROPPED_METRIC not in uncapped
    # And the capped pair still agrees with itself.
    assert (_stream(srcs, max_label_sets=512) == merge_expositions(srcs))


def test_streaming_merge_empty_sources_render_empty_exposition():
    assert _stream([]) == merge_expositions([])
    assert _stream([({}, "")]) == merge_expositions([({}, "")])


def test_streaming_merger_is_incremental_not_monolithic():
    """The generator must yield one block per family, not buffer the whole
    text — the O(largest shard) memory bound depends on it."""
    rng = random.Random("chunks")
    sources = [_random_source(rng, i) for i in range(4)]
    chunks = list(StreamingMerger().merge(sources))
    assert len(chunks) > 1
    fam_count = len(parse_exposition("".join(chunks)))
    assert len(chunks) == fam_count  # one yielded chunk per family block


def test_malformed_shard_is_isolated_not_fatal():
    """drop_malformed: a shard answering garbage costs THAT shard; the
    remaining shards still merge byte-identically to the oracle over the
    surviving sources."""
    rng = random.Random("broken")
    good = [_random_source(rng, i) for i in range(3)]
    bad = ({"instance": "w-broken"},
           "serving_requests_total{ 1.0\ntotal garbage }{\n")
    merger = StreamingMerger(drop_malformed=True)
    out = "".join(merger.merge([good[0], bad, good[1], good[2]]))
    assert merger.dropped_sources == [1]
    assert out == merge_expositions(good)


def test_malformed_shard_without_drop_flag_raises():
    with pytest.raises(ValueError):
        _stream([({}, "not { valid\n")])


def test_fleet_render_counts_dropped_shards():
    """FleetCollector.render_fleet_chunks survives a poisoned (cached)
    shard text and counts it via lws_fleet_shards_dropped_total — the
    fleet view keeps serving the healthy shards."""
    import time as _time

    from lws_tpu.api.pod import Container, EnvVar, Pod, PodPhase, PodSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime.fleet import FleetCollector

    reg = MetricsRegistry()
    reg.inc("racetest_control_total")
    pod = Pod(
        meta=new_meta("sim-poison-0"),
        spec=PodSpec(containers=[Container(
            name="w", command=["sleep", "1"],
            env=[EnvVar("LWS_TPU_METRICS_PORT", "1")],
        )]),
    )
    pod.status.phase = PodPhase.RUNNING
    pod.status.ready = True
    pod.status.address = "127.0.0.1"

    class _OnePodStore:
        def list(self, kind):
            return [pod] if kind == "Pod" else []

    fc = FleetCollector(_OnePodStore(), control_registries=(reg,),
                        metrics_registry=reg, cache_ttl_s=3600.0)
    # A fresh, member-matched cache entry whose TEXT is garbage: the shard
    # is current (no re-scrape), so the streamed merge is what must cope.
    fc._shard_cache["default-0"] = {
        "text": "garbage { text\n", "at": _time.monotonic(),
        "members": ("sim-poison-0",), "scraped": 1, "failed": 0, "skipped": 0,
    }
    text = fc.render_fleet()
    assert "racetest_control_total" in text
    fams = parse_exposition(text)
    assert "racetest_control_total" in fams
    assert metrics.render_exposition(reg).count(
        "lws_fleet_shards_dropped_total 1.0") == 1
