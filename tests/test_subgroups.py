"""Subgroups end-to-end: subgroup index/key labels, per-subgroup TPU hostname
windows, LeaderExcluded, sub-slice exclusive placement — the TP x PP
orchestration shape (SURVEY §2.10, BASELINE config #4)."""

from lws_tpu.api import contract
from lws_tpu.api.types import SubGroupPolicyType
from lws_tpu.core.store import AdmissionError
from lws_tpu.runtime import ControlPlane
from lws_tpu.sched import make_slice_nodes
from lws_tpu.testing import LWSBuilder, assert_valid_lws, lws_pods

import pytest


def env_map(pod):
    return {e.name: e.value for e in pod.spec.containers[0].env}


def test_subgroup_labels_and_tpu_windows():
    # size=8, subGroupSize=4, leader holds TPUs: subgroup 0 = leader+1..3,
    # subgroup 1 = 4..7 with shifted window.
    cp = ControlPlane(auto_ready=True)
    cp.create(
        LWSBuilder().replicas(1).size(8).tpu_chips(4)
        .leader_template(tpu_chips=4).subgroup(4).build()
    )
    cp.run_until_stable()
    assert_valid_lws(cp.store, "sample")
    pods = {p.meta.name: p for p in lws_pods(cp.store, "sample")}
    assert len(pods) == 8

    leader = pods["sample-0"]
    assert leader.meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert env_map(leader)[contract.TPU_WORKER_ID] == "0"
    assert env_map(leader)[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-0.sample,sample-0-1.sample,sample-0-2.sample,sample-0-3.sample"
    )

    w2 = pods["sample-0-2"]
    assert w2.meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert (
        w2.meta.labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY]
        == leader.meta.labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY]
    )

    w5 = pods["sample-0-5"]
    assert w5.meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    assert w5.meta.labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY] != (
        leader.meta.labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY]
    )
    env5 = env_map(w5)
    assert env5[contract.TPU_WORKER_ID] == "1"  # 5 % 4
    # Window shifted left because the leader holds TPUs.
    assert env5[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-0-4.sample,sample-0-5.sample,sample-0-6.sample,sample-0-7.sample"
    )
    # Subgroup hints surfaced to JAX bootstrap.
    assert env5[contract.LWS_SUBGROUP_SIZE] == "4"
    assert env5[contract.LWS_SUBGROUP_INDEX] == "1"


def test_leader_excluded_subgroups():
    # size=9, sgs=4, LeaderExcluded: leader in no subgroup, workers 1..8 in 2.
    cp = ControlPlane(auto_ready=True)
    cp.create(
        LWSBuilder().replicas(1).size(9).tpu_chips(4)
        .leader_template(tpu_chips=0)  # LeaderExcluded: leader holds no chips
        .subgroup(4, SubGroupPolicyType.LEADER_EXCLUDED).build()
    )
    cp.run_until_stable()
    assert_valid_lws(cp.store, "sample")
    pods = {p.meta.name: p for p in lws_pods(cp.store, "sample")}
    leader = pods["sample-0"]
    assert contract.SUBGROUP_INDEX_LABEL_KEY not in leader.meta.labels
    assert pods["sample-0-4"].meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert pods["sample-0-5"].meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    env4 = env_map(pods["sample-0-4"])
    assert env4[contract.TPU_WORKER_ID] == "3"  # (4-1) % 4
    assert env4[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-0-1.sample,sample-0-2.sample,sample-0-3.sample,sample-0-4.sample"
    )


def test_leader_excluded_with_tpu_leader_rejected():
    cp = ControlPlane()
    with pytest.raises(AdmissionError):
        cp.create(
            LWSBuilder().replicas(1).size(9).tpu_chips(4)
            .leader_template(tpu_chips=4)
            .subgroup(4, SubGroupPolicyType.LEADER_EXCLUDED).build()
        )


def test_subgroup_policy_immutable():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(8).subgroup(4).build())
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.leader_worker_template.sub_group_policy.sub_group_size = 2
    with pytest.raises(AdmissionError):
        cp.store.update(lws)


def test_subgroup_exclusive_placement_sub_slices():
    """subgroup-exclusive-topology: each subgroup (TP island) lands on its own
    slice — the PP x TP sub-slice shape of BASELINE config #4."""
    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    for s in range(2):
        cp.add_nodes(make_slice_nodes(f"sub-{s}", topology="2x4"))  # 2 hosts x 4 chips
    cp.create(
        LWSBuilder().replicas(1).size(4).tpu_chips(4)
        .subgroup(2)
        .annotation(contract.SUBGROUP_EXCLUSIVE_KEY_ANNOTATION_KEY, contract.NODE_TPU_SLICE_LABEL)
        .build()
    )
    cp.run_until_stable()
    pods = {p.meta.name: p for p in lws_pods(cp.store, "sample")}
    assert len(pods) == 4

    def slice_of(name):
        pod = pods[name]
        assert pod.spec.node_name, f"{name} unscheduled"
        node = cp.store.get("Node", "_cluster", pod.spec.node_name)
        return node.meta.labels[contract.NODE_TPU_SLICE_LABEL]

    # Subgroup 0 = leader + worker 1; subgroup 1 = workers 2,3 (size 4, sgs 2).
    sg0 = {slice_of("sample-0"), slice_of("sample-0-1")}
    sg1 = {slice_of("sample-0-2"), slice_of("sample-0-3")}
    assert len(sg0) == 1 and len(sg1) == 1
    assert sg0 != sg1
