"""Fleet telemetry plane (ISSUE 4): per-request SLO histograms with trace
exemplars, the flight recorder + stall watchdogs, control-plane fleet
metric aggregation, and the `lws-tpu top` renderer.

The watchdog tests drive time explicitly (beat(now=...)/check_now(now=...))
so stall windows need no sleeping; the fleet tests run REAL worker
telemetry HTTP servers scraped over localhost sockets by a real control
plane — the same path the multi-process e2e (test_e2e_disagg) exercises
with separate OS processes."""

import json
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.core import flightrecorder, metrics, trace
from lws_tpu.core.flightrecorder import (
    BacklogRule,
    FlightRecorder,
    HotLoopRule,
    StallRule,
    Watchdog,
)
from lws_tpu.core.metrics import MetricsRegistry, merge_expositions
from lws_tpu.core.slo import SLORecorder, SLOTargets
from tests.test_dns_metrics import parse_exposition

T0 = 1000.0  # arbitrary monotonic origin for time-injected watchdog tests


# ---------------------------------------------------------------------------
# SLO recorder


def test_slo_timeline_emits_histograms_and_attainment():
    reg = MetricsRegistry()
    rec = SLORecorder(SLOTargets(ttft_s=1.0, itl_s=1.0, queue_wait_s=1.0),
                      registry=reg, window=8)
    tl = rec.request("paged")
    tl.queue_wait(0.01)
    tl.first_token(0.05)
    tl.tokens(4, 0.02)  # mean ITL 0.005
    assert tl.finish() is True
    assert rec.attainment("paged") == 1.0
    fams = parse_exposition(reg.render())
    for name in ("serving_queue_wait_seconds", "serving_ttft_seconds",
                 "serving_itl_seconds"):
        assert fams[name]["type"] == "histogram"
        counts = [v for n, _, v in fams[name]["samples"] if n.endswith("_count")]
        assert counts == [1.0], (name, counts)
    assert fams["serving_slo_attainment"]["samples"][0][2] == 1.0


def test_slo_breach_degrades_attainment_window():
    reg = MetricsRegistry()
    rec = SLORecorder(SLOTargets(ttft_s=0.1, itl_s=1.0, queue_wait_s=1.0),
                      registry=reg, window=4)
    for ttft in (0.05, 0.5, 0.05, 0.05):  # one breach in four
        tl = rec.request("dense")
        tl.first_token(ttft)
        tl.finish()
    assert rec.attainment("dense") == 0.75
    assert reg.gauge_value("serving_slo_attainment", {"engine": "dense"}) == 0.75
    # The window is trailing: four clean requests push the breach out.
    for _ in range(4):
        tl = rec.request("dense")
        tl.first_token(0.01)
        tl.finish()
    assert rec.attainment("dense") == 1.0


def test_slo_observation_carries_trace_exemplar():
    reg = MetricsRegistry()
    rec = SLORecorder(registry=reg)
    tracer_enabled = trace.TRACER.enabled
    trace.TRACER.enabled = True
    try:
        with trace.span("serve.request", engine="paged") as sp:
            tl = rec.request("paged")
            tl.first_token(0.02)
            trace_id = sp.trace_id
    finally:
        trace.TRACER.enabled = tracer_enabled
    text = reg.render()
    assert f'trace_id="{trace_id}"' in text
    # The exemplar parses under the STRICT scraper-semantics validator.
    fams = parse_exposition(text)
    assert fams["serving_ttft_seconds"]["type"] == "histogram"


# ---------------------------------------------------------------------------
# Configurable histogram buckets (satellite)


def test_describe_buckets_override_default_ladder():
    metrics.describe("test_rollout_minutes_seconds", "minute-scale", buckets=(30.0, 300.0, 1800.0))
    try:
        reg = MetricsRegistry()
        reg.observe("test_rollout_minutes_seconds", 200.0)
        text = reg.render()
        assert 'le="300.0"} 1' in text
        assert 'le="5.0"' not in text  # default ladder NOT in play
    finally:
        metrics._BUCKETS.pop("test_rollout_minutes_seconds", None)
        metrics._HELP.pop("test_rollout_minutes_seconds", None)


def test_registry_bucket_override_beats_describe_and_default():
    reg = MetricsRegistry(buckets={"x_seconds": (1.0, 2.0)})
    reg.observe("x_seconds", 1.5)
    assert 'x_seconds_bucket{le="2.0"} 1' in reg.render()
    reg.set_buckets("y_seconds", (0.25,))
    reg.observe("y_seconds", 0.1)
    assert 'y_seconds_bucket{le="0.25"} 1' in reg.render()
    # Existing series keep their layout (no fabricated history).
    reg.set_buckets("x_seconds", (9.0,))
    reg.observe("x_seconds", 1.5)
    assert 'x_seconds_bucket{le="2.0"} 2' in reg.render()


def test_sub_ms_itl_buckets_do_not_collapse():
    reg = MetricsRegistry()
    reg.observe("serving_itl_seconds", 0.0002, {"engine": "paged"})
    reg.observe("serving_itl_seconds", 0.004, {"engine": "paged"})
    fams = parse_exposition(reg.render())
    by_le = {
        labels["le"]: v
        for n, labels, v in fams["serving_itl_seconds"]["samples"]
        if n.endswith("_bucket")
    }
    # The two sub-5ms observations land in DIFFERENT buckets.
    assert by_le["0.00025"] == 1.0 and by_le["0.005"] == 2.0


# ---------------------------------------------------------------------------
# Fleet exposition merge


def _worker_exposition(requests: float) -> str:
    reg = MetricsRegistry()
    reg.inc("serving_requests_total", {"engine": "paged"}, value=requests)
    reg.set("serving_active_slots", 3.0, {"engine": "paged"})
    reg.observe("serving_ttft_seconds", 0.03, {"engine": "paged"},
                exemplar={"trace_id": "abc123", "span_id": "def456"})
    return reg.render()


def test_merge_expositions_labels_help_type_roundtrip():
    merged = merge_expositions([
        ({"instance": "w0", "role": "prefill", "revision": "r1"}, _worker_exposition(2)),
        ({"instance": "w1", "role": "decode", "revision": "r1"}, _worker_exposition(5)),
    ])
    fams = parse_exposition(merged)  # strict: one TYPE block per family
    reqs = {
        labels["instance"]: (v, labels)
        for _, labels, v in fams["serving_requests_total"]["samples"]
    }
    assert reqs["w0"][0] == 2.0 and reqs["w1"][0] == 5.0
    assert reqs["w0"][1]["role"] == "prefill"
    assert reqs["w1"][1]["revision"] == "r1"
    assert fams["serving_ttft_seconds"]["type"] == "histogram"
    # HELP text survives the merge; exemplars ride the bucket lines.
    assert "# HELP serving_requests_total Requests admitted per engine" in merged
    assert 'trace_id="abc123"' in merged


def test_merge_expositions_cardinality_cap_drops_and_counts():
    sources = [
        ({"instance": f"w{i}"}, _worker_exposition(1)) for i in range(6)
    ]
    merged = merge_expositions(sources, max_label_sets=4)
    fams = parse_exposition(merged)
    assert len(fams["serving_requests_total"]["samples"]) == 4
    drops = {
        labels["metric"]: v
        for _, labels, v in fams["lws_metric_label_sets_dropped_total"]["samples"]
        if labels.get("scope") == "fleet"
    }
    assert drops["serving_requests_total"] == 2.0


# ---------------------------------------------------------------------------
# Flight recorder + watchdogs


def test_flight_recorder_ring_and_heartbeats():
    fr = FlightRecorder(ring=4)
    for i in range(6):
        fr.record("test_event", i=i)
    events = fr.events()
    assert len(events) == 4 and events[-1]["i"] == 5  # bounded, newest kept
    assert fr.events(limit=2)[0]["i"] == 4
    assert fr.events(limit=0) == []
    fr.beat("decode_ring:paged", progress=3, depth=1, now=T0)
    hb = fr.heartbeats()["decode_ring:paged"]
    assert hb["progress"] == 3 and hb["depth"] == 1


def test_flight_recorder_event_captures_trace_context():
    fr = FlightRecorder()
    enabled = trace.TRACER.enabled
    trace.TRACER.enabled = True
    try:
        with trace.span("serve.request", engine="paged") as sp:
            fr.record("pipeline_discard", engine="paged")
            trace_id = sp.trace_id
    finally:
        trace.TRACER.enabled = enabled
    assert fr.events()[-1]["trace"]["trace_id"] == trace_id


def test_stall_watchdog_trips_on_frozen_ring():
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=[StallRule("decode_ring_stall", "decode_ring:*",
                                                stall_after_s=5.0)])
    fr.beat("decode_ring:paged", progress=7, depth=2, now=T0)
    assert wd.check_now(now=T0 + 1) == {}  # inside the window: quiet
    before = metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "decode_ring_stall"})
    firing = wd.check_now(now=T0 + 10)
    assert "decode_ring_stall" in firing
    assert firing["decode_ring_stall"][0]["source"] == "decode_ring:paged"
    after = metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "decode_ring_stall"})
    assert after == before + 1
    assert metrics.REGISTRY.gauge_value(
        "lws_watchdog_active", {"watchdog": "decode_ring_stall"}) == 1.0
    # Steady firing does not re-count; recovery clears the gauge.
    wd.check_now(now=T0 + 11)
    assert metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "decode_ring_stall"}) == after
    fr.beat("decode_ring:paged", progress=8, depth=0, now=T0 + 12)
    assert wd.check_now(now=T0 + 12) == {}
    assert metrics.REGISTRY.gauge_value(
        "lws_watchdog_active", {"watchdog": "decode_ring_stall"}) == 0.0
    # The trip captured a diagnostics bundle: ring + heartbeats + metrics.
    dump = wd.last_dump
    assert dump["reason"] == "watchdog:decode_ring_stall"
    assert dump["heartbeats"]["decode_ring:paged"]["depth"] == 2
    assert "# TYPE lws_watchdog_alerts_total counter" in dump["metrics"]
    assert any(e["kind"] == "watchdog_alert" for e in dump["events"])


def test_slow_but_progressing_ring_never_trips():
    """The false-positive guard: a ring that is SLOW (one consume per 3s
    against a 5s stall window) but advancing must not alarm."""
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=[StallRule("decode_ring_stall", "decode_ring:*",
                                                stall_after_s=5.0)])
    progress = 0
    for step in range(8):  # 24 seconds of slow progress, depth always > 0
        progress += 1
        fr.beat("decode_ring:paged", progress=progress, depth=3, now=T0 + 3 * step)
        assert wd.check_now(now=T0 + 3 * step + 2) == {}, f"tripped at step {step}"
    assert wd.last_dump is None


def test_hot_loop_and_backlog_rules():
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=[
        HotLoopRule("reconcile_hot_loop", "reconcile:*", streak=100),
        BacklogRule("kv_handoff_backlog", "kv_backlog:*",
                    depth_threshold=8, sustain_s=5.0),
    ])
    fr.beat("reconcile:lws", depth=99, now=T0)
    fr.beat("kv_backlog:9000", progress=4, depth=12, now=T0)
    assert wd.check_now(now=T0 + 1) == {}  # streak under, backlog young
    fr.beat("reconcile:lws", depth=150, now=T0 + 2)
    firing = wd.check_now(now=T0 + 6)  # backlog depth 12 for 6s, no progress
    assert set(firing) == {"reconcile_hot_loop", "kv_handoff_backlog"}
    # A DRAINING backlog (progress advancing) clears even at high depth.
    fr.beat("kv_backlog:9000", progress=5, depth=12, now=T0 + 7)
    fr.beat("reconcile:lws", depth=1, now=T0 + 7)
    assert wd.check_now(now=T0 + 8) == {}


def test_manager_feeds_hot_loop_streak():
    """A reconciler requeue-looping on one key grows the heartbeat streak
    the HotLoopRule watches, and the flight recorder logs the offending
    key at the escalation point."""
    from lws_tpu.core.manager import Manager
    from lws_tpu.core.store import Store

    class Spinner:
        name = "spinner"

        def __init__(self):
            self.count = 0

        def reconcile(self, key):
            from lws_tpu.core.manager import Result

            self.count += 1
            if self.count < 120:
                return Result(requeue=True)
            return None

    store = Store()
    mgr = Manager(store)
    spinner = Spinner()
    mgr.register(spinner, {"Node": lambda o: [o.key()]})
    from lws_tpu.api.node import CLUSTER_NAMESPACE, Node
    from lws_tpu.core.store import new_meta

    store.create(Node(meta=new_meta("spin-target", namespace=CLUSTER_NAMESPACE)))
    mgr.run_until_stable(max_iterations=500)
    hb = flightrecorder.RECORDER.heartbeats()["reconcile:spinner"]
    assert hb["depth"] >= 100
    hot = [e for e in flightrecorder.RECORDER.events()
           if e["kind"] == "reconcile_hot_loop" and e["controller"] == "spinner"]
    assert hot and hot[-1]["object"] == "spin-target"


def test_pipeline_heartbeat_and_stall_injection_end_to_end():
    """An injected decode-ring stall on a REAL paged engine: a dispatched
    chunk parks in the ring (depth 1, progress frozen), the watchdog trips,
    and the dump's spans reference the stalled request's trace id."""
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    enabled, rate = trace.TRACER.enabled, trace.TRACER.sample_rate
    trace.TRACER.enabled, trace.TRACER.sample_rate = True, 1.0
    try:
        cfg = LlamaConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
            d_ff=64, max_seq_len=64, dtype=jnp.float32,
            param_dtype=jnp.float32, remat=False,
        )
        params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
        engine = PagedBatchEngine(cfg, params, slots=2, max_len=64,
                                  block_size=16, pipeline_depth=2)
        with trace.span("serve.request", engine="paged", request_id="stalled") as sp:
            rid = engine.submit(np.arange(1, 9, dtype=np.int32), 16)
            assert rid is not None
            engine.step_n(4)  # chunk rides the ring, unconsumed
            stalled_trace = sp.trace_id
        hb = flightrecorder.RECORDER.heartbeats()["decode_ring:paged"]
        assert hb["depth"] >= 1
        wd = Watchdog(rules=[StallRule("decode_ring_stall", "decode_ring:*",
                                       stall_after_s=5.0)])
        firing = wd.check_now(now=time.monotonic() + 30)
        assert "decode_ring_stall" in firing
        dump = wd.last_dump
        assert any(s.get("trace_id") == stalled_trace for s in dump["spans"]), \
            "dump does not reference the stalled request's trace"
        engine.run_until_drained()  # leave the engine clean
    finally:
        trace.TRACER.enabled, trace.TRACER.sample_rate = enabled, rate


def test_pipeline_discard_records_rollback_event():
    from lws_tpu.serving.pipeline import DecodePipeline

    pipe = DecodePipeline(depth=2, engine="paged")
    pipe.push(4, np.zeros((4, 1), np.int32), lambda h: None)
    pipe.discard()
    ev = [e for e in flightrecorder.RECORDER.events()
          if e["kind"] == "pipeline_discard"]
    assert ev and ev[-1]["chunks"] == 1 and ev[-1]["steps"] == 4


# ---------------------------------------------------------------------------
# Engine integration: SLO histograms + resolvable exemplars


def test_paged_engine_emits_slo_metrics_with_resolvable_exemplars(monkeypatch):
    from lws_tpu.core import slo
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    enabled, rate = trace.TRACER.enabled, trace.TRACER.sample_rate
    trace.TRACER.enabled, trace.TRACER.sample_rate = True, 1.0
    # A fresh registry/recorder pair: the process REGISTRY accumulates SLO
    # exemplars from every earlier engine test in the suite, whose spans the
    # bounded tracer ring has long evicted — only THIS test's exemplars can
    # be held to the resolvable-in-the-live-tracer contract.
    registry = MetricsRegistry()
    monkeypatch.setattr(slo, "RECORDER", SLORecorder(registry=registry))
    try:
        cfg = LlamaConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
            d_ff=64, max_seq_len=64, dtype=jnp.float32,
            param_dtype=jnp.float32, remat=False,
        )
        params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
        engine = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=16)
        rid = engine.submit(np.arange(1, 9, dtype=np.int32), 8)
        engine.run_until_drained()
        assert engine.result(rid) is not None
        fams = parse_exposition(registry.render())
        for name in ("serving_queue_wait_seconds", "serving_ttft_seconds",
                     "serving_itl_seconds"):
            assert any(
                labels.get("engine") == "paged" and n.endswith("_count") and v > 0
                for n, labels, v in fams[name]["samples"]
            ), name
        att = [
            v for _, labels, v in fams["serving_slo_attainment"]["samples"]
            if labels.get("engine") == "paged"
        ]
        assert att and 0.0 <= att[0] <= 1.0
        # Exemplars on the SLO buckets resolve to spans in the live tracer
        # (the /debug/traces contract).
        text = registry.render()
        known = {s["trace_id"] for s in trace.TRACER.spans()}
        exemplar_ids = {
            m.split('trace_id="')[1].split('"')[0]
            for m in text.splitlines()
            if "serving_ttft_seconds_bucket" in m and 'trace_id="' in m
        }
        assert exemplar_ids and exemplar_ids <= known
    finally:
        trace.TRACER.enabled, trace.TRACER.sample_rate = enabled, rate


# ---------------------------------------------------------------------------
# Worker telemetry server + fleet aggregation + API surface


def _make_worker_pod(name: str, port: int, role: str | None = None):
    from lws_tpu.api.pod import Container, EnvVar, Pod, PodPhase, PodSpec
    from lws_tpu.core.store import new_meta

    pod = Pod(
        meta=new_meta(name),
        spec=PodSpec(containers=[Container(
            name="w",
            command=["sleep", "1"],
            env=[EnvVar("LWS_TPU_METRICS_PORT", str(port))],
        )]),
    )
    if role is not None:
        from lws_tpu.api import disagg

        pod.meta.labels[disagg.DS_ROLE_LABEL_KEY] = role
        pod.meta.labels[disagg.DS_REVISION_LABEL_KEY] = "rev1"
    return pod


def test_fleet_scrape_merges_worker_surfaces_and_serves_http(tmp_path):
    from lws_tpu.api.pod import PodPhase
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer
    from lws_tpu.runtime.telemetry import TelemetryServer

    metrics.REGISTRY.inc("serving_requests_total", {"engine": "paged"})
    workers = [TelemetryServer(port=0) for _ in range(2)]
    for w in workers:
        w.start()
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        for i, w in enumerate(workers):
            pod = cp.store.create(_make_worker_pod(
                f"fleet-w{i}", w.port, role="prefill" if i == 0 else "decode"
            ))
            pod.status.phase = PodPhase.RUNNING
            pod.status.ready = True
            pod.status.address = "127.0.0.1"
            cp.store.update_status(pod)
        merged = cp.fleet.render_fleet(force=True)
        fams = parse_exposition(merged)
        instances = {
            labels.get("instance")
            for _, labels, _ in fams["serving_requests_total"]["samples"]
        }
        assert {"fleet-w0", "fleet-w1"} <= instances
        roles = {
            labels.get("instance"): labels.get("role")
            for _, labels, _ in fams["serving_requests_total"]["samples"]
        }
        assert roles["fleet-w0"] == "prefill" and roles["fleet-w1"] == "decode"
        # Control-plane registries ride along under their own instance.
        assert any(
            labels.get("instance") == "control-plane"
            for fam in fams.values() for _, labels, _ in fam["samples"]
        )
        assert cp.metrics.gauge_value("lws_fleet_instances") == 2.0
        # And the API server serves the same surface over HTTP.
        with urllib.request.urlopen(
            f"http://127.0.0.1:{api.port}/metrics/fleet", timeout=10
        ) as resp:
            via_http = parse_exposition(resp.read().decode())
        assert "serving_requests_total" in via_http
    finally:
        api.stop()
        for w in workers:
            w.stop()


def test_fleet_scrape_failure_degrades_per_instance():
    from lws_tpu.api.pod import PodPhase
    from lws_tpu.runtime import ControlPlane

    cp = ControlPlane()
    # Port 1: nothing listening — the scrape must fail fast and visibly.
    pod = cp.store.create(_make_worker_pod("fleet-dead", 1))
    pod.status.phase = PodPhase.RUNNING
    pod.status.ready = True
    pod.status.address = "127.0.0.1"
    cp.store.update_status(pod)
    cp.fleet.timeout_s = 0.2
    merged = cp.fleet.render_fleet(force=True)
    parse_exposition(merged)  # still valid with zero reachable workers
    assert cp.metrics.counter_value(
        "lws_fleet_scrape_errors_total", {"instance": "fleet-dead"}) == 1.0


def test_debug_endpoint_limit_validation(tmp_path):
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        for path in ("/debug/traces", "/debug/flightrecorder"):
            for bad in ("abc", "-5", "1.5"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(f"{base}{path}?limit={bad}", timeout=10)
                assert err.value.code == 400, (path, bad)
            with urllib.request.urlopen(f"{base}{path}?limit=3", timeout=10) as resp:
                assert resp.status == 200
        with urllib.request.urlopen(
            f"{base}/debug/flightrecorder?limit=5", timeout=10
        ) as resp:
            body = json.loads(resp.read().decode())
        assert set(body) == {"events", "heartbeats", "alerts", "last_dump"}
    finally:
        api.stop()


def test_metrics_exemplar_content_negotiation():
    """Classic text-format clients must get a parseable exposition with NO
    exemplar suffixes (the 0.0.4 format has no exemplar syntax); OpenMetrics
    clients get the suffixes and the OpenMetrics content type."""
    from lws_tpu.runtime.telemetry import TelemetryServer

    metrics.REGISTRY.observe(
        "serving_ttft_seconds", 0.02, {"engine": "paged"},
        exemplar={"trace_id": "negotiate1", "span_id": "s1"},
    )
    server = TelemetryServer(port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            classic = resp.read().decode()
            assert "openmetrics" not in (resp.headers.get("Content-Type") or "")
        assert 'trace_id="negotiate1"' not in classic
        assert " # {" not in classic
        req = urllib.request.Request(
            f"{base}/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            openmetrics = resp.read().decode()
            assert "openmetrics" in resp.headers.get("Content-Type")
        assert 'trace_id="negotiate1"' in openmetrics
    finally:
        server.stop()


def test_fleet_survives_malformed_worker_exposition():
    """One worker answering garbage (port reused mid-restart, truncated
    body) degrades per instance — it must not blank the fleet view."""
    import http.server
    import threading as _threading

    from lws_tpu.api.pod import PodPhase
    from lws_tpu.runtime import ControlPlane

    class GarbageHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"this is { not a metrics exposition\n=== 12"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), GarbageHandler)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    cp = ControlPlane()
    try:
        pod = cp.store.create(_make_worker_pod("fleet-garbage", httpd.server_port))
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = "127.0.0.1"
        cp.store.update_status(pod)
        merged = cp.fleet.render_fleet(force=True)
        parse_exposition(merged)  # the fleet view stays parser-valid
        assert cp.metrics.counter_value(
            "lws_fleet_scrape_errors_total", {"instance": "fleet-garbage"}) == 1.0
    finally:
        httpd.shutdown()


def test_worker_telemetry_token_and_watchdog():
    """A token-configured worker rejects unauthenticated reads of every
    surface except /healthz, and a worker-side watchdog's alerts appear in
    the worker's own /debug/flightrecorder — a stalled ring in a WORKER
    process must be detectable, not just heartbeat into a table nothing
    evaluates."""
    from lws_tpu.runtime.telemetry import TelemetryServer

    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=[StallRule("decode_ring_stall", "decode_ring:*",
                                                stall_after_s=0.0)])
    fr.beat("decode_ring:w", progress=1, depth=2, now=T0)
    wd.check_now(now=T0 + 1)
    server = TelemetryServer(port=0, watchdog=wd, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            assert resp.status == 200  # liveness stays open
        for path in ("/metrics", "/debug/traces", "/debug/flightrecorder"):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}{path}", timeout=10)
            assert err.value.code == 401, path
        req = urllib.request.Request(
            f"{base}/debug/flightrecorder",
            headers={"Authorization": "Bearer s3cret"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert "decode_ring_stall" in body["alerts"]
        assert body["last_dump"]["reason"] == "watchdog:decode_ring_stall"
    finally:
        server.stop()


def test_worker_telemetry_server_surfaces(tmp_path):
    from lws_tpu.runtime.telemetry import TelemetryServer

    server = TelemetryServer(port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            parse_exposition(resp.read().decode())
        with urllib.request.urlopen(f"{base}/debug/flightrecorder", timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert set(body) == {"events", "heartbeats", "alerts", "last_dump"}
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/traces?limit=-1", timeout=10)
        assert err.value.code == 400
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Resilience-plane watchdog rules (ISSUE 8): an open breaker and a tripped
# deadline each produce EXACTLY ONE edge-triggered alert with a dump.


def test_open_circuit_breaker_alerts_once_with_dump():
    from lws_tpu.core.flightrecorder import BacklogRule
    from lws_tpu.core.resilience import CircuitBreaker

    fake = {"t": 0.0}
    breaker = CircuitBreaker("wd@peer", failure_threshold=1,
                             reset_timeout_s=60.0, clock=lambda: fake["t"])
    wd = Watchdog(rules=[BacklogRule("circuit_open", "breaker:wd@*",
                                     depth_threshold=1.0, sustain_s=0.0)])
    now = time.monotonic()
    assert "circuit_open" not in wd.check_now(now=now)  # closed: quiet
    before = metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "circuit_open"})
    breaker.record_failure()  # threshold 1: opens, beats depth 1
    firing = wd.check_now(now=time.monotonic() + 0.001)
    assert firing["circuit_open"][0]["source"] == "breaker:wd@peer"
    after = metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "circuit_open"})
    assert after == before + 1
    # Steady-open does NOT re-alert (edge-triggered)...
    wd.check_now(now=time.monotonic() + 0.002)
    assert metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "circuit_open"}) == after
    # ...and the trip captured a diagnostics dump naming the alert.
    dump = wd.last_dump
    assert dump["reason"] == "watchdog:circuit_open"
    assert dump["heartbeats"]["breaker:wd@peer"]["depth"] == 1.0
    assert any(e["kind"] == "circuit_breaker" for e in dump["events"])
    # Recovery clears the alert.
    fake["t"] = 100.0
    assert breaker.allow()  # half-open probe
    breaker.record_success()  # closed: beat depth 0
    assert "circuit_open" not in wd.check_now(now=time.monotonic() + 1)
    assert metrics.REGISTRY.gauge_value(
        "lws_watchdog_active", {"watchdog": "circuit_open"}) == 0.0


def test_tripped_deadline_alerts_once_with_dump():
    from lws_tpu.core.flightrecorder import TripRule
    from lws_tpu.core.resilience import Deadline, DeadlineExceeded

    wd = Watchdog(rules=[TripRule("deadline_tripped", "deadline_trips:wd.*",
                                  window_s=5.0)])
    before = metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "deadline_tripped"})
    deadline = Deadline(0.0)  # born expired
    with pytest.raises(DeadlineExceeded):
        deadline.check("wd.site")
    firing = wd.check_now(now=time.monotonic())
    assert firing["deadline_tripped"][0]["source"] == "deadline_trips:wd.site"
    after = metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "deadline_tripped"})
    assert after == before + 1
    # Steady within the window: still firing but NOT re-counted.
    wd.check_now(now=time.monotonic() + 1.0)
    assert metrics.REGISTRY.counter_value(
        "lws_watchdog_alerts_total", {"watchdog": "deadline_tripped"}) == after
    dump = wd.last_dump
    assert dump["reason"] == "watchdog:deadline_tripped"
    assert any(e["kind"] == "deadline_exceeded" and e["site"] == "wd.site"
               for e in dump["events"])
    # The burst going quiet (window passes with no new trips) clears it.
    assert "deadline_tripped" not in wd.check_now(now=time.monotonic() + 60.0)
    assert metrics.REGISTRY.gauge_value(
        "lws_watchdog_active", {"watchdog": "deadline_tripped"}) == 0.0


# ---------------------------------------------------------------------------
# Fleet scrape backoff (ISSUE 8 satellite): a down instance is SKIPPED
# until its backoff expires, with deterministic `now=` injection.


def test_fleet_scrape_backoff_skips_down_instance_until_expiry():
    from lws_tpu.api.pod import PodPhase
    from lws_tpu.runtime import ControlPlane

    cp = ControlPlane()
    pod = cp.store.create(_make_worker_pod("backoff-dead", 1))  # nothing listens
    pod.status.phase = PodPhase.RUNNING
    pod.status.ready = True
    pod.status.address = "127.0.0.1"
    cp.store.update_status(pod)
    cp.fleet.timeout_s = 0.2
    errors = lambda: cp.metrics.counter_value(  # noqa: E731
        "lws_fleet_scrape_errors_total", {"instance": "backoff-dead"})
    skips = lambda: cp.metrics.counter_value(  # noqa: E731
        "lws_fleet_scrape_skipped_total", {"instance": "backoff-dead"})
    cp.fleet.collect(now=100.0)
    assert errors() == 1.0 and skips() == 0.0
    # Inside the first backoff window (base 2s): not even dialed.
    cp.fleet.collect(now=100.5)
    cp.fleet.collect(now=101.9)
    assert errors() == 1.0 and skips() == 2.0
    # Window expired: dialed again (fails again — window doubles to 4s).
    # The window anchors at the FAILURE time (injected now + the scrape's
    # own elapsed), so the re-dial points leave sub-second slack.
    cp.fleet.collect(now=103.0)
    assert errors() == 2.0
    cp.fleet.collect(now=105.0)  # ~103 + 4 > 105: still backed off
    assert errors() == 2.0 and skips() == 3.0
    cp.fleet.collect(now=108.0)
    assert errors() == 3.0
    # The merged view stays parser-valid throughout.
    parse_exposition(cp.fleet.render_fleet(force=True))


def test_fleet_backoff_caps_and_recovers():
    """The window doubles only to the cap, and one success clears ALL
    backoff state (plus records the recovery edge event)."""
    from lws_tpu.runtime.fleet import FleetCollector

    fc = FleetCollector(store=None, metrics_registry=MetricsRegistry(),
                        backoff_base_s=1.0, backoff_cap_s=4.0)
    assert [fc._backoff_s(n) for n in (1, 2, 3, 4, 9)] == \
        [1.0, 2.0, 4.0, 4.0, 4.0]
    fc._failing["w0"] = {"failures": 3, "until": 200.0}
    assert fc.in_backoff("w0", 199.0) and not fc.in_backoff("w0", 200.0)


TOP_EXPOSITION = """\
# HELP serving_slo_attainment x
# TYPE serving_slo_attainment gauge
serving_slo_attainment{engine="paged",instance="w0"} 0.875
# HELP serving_requests_total x
# TYPE serving_requests_total counter
serving_requests_total{engine="paged",instance="w0"} 42.0
# HELP serving_active_slots x
# TYPE serving_active_slots gauge
serving_active_slots{engine="paged",instance="w0"} 6.0
# HELP serving_inflight_dispatches x
# TYPE serving_inflight_dispatches gauge
serving_inflight_dispatches{engine="paged",instance="w0"} 2.0
# HELP serving_decode_dispatch_duration_seconds x
# TYPE serving_decode_dispatch_duration_seconds histogram
serving_decode_dispatch_duration_seconds_bucket{engine="paged",instance="w0",le="+Inf"} 100
serving_decode_dispatch_duration_seconds_sum{engine="paged",instance="w0"} 1.0
serving_decode_dispatch_duration_seconds_count{engine="paged",instance="w0"} 100
# HELP serving_ttft_seconds x
# TYPE serving_ttft_seconds histogram
serving_ttft_seconds_bucket{engine="paged",instance="w0",le="0.05"} 8
serving_ttft_seconds_bucket{engine="paged",instance="w0",le="0.1"} 10
serving_ttft_seconds_bucket{engine="paged",instance="w0",le="+Inf"} 10
serving_ttft_seconds_sum{engine="paged",instance="w0"} 0.5
serving_ttft_seconds_count{engine="paged",instance="w0"} 10
# HELP lws_fleet_instances x
# TYPE lws_fleet_instances gauge
lws_fleet_instances 1.0
"""


def test_render_top_formats_fleet_view():
    from lws_tpu.cli import _top_rows, render_top
    from lws_tpu.core.metrics import parse_exposition as parse_prod

    fams = parse_prod(TOP_EXPOSITION)
    frame = render_top(fams, alerts={"decode_ring_stall": [{"source": "decode_ring:paged"}]})
    assert "instances=1" in frame
    assert "alerts=decode_ring_stall" in frame
    assert "ALERT decode_ring_stall" in frame
    row = next(l for l in frame.splitlines() if l.startswith("w0"))
    assert "paged" in row and "0.88" in row and "42" in row and "6" in row
    # TTFT p95: between the 0.05 and 0.1 bucket bounds.
    assert "0.0" in row
    rows = _top_rows(fams)
    assert 0.05 <= rows[("w0", "paged")]["ttft_p95"] <= 0.1
    # Rates appear once a previous frame exists.
    prev = {("w0", "paged"): {"dispatches": 60.0}}
    frame2 = render_top(fams, alerts={}, prev=prev, dt_s=2.0)
    assert "20.0" in frame2  # (100-60)/2 dispatches per second


def test_cmd_top_one_shot_against_live_server(capsys):
    from lws_tpu import cli
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    metrics.REGISTRY.set("serving_slo_attainment", 0.9, {"engine": "paged"})
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        rc = cli.main(["top", "--server", f"127.0.0.1:{api.port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "FLEET" in out and "INSTANCE" in out
        assert "control-plane" in out  # the CP's own registries render
    finally:
        api.stop()
