"""TPU env synthesis behavior (parity with pkg/utils/accelerators/tpu_test.go):
whole-group hostnames, leader-without-TPU shift, multi-container interleaving,
subgroup windows with leader-inclusion shifts."""

from lws_tpu.api import contract
from lws_tpu.api.meta import ObjectMeta
from lws_tpu.api.pod import Container, EnvVar, Pod, PodSpec
from lws_tpu.utils.tpu import add_tpu_variables, get_subgroup_index


def env_map(container):
    return {e.name: e.value for e in container.env}


def make_pod(
    name,
    worker_index=None,
    leader_requests=None,
    subgroup=None,  # (size, index)
    tpu_containers=1,
    chips=4,
    subdomain="default",
    extra_env=(),
):
    labels, annotations = {}, {}
    if worker_index is not None:
        labels[contract.WORKER_INDEX_LABEL_KEY] = str(worker_index)
    if leader_requests:
        annotations[contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY] = "true"
    if subgroup is not None:
        annotations[contract.SUBGROUP_SIZE_ANNOTATION_KEY] = str(subgroup[0])
        labels[contract.SUBGROUP_INDEX_LABEL_KEY] = str(subgroup[1])
    containers = [
        Container(name=f"c{i}", resources={contract.TPU_RESOURCE_NAME: chips}, env=[EnvVar(*e) for e in extra_env])
        for i in range(tpu_containers)
    ]
    return Pod(
        meta=ObjectMeta(name=name, labels=labels, annotations=annotations),
        spec=PodSpec(containers=containers, subdomain=subdomain),
    )


def test_leader_pod_whole_group():
    pod = make_pod("sample-1", worker_index=0)
    add_tpu_variables(pod, size=2)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_WORKER_HOSTNAMES] == "sample-1.default,sample-1-1.default"
    assert env[contract.TPU_WORKER_ID] == "0"
    assert env[contract.TPU_NAME] == "sample-1"
    assert env[contract.TPU_PROCESS_ADDRESSES] == "sample-1.default:8476,sample-1-1.default:8476"
    assert env[contract.TPU_PROCESS_PORT] == "8476"


def test_worker_pod_leader_requests_tpus():
    pod = make_pod("sample-1-3", worker_index=3, leader_requests=True)
    add_tpu_variables(pod, size=5)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1.default,sample-1-1.default,sample-1-2.default,"
        "sample-1-3.default,sample-1-4.default"
    )
    assert env[contract.TPU_WORKER_ID] == "3"
    assert env[contract.TPU_NAME] == "sample-1"


def test_worker_pod_leader_without_tpus_shifts_ids():
    pod = make_pod("sample-1-3", worker_index=3)
    add_tpu_variables(pod, size=5)
    env = env_map(pod.spec.containers[0])
    # Leader excluded from hostnames; ids shift down by one.
    assert env[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1-1.default,sample-1-2.default,sample-1-3.default,sample-1-4.default"
    )
    assert env[contract.TPU_WORKER_ID] == "2"


def test_multi_container_interleaving():
    leader = make_pod("sample-1", worker_index=0, tpu_containers=2)
    add_tpu_variables(leader, size=2)
    env0, env1 = env_map(leader.spec.containers[0]), env_map(leader.spec.containers[1])
    assert env0[contract.TPU_WORKER_ID] == "0"
    assert env1[contract.TPU_WORKER_ID] == "1"
    assert env0[contract.TPU_PROCESS_PORT] == "8476"
    assert env1[contract.TPU_PROCESS_PORT] == "8477"
    # Hostname list interleaves per-container entries: each pod appears
    # numContainers times.
    assert env0[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1.default,sample-1.default,sample-1-1.default,sample-1-1.default"
    )
    # Per-container ports interleave in the address list (ref tpu.go:251-263).
    assert env0[contract.TPU_PROCESS_ADDRESSES] == (
        "sample-1.default:8476,sample-1.default:8477,"
        "sample-1-1.default:8476,sample-1-1.default:8477"
    )

    worker = make_pod("sample-1-1", worker_index=1, leader_requests=True, tpu_containers=2)
    add_tpu_variables(worker, size=2)
    wenv0, wenv1 = env_map(worker.spec.containers[0]), env_map(worker.spec.containers[1])
    assert wenv0[contract.TPU_WORKER_ID] == "2"
    assert wenv1[contract.TPU_WORKER_ID] == "3"


def test_user_specified_port_wins():
    pod = make_pod("sample-1", worker_index=0, extra_env=[(contract.TPU_PROCESS_PORT, "9999")])
    add_tpu_variables(pod, size=2)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_PROCESS_ADDRESSES] == "sample-1.default:9999,sample-1-1.default:9999"
    # Not re-appended.
    assert [e.name for e in pod.spec.containers[0].env].count(contract.TPU_PROCESS_PORT) == 1


def test_idempotent():
    pod = make_pod("sample-1", worker_index=0)
    add_tpu_variables(pod, size=2)
    n = len(pod.spec.containers[0].env)
    add_tpu_variables(pod, size=2)
    assert len(pod.spec.containers[0].env) == n


def test_no_tpu_containers_noop():
    pod = make_pod("sample-1", worker_index=0, chips=0)
    add_tpu_variables(pod, size=2)
    assert pod.spec.containers[0].env == []


# ---- subgroup path ---------------------------------------------------------


def test_subgroup_leader_requests_tpus_window0():
    # size=8, sgs=4, leader holds TPUs -> subgroup 0 = leader + workers 1..3.
    pod = make_pod("sample-1", worker_index=0, leader_requests=True, subgroup=(4, 0))
    add_tpu_variables(pod, size=8)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1.default,sample-1-1.default,sample-1-2.default,sample-1-3.default"
    )
    assert env[contract.TPU_WORKER_ID] == "0"


def test_subgroup_worker_in_leader_subgroup():
    pod = make_pod("sample-1-2", worker_index=2, leader_requests=True, subgroup=(4, 0))
    add_tpu_variables(pod, size=8)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1.default,sample-1-1.default,sample-1-2.default,sample-1-3.default"
    )
    assert env[contract.TPU_WORKER_ID] == "2"


def test_subgroup_second_window_shifted_when_leader_has_tpus():
    # Subgroup 1 window [5..8] shifts left to [4..7].
    pod = make_pod("sample-1-5", worker_index=5, leader_requests=True, subgroup=(4, 1))
    add_tpu_variables(pod, size=8)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1-4.default,sample-1-5.default,sample-1-6.default,sample-1-7.default"
    )
    assert env[contract.TPU_WORKER_ID] == "1"  # 5 % 4


def test_subgroup_leader_without_tpus_no_shift():
    # size=9, sgs=4, leader not a TPU worker: workers 1..8, windows [1..4],[5..8].
    pod = make_pod("sample-1-5", worker_index=5, subgroup=(4, 1))
    add_tpu_variables(pod, size=9)
    env = env_map(pod.spec.containers[0])
    assert env[contract.TPU_WORKER_HOSTNAMES] == (
        "sample-1-5.default,sample-1-6.default,sample-1-7.default,sample-1-8.default"
    )
    assert env[contract.TPU_WORKER_ID] == "0"  # (5-1) % 4


def test_subgroup_index_math():
    # size-1 divisible: leader is extra pod in subgroup 0.
    assert get_subgroup_index(9, 4, 1) == 0
    assert get_subgroup_index(9, 4, 4) == 0
    assert get_subgroup_index(9, 4, 5) == 1
    assert get_subgroup_index(9, 4, 8) == 1
    # size divisible (not size-1): plain division.
    assert get_subgroup_index(8, 4, 3) == 0
    assert get_subgroup_index(8, 4, 4) == 1
    assert get_subgroup_index(8, 4, 7) == 1
