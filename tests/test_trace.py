"""Trace spine units: span nesting, thread isolation, ring bounds, JSONL
round-trip, the no-op fast path, and the controller/serving integration
(reconcile root spans + a connected cross-layer tree)."""

import json
import threading
import time

from lws_tpu.core import trace
from lws_tpu.core.trace import Tracer, connected_tree, walk
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder


def test_span_nesting_parent_links():
    t = Tracer()
    with t.span("root", layer="test") as root:
        with t.span("child") as child:
            with t.span("grandchild") as grand:
                assert t.current_context() == grand.context
    spans = t.spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["child"]["parent_id"] == root.span_id
    assert by_name["grandchild"]["parent_id"] == child.span_id
    assert {s["trace_id"] for s in spans} == {root.trace_id}
    assert connected_tree(spans)
    # Attributes and durations ride the record.
    assert by_name["root"]["attrs"] == {"layer": "test"}
    assert all(s["duration_s"] >= 0 for s in spans)


def test_span_decorator_and_error_status():
    t = Tracer()

    @t.trace("decorated", kind="unit")
    def work():
        return 42

    assert work() == 42
    assert t.spans()[-1]["name"] == "decorated"

    try:
        with t.span("boom"):
            raise ValueError("nope")
    except ValueError:
        pass
    assert t.spans()[-1]["status"] == "error"
    assert "ValueError" in t.spans()[-1]["attrs"]["error"]


def test_thread_isolation():
    """Concurrent threads nest independently: no cross-thread parenting."""
    t = Tracer()
    barrier = threading.Barrier(2)

    def worker(name):
        with t.span(name):
            barrier.wait(timeout=5)
            with t.span(f"{name}.child"):
                time.sleep(0.01)

    threads = [threading.Thread(target=worker, args=(f"t{i}",)) for i in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    by_name = {s["name"]: s for s in t.spans()}
    for i in range(2):
        child, parent = by_name[f"t{i}.child"], by_name[f"t{i}"]
        assert child["parent_id"] == parent["span_id"]
        assert child["trace_id"] == parent["trace_id"]
    assert by_name["t0"]["trace_id"] != by_name["t1"]["trace_id"]


def test_ring_bounds():
    t = Tracer(ring=8)
    for i in range(32):
        with t.span(f"s{i}"):
            pass
    spans = t.spans()
    assert len(spans) == 8
    assert spans[-1]["name"] == "s31"  # newest kept, oldest dropped
    assert t.spans(limit=3) == spans[-3:]


def test_jsonl_round_trip(tmp_path):
    t = Tracer()
    with t.span("outer", pos=7):
        with t.span("inner", bundle_bytes=123):
            pass
    path = str(tmp_path / "spans.jsonl")
    n = t.export_jsonl(path)
    assert n == 2
    loaded = Tracer.read_jsonl(path)
    assert loaded == t.spans()
    assert connected_tree(loaded)


def test_live_export_path(tmp_path):
    path = str(tmp_path / "live.jsonl")
    t = Tracer(export_path=path)
    with t.span("a"):
        pass
    with t.span("b"):
        pass
    lines = [json.loads(line) for line in open(path)]
    assert [rec["name"] for rec in lines] == ["a", "b"]


def test_noop_fast_path():
    t = Tracer(enabled=False)
    sp = t.span("anything", heavy="attr")
    assert sp is trace.NOOP  # one shared object, nothing allocated
    with sp as inner:
        inner.set(ignored=True)
        assert t.current_context() is None
    assert t.spans() == []
    # A sampled-out root suppresses its WHOLE subtree (no orphan fragments
    # from children independently re-rolling the sampler)...
    t2 = Tracer(sample_rate=0.0)
    with t2.span("root") as root:
        assert root.context is None
        with t2.span("child"):
            with t2.span("grandchild"):
                pass
    assert t2.spans() == []
    # ...and suppression ends with the root: an always-sample tracer nested
    # after a suppressed region records normally.
    t3 = Tracer(sample_rate=0.5)
    recorded = orphans = 0
    for _ in range(200):
        with t3.span("root"):
            with t3.span("child"):
                pass
    for s in t3.spans():
        if s["name"] == "child" and s["parent_id"] is None:
            orphans += 1
        recorded += 1
    assert orphans == 0, "sampling shredded a trace"
    assert 0 < recorded < 400  # sampled some, not all
    # Children of a live span (or an explicit peer context) are always kept.
    with t2.span("root", parent={"trace_id": "abc", "span_id": "def"}):
        assert t2.span("child") is not trace.NOOP  # vet: ignore[span-context-manager]: sampling check needs the raw span object, never entered on purpose


def test_reconcile_root_spans_flow_through_control_plane():
    trace.TRACER.clear()
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    spans = trace.TRACER.spans()
    roots = [s for s in spans if s["name"] == "reconcile"]
    controllers = {s["attrs"]["controller"] for s in roots}
    assert {"lws", "groupset", "pod"} <= controllers
    # Child spans parent under their reconcile root.
    ids = {s["span_id"] for s in roots}
    for child_name in ("reconcile.rollout_step", "reconcile.placement",
                      "reconcile.status"):
        children = [s for s in spans if s["name"] == child_name]
        assert children, f"no {child_name} spans recorded"
        assert all(c["parent_id"] in ids for c in children)
    # The rollout gauge fed by the status pass is live.
    assert cp.metrics.gauge_value(
        "lws_rollout_progress",
        {"lws": "default/sample",
         "revision": _revision_of(cp)},
    ) == 1.0


def _revision_of(cp):
    from lws_tpu.utils import revision as revisionutils

    gs = cp.store.get("GroupSet", "default", "sample")
    return revisionutils.get_revision_key(gs)


def test_connected_tree_helpers_reject_forests():
    t = Tracer()
    with t.span("a"):
        pass
    with t.span("b"):
        pass
    assert not connected_tree(t.spans())  # two roots, two traces
    t2 = Tracer()
    with t2.span("root") as r:
        with t2.span("x"):
            pass
        with t2.span("y"):
            pass
    names = {s["name"] for s in walk(t2.spans(), r.span_id)}
    assert names == {"root", "x", "y"}
