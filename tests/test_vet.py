"""Analyzer self-tests: each vet pass against the fixture snippets under
tests/vet_fixtures/ (true positives AND the false-positive guards), the
suppression/baseline machinery, and the repo-level contract that
`python -m tools.vet` runs clean with the committed baseline.

The fixtures are excluded from normal vet discovery (deliberate
violations) and never imported — the passes only parse them."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = ROOT / "tests" / "vet_fixtures"

sys.path.insert(0, str(ROOT))

from tools.vet import run_pass  # noqa: E402
from tools.vet.core import (  # noqa: E402
    Module,
    apply_baseline,
    iter_source_files,
    load_baseline,
    malformed_suppressions,
    write_baseline,
)


def findings_for(pass_name: str, *names: str, root: Path = FIXTURES):
    return run_pass(pass_name, [FIXTURES / n for n in names], root=root)


# ---------------------------------------------------------------------------
# locks pass


def test_locks_flags_unguarded_access_and_order_inversion():
    found = findings_for("locks", "lock_unguarded.py")
    details = {f.detail for f in found}
    assert any(f.rule == "lock-guarded-attr" and f.detail == "bad_read._items"
               for f in found), found
    assert any(f.rule == "lock-guarded-attr" and f.detail == "bad_write.count"
               for f in found), found
    # After a try/finally release the region has ENDED: the trailing
    # access is flagged even though the locked one inside the try is not.
    after = [f for f in found if f.detail == "bad_after_finally_release.count"]
    assert len(after) == 1, found
    assert not any(f.detail == "bad_after_finally_release._items" for f in found)
    # The locked accesses in good() are never flagged.
    assert not any("good." in d for d in details), details
    assert any(f.rule == "lock-order" and "Guarded" in f.detail
               for f in found), found


def test_lock_order_does_not_merge_same_named_classes(tmp_path):
    """Two unrelated classes that happen to share a name in different
    modules must not merge into one phantom ABBA pair."""
    src = (
        "import threading\n\n\nclass Mgr:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n\n"
        "    def op(self):\n"
        "        with self.{0}:\n"
        "            with self.{1}:\n"
        "                return None\n"
    )
    (tmp_path / "mod_a.py").write_text(src.format("_a_lock", "_b_lock"))
    (tmp_path / "mod_b.py").write_text(src.format("_b_lock", "_a_lock"))
    found = run_pass(
        "locks", [tmp_path / "mod_a.py", tmp_path / "mod_b.py"], root=tmp_path
    )
    assert not any(f.rule == "lock-order" for f in found), found


def test_locks_false_positive_guards_stay_silent():
    """with-blocks, acquire/try/finally, RLock re-entrancy, _locked
    suffix, holds-lock annotations (incl. the decorated-lock shape) and
    nested callbacks must produce ZERO findings."""
    assert findings_for("locks", "lock_guards_ok.py") == []


def test_callgraph_resolution_shapes():
    """The shared call graph (tools/vet/callgraph.py): every provable
    shape resolves (self-calls, ctor-typed locals, annotations, module
    globals, attr inference, IfExp receivers) and every dynamic shape
    conservatively does NOT."""
    from tools.vet import callgraph
    from tools.vet.core import load_modules

    mods = load_modules([FIXTURES / "callgraph_cases.py"], FIXTURES)
    graph = callgraph.build(mods)
    rel = "callgraph_cases.py"

    def callees_of(qual):
        info = graph.funcs[(rel, qual)]
        return {k for k, _ in graph.callees(info)}

    # Plain call + ctor-typed local + module-global instance.
    assert callees_of("root") == {
        (rel, "helper"), (rel, "Worker.__init__"), (rel, "Worker.step"),
    }
    # self-method, annotated param, attr-inferred type.
    assert (rel, "Worker._locked_inner") in callees_of("Worker.step")
    assert (rel, "Worker.step") in callees_of("typed_param")
    assert (rel, "Other.poke") in callees_of("Worker._locked_inner")
    # IfExp receiver: both branches resolve to Worker.
    assert (rel, "Worker.step") in callees_of("conditional")
    # Conservatism: untyped callables/receivers make NO edge.
    assert callees_of("dynamic") == set()
    assert callees_of("duck") == set()
    # Reachability closes over the chain.
    reached = graph.reachable([(rel, "root")])
    assert (rel, "Other.poke") in reached
    # resolve_callable: a function-valued Name resolves without a call.
    import ast

    info = graph.funcs[(rel, "observer_ref")]
    ret = info.node.body[-1]
    assert isinstance(ret, ast.Return)
    assert graph.resolve_callable(info, ret.value) == (rel, "helper")


def test_locks_interprocedural_blocking_and_cross_class_order():
    """lock-held-blocking direct + through a resolvable callee, and the
    cross-class ABBA inversion only the call graph can see; the released/
    unresolvable shapes stay silent."""
    found = findings_for("locks", "lock_interproc.py")
    pairs = {(f.rule, f.detail) for f in found}
    assert ("lock-held-blocking", "bad_direct:time.sleep") in pairs, found
    assert ("lock-held-blocking",
            "bad_transitive->Blocker._helper") in pairs, found
    order = [f for f in found if f.rule == "lock-order"]
    assert len(order) == 1, found
    assert order[0].detail == "Left._l_lock<->Right._r_lock"
    assert "across classes" in order[0].message
    # ok_outside (lock released) and ok_unresolvable produce nothing.
    assert not any(f.detail.startswith("ok_") for f in found), found


# ---------------------------------------------------------------------------
# hotpath pass


def test_hotpath_roots_reachability_and_suppression():
    found = findings_for("hotpath", "hotpath_cases.py")
    by_detail = {f.detail: f.rule for f in found}
    # Direct violations in the annotated root.
    assert by_detail.get("hot_root:time.sleep") == "hotpath-blocking-call"
    assert by_detail.get("hot_root:socket.create_connection") == "hotpath-blocking-call"
    assert by_detail.get("hot_root:np.asarray") == "hotpath-host-sync"
    # Reachability: a helper the root calls, and a self-method call.
    assert by_detail.get("helper_sleeps:time.sleep") == "hotpath-blocking-call"
    assert by_detail.get("Engine._inner:np.asarray") == "hotpath-host-sync"
    # The suppressed fence produced no finding beyond the flagged one
    # (same detail key would collide — assert by line instead).
    suppressed_line = next(
        i for i, text in enumerate(
            (FIXTURES / "hotpath_cases.py").read_text().splitlines(), 1
        ) if "vet: ignore[hotpath-host-sync]" in text
    )
    assert not any(f.line == suppressed_line for f in found)
    # A closure inside a BFS-REACHED callee (not just an annotated root)
    # is hot too: blocking hidden in a helper's nested def is found.
    assert by_detail.get("helper_with_closure.inner:time.sleep") == \
        "hotpath-blocking-call"
    # Lambdas are scanned inline with their containing hot function —
    # the engines' commit callbacks are exactly this shape.
    assert by_detail.get("hot_root3:np.asarray") == "hotpath-host-sync"
    # cold() is unreachable from any hot root: blocking is fine there.
    assert not any(f.detail.startswith("cold:") for f in found), found


def test_hotpath_flags_buffered_serialization_in_serving():
    """hotpath-serialize-copy (ISSUE 10): np.savez / io.BytesIO anywhere
    under lws_tpu/serving/ — lexical, no hot-root reachability needed (the
    npz double copy this rule guards against was never on a hot root)."""
    found = run_pass(
        "hotpath",
        [FIXTURES / "lws_tpu" / "serving" / "serialize_cases.py"],
        root=FIXTURES,
    )
    rules = {(f.rule, f.detail) for f in found}
    assert ("hotpath-serialize-copy", "npz_round_trip:io.BytesIO") in rules
    assert ("hotpath-serialize-copy", "npz_round_trip:np.savez") in rules
    assert ("hotpath-serialize-copy",
            "compressed_variant:np.savez_compressed") in rules
    # The suppressed buffered dump and the sanctioned raw-framing /
    # bytes-join shapes produce nothing.
    assert not any(d.startswith(("suppressed_copy", "raw_framing_ok",
                                 "bytes_join_ok"))
                   for r, d in rules if r == "hotpath-serialize-copy"), rules
    # Scope: the SAME shapes outside lws_tpu/serving/ are not this rule's
    # business (root=FIXTURES/"lws_tpu"/"serving" puts the file OUTSIDE the
    # scoped prefix).
    outside = run_pass(
        "hotpath",
        [FIXTURES / "lws_tpu" / "serving" / "serialize_cases.py"],
        root=FIXTURES / "lws_tpu" / "serving",
    )
    assert not any(f.rule == "hotpath-serialize-copy" for f in outside)


# ---------------------------------------------------------------------------
# resources pass


def test_resources_flags_leaks_and_honors_ownership_shapes():
    found = findings_for("resources", "resource_cases.py")
    rules = {(f.rule, f.detail) for f in found}
    assert ("resource-unclosed", "leaky_local:sock") in rules, found
    assert any(r == "resource-unclosed" and "discarded:" in d
               for r, d in rules), found
    assert any(r == "resource-ctor-leak" and d.startswith("LeakyServer.__init__")
               for r, d in rules), found
    # Every ok_* shape and the try/except-close server stay silent.
    for f in found:
        assert not f.detail.startswith(("ok_", "SafeServer")), f


# ---------------------------------------------------------------------------
# spans pass


def test_spans_context_and_literal_rules():
    found = run_pass(
        "spans", [FIXTURES / "lws_tpu" / "span_cases.py"], root=FIXTURES
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    ctx = by_rule.get("span-context-manager", [])
    assert {f.detail.split(":")[0] for f in ctx} == {
        "bad_span", "bad_span_shared_name",
    }, ctx
    # bad_span_shared_name is the masking case: ANOTHER function enters a
    # same-named variable — per-scope matching must still flag the leak.
    assert len(by_rule.get("metric-name-literal", [])) == 1
    assert "bad_metric_name" in by_rule["metric-name-literal"][0].detail
    assert len(by_rule.get("span-name-literal", [])) == 1
    assert "bad_span_name" in by_rule["span-name-literal"][0].detail
    # Profiler phase tags carry the same literal-name contract — both the
    # `profile.phase(...)` and directly-imported bare `phase(...)` shapes;
    # the ok_phase literal stays silent.
    phases = by_rule.get("profile-phase-literal", [])
    assert {f.detail.split(":")[0] for f in phases} == {
        "bad_phase_name", "bad_phase_name_direct",
    }, phases


def test_spans_rules_cover_loadgen_package():
    """lws_tpu/loadgen/ is INSIDE the catalogue scope: scenario-emitted
    metric/span names must be literal (and spans entered) exactly like the
    serving plane's — a computed per-scenario name would mint ungreppable
    families the catalogue checker can't see."""
    found = run_pass(
        "spans",
        [FIXTURES / "lws_tpu" / "loadgen" / "report_cases.py"],
        root=FIXTURES,
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("bad_scenario_metric" in f.detail
               for f in by_rule.get("metric-name-literal", [])), found
    assert any("bad_scenario_span" in f.detail
               for f in by_rule.get("span-name-literal", [])), found
    assert any("bad_unentered_span" in f.detail
               for f in by_rule.get("span-context-manager", [])), found
    for f in found:
        assert not f.detail.startswith("ok_"), f


def test_spans_rules_cover_obs_package():
    """lws_tpu/obs/ is INSIDE the catalogue scope: the history plane's
    decision metrics (`serving_scale_recommendation`,
    `serving_slo_burn_rate`) are exactly the names dashboards and the
    autoscaler seam are built against — a recommender minting per-role or
    per-window names dynamically would evade the catalogue contract."""
    found = run_pass(
        "spans",
        [FIXTURES / "lws_tpu" / "obs" / "signal_cases.py"],
        root=FIXTURES,
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("bad_role_metric" in f.detail
               for f in by_rule.get("metric-name-literal", [])), found
    assert any("bad_window_span" in f.detail
               for f in by_rule.get("span-name-literal", [])), found
    assert any("bad_unentered_span" in f.detail
               for f in by_rule.get("span-context-manager", [])), found
    for f in found:
        assert not f.detail.startswith("ok_"), f


def test_spans_rules_cover_journey_vault():
    """The journey vault (lws_tpu/obs/journey.py) is INSIDE the catalogue
    scope: its retention-accounting names (`serving_journeys_*_total`) are
    what the tail-latency runbook audits losses with — a vault minting
    per-outcome/per-reason names dynamically would make the loss-accounting
    surface itself uncatalogueable."""
    found = run_pass(
        "spans",
        [FIXTURES / "lws_tpu" / "obs" / "journey_cases.py"],
        root=FIXTURES,
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("bad_outcome_metric" in f.detail
               for f in by_rule.get("metric-name-literal", [])), found
    assert any("bad_reason_span" in f.detail
               for f in by_rule.get("span-name-literal", [])), found
    assert any("bad_unentered_span" in f.detail
               for f in by_rule.get("span-context-manager", [])), found
    for f in found:
        assert not f.detail.startswith("ok_"), f


def test_spans_rules_cover_rollout_plane():
    """The rollout plane (lws_tpu/obs/rollout.py) is INSIDE the catalogue
    scope: its decision surface (`lws_rollout_canary_verdict`,
    `serving_slo_burn_rate_by_revision`, `lws_rollout_ledger_events_total`)
    is what rollback automation and rollout dashboards key on — an
    analyzer minting per-revision names dynamically would make the one
    surface that gates promotions uncatalogueable."""
    found = run_pass(
        "spans",
        [FIXTURES / "lws_tpu" / "obs" / "rollout_cases.py"],
        root=FIXTURES,
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("bad_revision_metric" in f.detail
               for f in by_rule.get("metric-name-literal", [])), found
    assert any("bad_verdict_span" in f.detail
               for f in by_rule.get("span-name-literal", [])), found
    assert any("bad_unentered_span" in f.detail
               for f in by_rule.get("span-context-manager", [])), found
    for f in found:
        assert not f.detail.startswith("ok_"), f


def test_spans_rules_cover_device_plane():
    """The device-runtime plane (lws_tpu/obs/device.py) is INSIDE the
    catalogue scope: its forensics surface (`serving_compiles_total{kind}`,
    `serving_hbm_pool_bytes{pool}`, the `fleet.compile_scrape` span) is
    what recompile-storm and HBM-pressure runbooks key on — a ledger
    minting per-kind/per-pool names dynamically would make the one surface
    that explains compile stalls itself uncatalogueable."""
    found = run_pass(
        "spans",
        [FIXTURES / "lws_tpu" / "obs" / "device_cases.py"],
        root=FIXTURES,
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("bad_kind_metric" in f.detail
               for f in by_rule.get("metric-name-literal", [])), found
    assert any("bad_pool_span" in f.detail
               for f in by_rule.get("span-name-literal", [])), found
    assert any("bad_unentered_span" in f.detail
               for f in by_rule.get("span-context-manager", [])), found
    for f in found:
        assert not f.detail.startswith("ok_"), f


def test_spans_name_rules_scoped_to_catalogue_source():
    """The same file OUTSIDE an lws_tpu/ root only keeps the context-
    manager rule — test code can't pollute the metrics catalogue."""
    found = run_pass(
        "spans", [FIXTURES / "lws_tpu" / "span_cases.py"], root=FIXTURES / "lws_tpu"
    )
    rules = {f.rule for f in found}
    assert "metric-name-literal" not in rules
    assert "span-name-literal" not in rules
    assert "profile-phase-literal" not in rules
    assert "span-context-manager" in rules


# ---------------------------------------------------------------------------
# style pass (the folded-in linter)


def test_style_pass_keeps_lint_behavior():
    found = findings_for("style", "style_cases.py")
    rules = sorted(f.rule for f in found)
    assert "style-mutable-default" in rules
    assert "style-eq-none" in rules
    assert "style-bare-except" in rules
    assert "style-fstring" in rules
    unused = [f for f in found if f.rule == "style-unused-import"]
    # json/sys are used; os carries noqa — NOTHING unused is reported.
    assert unused == [], unused


def test_style_trailing_ws_tabs_and_malformed_suppression():
    found = findings_for("style", "suppress_cases.py")
    rules = {f.rule for f in found}
    assert "style-trailing-ws" in rules
    assert "style-tab-indent" in rules
    mod = Module(FIXTURES / "suppress_cases.py", FIXTURES)
    malformed = malformed_suppressions(mod)
    # Line 1 lacks the rule id; line 3 has an id but NO `: reason` — both
    # are malformed (and suppress nothing). The well-formed line 2 is not.
    assert [f.line for f in malformed] == [1, 3], malformed
    assert not any(f.line == 2 for f in malformed)


# ---------------------------------------------------------------------------
# purity pass


def test_purity_observer_containment_and_fleet_scans():
    found = run_pass(
        "purity", [FIXTURES / "lws_tpu" / "purity_cases.py"], root=FIXTURES
    )
    pairs = {(f.rule, f.detail) for f in found}
    # Uncontained observer flagged AT THE REGISTRATION SITE; the broad-
    # try-contained one and the suppressed registration stay silent.
    assert ("purity-observer-raise", "wire:bad_observer") in pairs, found
    assert not any("good_observer" in d for _, d in pairs), found
    assert not any("wire_suppressed" in d for _, d in pairs), found
    # Whole-fleet scan, per-item fan-out (loop), and the name-fallback
    # receiver; filtered/suppressed/unreachable scans stay silent.
    assert ("purity-fleet-scan", "Ctl.reconcile:list(Pod)") in pairs, found
    assert ("purity-fleet-scan",
            "Ctl.reconcile:list(Node)@loop") in pairs, found
    assert ("purity-fleet-scan", "untyped_helper:list(Pod)") in pairs, found
    assert not any("ok_filtered" in d or "ok_suppressed" in d
                   or "cold_scan" in d for _, d in pairs), found


def test_purity_scoped_to_lws_tpu_paths():
    """The same fixture rooted so its rel path is NOT under lws_tpu/
    produces nothing — tests may register throwaway callbacks."""
    found = run_pass(
        "purity", [FIXTURES / "lws_tpu" / "purity_cases.py"],
        root=FIXTURES / "lws_tpu",
    )
    assert found == []


# ---------------------------------------------------------------------------
# cardinality pass


def test_cardinality_traces_derived_labels_against_catalogue():
    found = run_pass(
        "cardinality", [FIXTURES / "lws_tpu" / "cardinality_cases.py"],
        root=FIXTURES,
    )
    pairs = {(f.rule, f.detail) for f in found}
    # Derived values (f-string identity, str(.request_id), via a local
    # binding) on an UNCATALOGUED metric are findings.
    assert ("cardinality-unbounded", "fixture_requests_total:pod") in pairs
    assert ("cardinality-unbounded",
            "fixture_latency_seconds:request") in pairs
    lines = sorted(f.line for f in found
                   if f.detail == "fixture_requests_total:pod")
    assert len(lines) == 2, found  # f-string site AND the binding site
    # The committed catalogue declares lws_rollout_progress `lws`: capped —
    # the sanctioned escape hatch stays silent; so do bounded/opaque values
    # and the suppressed site.
    assert not any("lws_rollout_progress" in d for _, d in pairs), found
    assert not any("outcome" in d for _, d in pairs), found
    assert not any(f.detail.endswith(":uid") for f in found), found


def test_cardinality_bound_cell_grammar():
    """parse_bound_cell is the ONE grammar both the vet pass and
    check_metrics_catalogue.py enforce."""
    from tools.vet.cardinality import catalogue_bounds, parse_bound_cell

    assert parse_bound_cell("—") == {}
    assert parse_bound_cell("") == {}
    assert parse_bound_cell("`engine`: enum") == {"engine": "enum"}
    assert parse_bound_cell("`lws`: capped, `revision`: capped") == {
        "lws": "capped", "revision": "capped",
    }
    assert parse_bound_cell("engine: enum") == {"engine": "enum"}  # unticked ok
    assert parse_bound_cell("`engine`: bogus") is None  # unknown class
    assert parse_bound_cell("garbage") is None
    table = (
        "## Metrics\n\n"
        "| Name | Type | Labels | Bound | Layer |\n"
        "|---|---|---|---|---|\n"
        "| `m_total` | counter | `a` | `a`: enum | x |\n"
        "| `g` | gauge | — | — | x |\n\n"
        "## Spans\n"
    )
    assert catalogue_bounds(table) == {"m_total": {"a": "enum"}, "g": {}}


def test_metrics_catalogue_checker_enforces_bound_shape(tmp_path):
    """tools/check_metrics_catalogue.py (the SHAPE side of the contract):
    the committed catalogue passes; a malformed Bound cell, a Labels/Bound
    set mismatch, and an undeclared source label each fail."""
    import tools.check_metrics_catalogue as checker

    catalogue = checker.CATALOGUE.read_text()
    rows = checker.metrics_rows(catalogue)
    assert len(rows) >= 30
    for name, labels, bound_cell in rows:
        bound = checker.parse_bound_cell(bound_cell)
        assert bound is not None, (name, bound_cell)
        assert set(bound) == labels, (name, bound, labels)
    # Synthetic violations exercise each error branch of the row checks.
    bad_rows = checker.metrics_rows(
        "## Metrics\n\n"
        "| Name | Type | Labels | Bound |\n"
        "|---|---|---|---|\n"
        "| `m1` | counter | `a` | `a`: nonsense |\n"
        "| `m2` | counter | `a`, `b` | `a`: enum |\n"
    )
    m1 = next(r for r in bad_rows if r[0] == "m1")
    m2 = next(r for r in bad_rows if r[0] == "m2")
    assert checker.parse_bound_cell(m1[2]) is None
    assert set(checker.parse_bound_cell(m2[2])) != m2[1]


def test_new_rule_suppressions_and_baseline_keys(tmp_path):
    """Per new rule id: the inline suppression is honored (asserted via
    the fixtures above) and findings round-trip through the baseline
    machinery with line-stable keys."""
    found = run_pass(
        "locks", [FIXTURES / "lock_interproc.py"], root=FIXTURES
    ) + run_pass(
        "purity", [FIXTURES / "lws_tpu" / "purity_cases.py"], root=FIXTURES
    ) + run_pass(
        "cardinality", [FIXTURES / "lws_tpu" / "cardinality_cases.py"],
        root=FIXTURES,
    )
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in ("lock-held-blocking", "lock-order",
                 "purity-observer-raise", "purity-fleet-scan",
                 "cardinality-unbounded"):
        assert by_rule.get(rule), f"no {rule} findings to baseline"
    counts: dict[str, int] = {}
    for f in found:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    new, old, orphans = apply_baseline(found, counts)
    assert new == [] and orphans == [] and len(old) == len(found)
    # Key shape: path::qual::rule::detail — scope+detail, never the line.
    for f in found:
        assert f.key() == f"{f.path}::{f.qual}::{f.rule}::{f.detail}"


def test_baseline_allows_known_and_errors_on_orphans(tmp_path):
    found = findings_for("locks", "lock_unguarded.py")
    assert found
    keys = [f.key() for f in found]
    baseline = dict.fromkeys(keys, 1)
    baseline["tests/gone.py::X::lock-guarded-attr::stale.entry"] = 1
    new, old, orphans = apply_baseline(found, baseline)
    assert new == [] and len(old) == len(found)
    assert orphans == ["tests/gone.py::X::lock-guarded-attr::stale.entry"]
    # Round-trip through the committed-file format (key -> count).
    path = tmp_path / "baseline.json"
    write_baseline(keys, path)
    loaded = load_baseline(path)
    assert set(loaded) == set(keys) and all(n == 1 for n in loaded.values())
    assert "_comment" in json.loads(path.read_text())


def test_baseline_counts_bound_same_key_findings():
    """One baselined key must not absorb NEW findings of the same shape:
    with count=N, an (N+1)-th occurrence fails; with more allowed than
    present, the stale count is an orphan (the file may only shrink)."""
    found = findings_for("locks", "lock_unguarded.py")
    a = [f for f in found if f.rule == "lock-guarded-attr"]
    assert len(a) >= 2
    key0 = a[0].key()
    same = [f for f in a if f.key() == key0]
    other = {f.key(): 1 for f in found if f.key() != key0}
    # Allowed count one LESS than present: exactly one finding is new.
    new, old, orphans = apply_baseline(found, {**other, key0: len(same) - 1})
    assert len(new) == 1 and new[0].key() == key0 and orphans == []
    # Allowed count one MORE than present: stale -> orphan.
    new, old, orphans = apply_baseline(found, {**other, key0: len(same) + 1})
    assert new == [] and orphans == [key0]


def test_baseline_keys_are_line_stable(tmp_path):
    """Shifting a finding DOWN by unrelated edits above it must not churn
    its baseline key (keys carry scope+detail, never line numbers)."""
    src = (FIXTURES / "lock_unguarded.py").read_text()
    shifted = tmp_path / "lock_unguarded.py"
    shifted.write_text("# pad\n# pad\n# pad\n" + src)
    orig = {f.key() for f in findings_for("locks", "lock_unguarded.py")}
    moved = {f.key() for f in run_pass("locks", [shifted], root=tmp_path)}
    assert orig == moved


# ---------------------------------------------------------------------------
# repo-level contract


def test_fixture_dir_is_excluded_from_discovery():
    files = {p.as_posix() for p in iter_source_files()}
    assert not any("vet_fixtures" in f for f in files)
    assert any(f.endswith("lws_tpu/serving/pipeline.py") for f in files)


def test_repo_vet_runs_clean_with_committed_baseline():
    """The acceptance gate: `python -m tools.vet` (what `make vet` runs)
    exits 0 on the repo — only baseline-allowed findings, no orphans."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.vet"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_partial_run_keeps_baseline_allowance():
    """`--only hotpath` must not re-report baselined findings as new —
    the allowance applies to any full-repo run; only the ORPHAN check
    needs every pass."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.vet", "--only", "hotpath"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_only_rejects_unknown_pass_with_valid_list():
    """--only with an unknown pass name fails fast (exit 2) and the error
    names every valid pass — no silent no-op runs."""
    from tools.vet import PASSES

    proc = subprocess.run(
        [sys.executable, "-m", "tools.vet", "--only", "nosuchpass"],
        cwd=ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "unknown pass(es): nosuchpass" in proc.stderr
    for name in PASSES:
        assert name in proc.stderr, (name, proc.stderr)


def test_format_json_and_sarif_are_stable_machine_output():
    """--format json/sarif emit ONE parseable document with the stable
    keys (file/line/rule/reason; SARIF ruleId/uri/startLine/message) and
    the same exit semantics as text."""
    fixture = str(FIXTURES / "lock_interproc.py")
    jproc = subprocess.run(
        [sys.executable, "-m", "tools.vet", "--format", "json",
         "--only", "locks", fixture],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert jproc.returncode == 1  # findings present
    doc = json.loads(jproc.stdout)
    assert doc and all(
        set(d) >= {"file", "line", "rule", "reason"} for d in doc
    ), doc
    assert any(d["rule"] == "lock-held-blocking" for d in doc)
    assert all(isinstance(d["line"], int) for d in doc)
    # Sorted deterministically by (file, line, rule).
    assert doc == sorted(doc, key=lambda d: (d["file"], d["line"], d["rule"]))

    sproc = subprocess.run(
        [sys.executable, "-m", "tools.vet", "--format", "sarif",
         "--only", "locks", fixture],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert sproc.returncode == 1
    sarif = json.loads(sproc.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "lws-tpu-vet"
    results = run["results"]
    assert len(results) == len(doc)
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    for res, j in zip(results, doc):
        assert res["ruleId"] == j["rule"] and res["ruleId"] in rule_ids
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == j["file"]
        assert loc["region"]["startLine"] == j["line"]
        assert res["message"]["text"] == j["reason"]

    # Clean repo run in json mode: an empty array, still exit 0.
    clean = subprocess.run(
        [sys.executable, "-m", "tools.vet", "--format", "json",
         "--only", "style"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert json.loads(clean.stdout) == []


def test_lint_alias_is_style_only_pass():
    """`make lint` muscle memory: the style-only invocation still works
    and the repo is style-clean."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.vet", "--only", "style"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1 pass(es)" in proc.stderr


def test_hazards_swallows_and_timeouts():
    found = run_pass(
        "hazards", [FIXTURES / "lws_tpu" / "hazard_cases.py"], root=FIXTURES
    )
    by_detail = {f.detail: f.rule for f in found}
    # True positives: broad swallows (direct + tuple member) and the two
    # timeout-less network calls.
    assert by_detail.get("except-Exception-pass") == "hazard-exception-swallow"
    assert by_detail.get("except-BaseException-pass") == "hazard-exception-swallow"
    assert by_detail.get("socket.create_connection") == "hazard-no-timeout"
    assert by_detail.get("urllib.request.urlopen") == "hazard-no-timeout"
    quals = {f.qual for f in found}
    # False-positive guards: narrow swallow, handled broad except, keyword
    # and positional timeouts, and the suppressed swallow stay silent.
    for clean in ("narrow_swallow_ok", "broad_but_handled_ok",
                  "dial_kw_timeout_ok", "dial_positional_timeout_ok",
                  "fetch_timeout_ok", "swallow_suppressed"):
        assert clean not in quals, found


def test_hazards_scoped_to_lws_tpu_paths():
    """The same fixture rooted so its rel path is NOT under lws_tpu/
    produces nothing — tests and tools may swallow and block."""
    found = run_pass(
        "hazards", [FIXTURES / "lws_tpu" / "hazard_cases.py"],
        root=FIXTURES / "lws_tpu",
    )
    assert found == []


def test_committed_baseline_has_no_orphans_offline():
    """The orphan rule, exercised directly against the committed file:
    every baseline entry (at its full count) must still correspond to
    real findings."""
    from tools.vet import collect_findings
    from tools.vet.core import load_modules

    current, _ = collect_findings(load_modules(iter_source_files()))
    _, _, orphans = apply_baseline(current, load_baseline())
    assert orphans == [], orphans


def test_committed_baseline_is_empty():
    """ISSUE 9 burned the last baseline entry (generate_speculative's host
    syncs) to zero. The file must STAY empty: any new hot-path host sync is
    fixed or suppressed inline with a rule id and reason — never
    re-baselined."""
    assert load_baseline() == {}, (
        "tools/vet/baseline.json grew an entry — fix the finding or "
        "suppress inline with `# vet: ignore[rule]: reason`"
    )
