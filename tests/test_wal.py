"""Durable store: write-ahead log, torn-tail recovery, compaction, and
flock-arbitrated multi-process HA (VERDICT #5; ref anchor: etcd-backed
apiserver durability + cmd/main.go:186 leader election)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from lws_tpu.api.pod import Pod
from lws_tpu.core.serialize import snapshot_store
from lws_tpu.core.store import Store, new_meta
from lws_tpu.core.wal import (
    CorruptWalError,
    StateDir,
    StateLockedError,
    replay_wal,
)
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder
from tests.test_rolling_update import image_of, settle_and_make_ready, update_image


def crash(sd: StateDir) -> None:
    """Simulate kill -9: no final snapshot, no clean close — just the
    kernel-side effects (flock released; fsync'd WAL bytes on disk)."""
    if sd._store is not None:
        sd._store._journal = None
        sd._store = None
    os.close(sd._lock_fd)
    sd._lock_fd = None


def fresh_attached(tmp_path, **kw):
    store = Store()
    sd = StateDir(str(tmp_path), **kw)
    sd.acquire()
    n = sd.attach(store)
    return store, sd, n


def test_acknowledged_writes_survive_crash(tmp_path):
    store, sd, _ = fresh_attached(tmp_path)
    for i in range(10):
        store.create(Pod(meta=new_meta(f"p{i}")))
    p3 = store.get("Pod", "default", "p3")
    p3.status.message = "updated"
    store.update_status(p3)
    store.delete("Pod", "default", "p7")
    expected = snapshot_store(store)
    crash(sd)

    store2, sd2, n = fresh_attached(tmp_path)
    assert n == 9
    assert snapshot_store(store2) == expected
    # rv counter resumed past everything: new writes version above old ones.
    old_rv = store2.get("Pod", "default", "p3").meta.resource_version
    created = store2.create(Pod(meta=new_meta("p-new")))
    assert created.meta.resource_version > old_rv
    sd2.close()


def test_delete_cascade_is_journaled_per_object(tmp_path):
    """Owner-cascade deletes must replay correctly: one WAL record per
    cascaded object (replay applies records verbatim, no re-cascade)."""
    from lws_tpu.core.store import owner_ref

    store, sd, _ = fresh_attached(tmp_path)
    parent = store.create(Pod(meta=new_meta("leader")))
    child_meta = new_meta("worker")
    child_meta.owner_references = [owner_ref(parent)]
    store.create(Pod(meta=child_meta))
    store.delete("Pod", "default", "leader")
    crash(sd)

    records = replay_wal(os.path.join(str(tmp_path), "wal.jsonl"))
    deletes = [r for r in records if r["op"] == "delete"]
    assert {d["name"] for d in deletes} == {"leader", "worker"}
    store2, sd2, _ = fresh_attached(tmp_path)
    assert store2.list("Pod") == []
    sd2.close()


def test_torn_wal_tail_is_discarded(tmp_path):
    store, sd, _ = fresh_attached(tmp_path)
    store.create(Pod(meta=new_meta("whole")))
    crash(sd)
    with open(tmp_path / "wal.jsonl", "a") as f:
        f.write('{"op": "create", "kind": "Pod", "obj": {"meta": {"na')  # torn

    store2, sd2, _ = fresh_attached(tmp_path)
    assert [p.meta.name for p in store2.list("Pod")] == ["whole"]
    sd2.close()


def test_corrupt_mid_wal_refuses_partial_replay(tmp_path):
    store, sd, _ = fresh_attached(tmp_path)
    store.create(Pod(meta=new_meta("a")))
    store.create(Pod(meta=new_meta("b")))
    crash(sd)
    lines = (tmp_path / "wal.jsonl").read_text().splitlines()
    assert len(lines) == 2
    lines[0] = lines[0][:20]  # corrupt a NON-final record
    (tmp_path / "wal.jsonl").write_text("\n".join(lines) + "\n")

    sd2 = StateDir(str(tmp_path))
    sd2.acquire()
    with pytest.raises(CorruptWalError):
        sd2.attach(Store())
    sd2.close(final_snapshot=False)


def test_compaction_resets_wal_and_preserves_state(tmp_path):
    store, sd, _ = fresh_attached(tmp_path, compact_records=5)
    for i in range(23):
        store.create(Pod(meta=new_meta(f"p{i}")))
    # Thresholded compaction ran; the journal stays bounded.
    assert sd._wal_records <= 5
    expected = snapshot_store(store)
    crash(sd)
    store2, sd2, _ = fresh_attached(tmp_path)
    assert snapshot_store(store2) == expected
    sd2.close()


def test_pending_write_survives_threshold_compaction(tmp_path):
    """The write whose journal append crosses the threshold is not yet in the
    store maps when the snapshot is cut; its record must land in the fresh
    WAL or it would vanish."""
    store, sd, _ = fresh_attached(tmp_path, compact_records=3)
    for i in range(3):  # third append triggers compaction mid-write
        store.create(Pod(meta=new_meta(f"p{i}")))
    crash(sd)
    store2, sd2, _ = fresh_attached(tmp_path)
    assert len(store2.list("Pod")) == 3
    sd2.close()


def test_flock_arbitration(tmp_path):
    _, sd, _ = fresh_attached(tmp_path)
    other = StateDir(str(tmp_path))
    assert other.locked_by_other()
    with pytest.raises(StateLockedError):
        other.acquire()
    crash(sd)
    assert not other.locked_by_other()
    other.acquire()
    other.close(final_snapshot=False)


def test_failover_resumes_rolling_update(tmp_path):
    """Active control plane dies (kill -9 equivalent) mid-rolling-update;
    the successor restores from snapshot+WAL and completes the update —
    the reference gets the same from etcd (SURVEY §5 checkpoint/resume)."""
    cp = ControlPlane()
    sd = StateDir(str(tmp_path))
    sd.acquire()
    sd.attach(cp.store)
    cp.create(LWSBuilder().replicas(3).size(2).image("img:v1").build())
    settle_and_make_ready(cp)
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()  # mid-rollout
    crash(sd)

    cp2 = ControlPlane()
    sd2 = StateDir(str(tmp_path))
    sd2.acquire()
    sd2.attach(cp2.store)
    cp2.resync()
    settle_and_make_ready(cp2)
    for i in range(3):
        assert image_of(cp2, f"sample-{i}") == "img:v2"
    assert cp2.store.get("LeaderWorkerSet", "default", "sample").status.updated_replicas == 3
    sd2.close()


# ---------------------------------------------------------------------------
# Real-process HA: kill -9 the active serve; standby takes over.
# ---------------------------------------------------------------------------

LWS_YAML = """\
apiVersion: leaderworkerset.x-k8s.io/v1
kind: LeaderWorkerSet
metadata:
  name: ha-demo
spec:
  replicas: 2
  leaderWorkerTemplate:
    size: 2
"""


def _start_serve(state_dir, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "lws_tpu", "serve", "--port", "0",
         "--state-dir", str(state_dir), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def _wait_for_port(proc, deadline=60):
    """Parse 'serving on http://127.0.0.1:PORT' from serve stdout."""
    end = time.time() + deadline
    port = None
    while time.time() < end:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(f"serve exited rc={proc.returncode}")
            time.sleep(0.05)
            continue
        if "serving on" in line:
            port = int(line.rsplit(":", 1)[1].split()[0].strip("/"))
            return port
    raise AssertionError("serve did not report its port in time")


def _http(port, method, path, body=None):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method=method
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode())


@pytest.mark.slow
def test_kill9_failover_between_real_processes(tmp_path):
    state = tmp_path / "state"
    active = _start_serve(state)
    standby = None
    try:
        port_a = _wait_for_port(active)
        applied = _http(port_a, "POST", "/apply", LWS_YAML.encode())
        assert applied["applied"] == ["LeaderWorkerSet/ha-demo"]

        # Hot spare: blocks on the flock until the active dies.
        standby = _start_serve(state, extra=("--standby",))
        time.sleep(1.0)  # standby reaches the flock wait
        assert standby.poll() is None

        os.kill(active.pid, signal.SIGKILL)  # no goodbye, no final snapshot
        port_b = _wait_for_port(standby, deadline=90)

        objs = _http(port_b, "GET", "/apis/lws")
        assert [o["metadata"]["name"] for o in objs] == ["ha-demo"]
        # The acknowledged write survived AND the control plane is live:
        # reconcilers on the successor materialized the group pods.
        deadline = time.time() + 30
        while time.time() < deadline:
            pods = _http(port_b, "GET", "/apis/pods")
            if len(pods) >= 2:
                break
            time.sleep(0.5)
        assert len(pods) >= 2
    finally:
        for proc in (active, standby):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def test_replayed_update_refreshes_owner_index(tmp_path):
    """An update that drops a controller ownerReference, replayed from the
    WAL, must not leave a stale owner-index entry — or deleting the former
    owner after failover would cascade-delete the deliberately orphaned
    object."""
    from lws_tpu.core.store import owner_ref

    store, sd, _ = fresh_attached(tmp_path)
    parent = store.create(Pod(meta=new_meta("boss")))
    child_meta = new_meta("kid")
    child_meta.owner_references = [owner_ref(parent)]
    child = store.create(Pod(meta=child_meta))
    child.meta.owner_references = []  # deliberate orphaning
    store.update(child)
    crash(sd)

    store2, sd2, _ = fresh_attached(tmp_path)
    store2.delete("Pod", "default", "boss")
    assert store2.try_get("Pod", "default", "kid") is not None
    sd2.close()
