"""/watch long-poll + RemoteClient + Informer (≈ client-go clientset,
informers and listers over the apiserver watch cache, SURVEY §2.9)."""

import threading
import time

import pytest

from lws_tpu.client import ApiError, Informer, RemoteClient
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.server import ApiServer
from lws_tpu.testing import LWSBuilder


def make_server(**kw):
    cp = ControlPlane(auto_ready=True)
    server = ApiServer(cp, port=0, **kw)
    server.start()
    return cp, server, RemoteClient(f"http://127.0.0.1:{server.port}")


def test_watch_replays_buffered_events():
    cp, server, client = make_server()
    try:
        cp.create(LWSBuilder().replicas(1).size(2).build())
        cp.run_until_stable()
        out = client.watch(since=0, timeout=0.1)
        types = {(e["object"]["kind"], e["type"]) for e in out["events"]}
        assert ("LeaderWorkerSet", "ADDED") in types
        assert ("Pod", "ADDED") in types
        assert out["next"] == out["events"][-1]["seq"]
        # Nothing new after the bookmark: empty poll, bookmark unchanged.
        again = client.watch(since=out["next"], timeout=0.1)
        assert again["events"] == [] and again["next"] == out["next"]
    finally:
        server.stop()


def test_watch_long_poll_blocks_until_event():
    cp, server, client = make_server()
    try:
        start_seq = client.current_seq()
        got = {}

        def poll():
            got["out"] = client.watch(since=start_seq, timeout=10)

        t = threading.Thread(target=poll)
        t.start()
        time.sleep(0.1)
        cp.create(LWSBuilder("late").replicas(1).size(1).build())
        t.join(timeout=5)
        assert not t.is_alive()
        kinds = {e["object"]["kind"] for e in got["out"]["events"]}
        assert "LeaderWorkerSet" in kinds
    finally:
        server.stop()


def test_watch_window_expiry_tells_client_to_relist():
    cp, server, client = make_server(watch_buffer=4)
    try:
        cp.create(LWSBuilder().replicas(2).size(2).build())
        cp.run_until_stable()  # >> 4 events
        out = client.watch(since=1, timeout=0.1)
        assert out.get("expired") is True
    finally:
        server.stop()


def test_remote_client_typed_round_trip():
    cp, server, client = make_server()
    try:
        client.apply_object(LWSBuilder().replicas(1).size(2).build())
        cp.run_until_stable()
        assert client.get("lws", "default", "sample")["spec"]["replicas"] == 1
        assert len(client.list("pods")) == 2
        client.scale("default", "sample", 2)
        cp.run_until_stable()
        assert len(client.list("pods")) == 4
        with pytest.raises(ApiError) as e:
            client.get("lws", "default", "ghost")
        assert e.value.code == 404
    finally:
        server.stop()


def test_informer_cache_tracks_cluster_state():
    cp, server, client = make_server()
    try:
        informer = Informer(client, kinds=("LeaderWorkerSet", "Pod"))
        informer.relist()
        assert informer.list("Pod") == []

        cp.create(LWSBuilder().replicas(1).size(2).build())
        cp.run_until_stable()
        informer.sync()
        assert len(informer.list("Pod")) == 2
        assert informer.get("LeaderWorkerSet", "default", "sample") is not None

        cp.store.delete("LeaderWorkerSet", "default", "sample")
        cp.run_until_stable()
        informer.sync()
        assert informer.get("LeaderWorkerSet", "default", "sample") is None
        assert informer.list("Pod") == []  # cascade delete observed
    finally:
        server.stop()


def test_informer_recovers_from_expired_window():
    cp, server, client = make_server(watch_buffer=4)
    try:
        events = []
        informer = Informer(client, kinds=("Pod",),
                            on_event=lambda t, m: events.append(t))
        informer.relist()
        cp.create(LWSBuilder().replicas(2).size(2).build())
        cp.run_until_stable()  # floods the 4-event ring
        informer.sync()  # sees "expired" -> relists
        assert len(informer.list("Pod")) == 4
    finally:
        server.stop()


def test_watch_future_bookmark_expires():
    """A bookmark ahead of the server (restart reset the sequence) must tell
    the client to relist, not hang it on an unreachable sequence number."""
    cp, server, client = make_server()
    try:
        out = client.watch(since=10_000, timeout=0.1)
        assert out.get("expired") is True
        # Informer recovers through the same path.
        informer = Informer(client, kinds=("Pod",))
        informer._seq = 10_000
        cp.create(LWSBuilder().replicas(1).size(1).build())
        cp.run_until_stable()
        informer.sync()
        assert len(informer.list("Pod")) == 1
    finally:
        server.stop()


def test_watch_rejects_malformed_params():
    _, server, client = make_server()
    try:
        with pytest.raises(ApiError) as e:
            client._request("GET", "/watch?since=abc")
        assert e.value.code == 400
    finally:
        server.stop()


def test_stopped_server_unsubscribes_from_store():
    cp, server, _ = make_server()
    n_before = len(cp.store._watchers)
    server.stop()
    assert len(cp.store._watchers) == n_before - 1
