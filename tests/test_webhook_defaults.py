"""LWS defaulting parity (≈ pkg/webhooks/leaderworkerset_webhook.go:52-85 +
its unit tests): every default the reference applies, applied here."""

from lws_tpu.api.types import (
    RestartPolicy,
    RolloutStrategyType,
    StartupPolicy,
    SubdomainPolicy,
    SubGroupPolicyType,
)
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder


def test_defaults_applied_on_create():
    cp = ControlPlane()
    lws = cp.create(LWSBuilder().subgroup(3, None).size(3).build())
    spec = lws.spec
    assert spec.rollout_strategy.type == RolloutStrategyType.ROLLING_UPDATE
    cfg = spec.rollout_strategy.rolling_update_configuration
    assert (cfg.partition, cfg.max_unavailable, cfg.max_surge) == (0, 1, 0)
    assert spec.startup_policy == StartupPolicy.LEADER_CREATED
    assert spec.network_config.subdomain_policy == SubdomainPolicy.SHARED
    # Subgroup policy type defaults to LeaderWorker when a policy is set.
    assert spec.leader_worker_template.sub_group_policy.type == SubGroupPolicyType.LEADER_WORKER


def test_deprecated_default_restart_policy_maps_to_none():
    cp = ControlPlane()
    lws = cp.create(
        LWSBuilder().restart_policy(RestartPolicy.DEPRECATED_DEFAULT).build()
    )
    assert lws.spec.leader_worker_template.restart_policy == RestartPolicy.NONE


def test_defaults_do_not_override_user_choices():
    cp = ControlPlane()
    lws = cp.create(
        LWSBuilder()
        .rollout(max_unavailable=2, max_surge=3, partition=1)
        .startup_policy(StartupPolicy.LEADER_READY)
        .subdomain_policy(SubdomainPolicy.UNIQUE_PER_REPLICA)
        .build()
    )
    cfg = lws.spec.rollout_strategy.rolling_update_configuration
    assert (cfg.partition, cfg.max_unavailable, cfg.max_surge) == (1, 2, 3)
    assert lws.spec.startup_policy == StartupPolicy.LEADER_READY
    assert lws.spec.network_config.subdomain_policy == SubdomainPolicy.UNIQUE_PER_REPLICA
