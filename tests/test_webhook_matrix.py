"""Admission matrix: field-by-field assertions for every pod-mutation path
and the LWS validation table, tracking the reference's integration suites
case-by-case (VERDICT r3 #6):

  P<n>  ≈ /root/reference/test/integration/webhooks/pod_test.go:<line>
  L<n>  ≈ /root/reference/test/integration/webhooks/leaderworkerset_test.go:<line>

Pod cases drive the REAL admission path — pods created through the store by
the controllers, mutated by the registered webhook — and assert the exact
labels, env values (names AND ordering), affinities, and annotations the
contract promises. The reference's suite is 938 LoC of such cases; this is
the same table re-expressed against the TPU-native contract."""

import pytest

from lws_tpu.api import contract
from lws_tpu.api.meta import ObjectMeta
from lws_tpu.api.pod import Container, Pod, PodSpec
from lws_tpu.api.types import StartupPolicy, SubdomainPolicy, SubGroupPolicyType
from lws_tpu.core.store import AdmissionError
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, lws_pods, make_worker_template
from lws_tpu.webhooks.pod_webhook import PodWebhook, gen_group_unique_key

TPU_PORT = str(contract.TPU_PROCESS_DEFAULT_PORT)


def run_cp(lws, **cp_kwargs):
    cp = ControlPlane(auto_ready=True, **cp_kwargs)
    cp.create(lws)
    cp.run_until_stable()
    return cp


def pod(cp, name, lws_name="sample"):
    for p in lws_pods(cp.store, lws_name):
        if p.meta.name == name:
            return p
    raise AssertionError(f"pod {name} not found: {[p.meta.name for p in lws_pods(cp.store, lws_name)]}")


def env_of(p, container=0):
    return {e.name: e.value for e in p.spec.containers[container].env}


def hostnames(p, container=0):
    return env_of(p, container)[contract.TPU_WORKER_HOSTNAMES].split(",")


# ---------------------------------------------------------------------------
# Index labels (P:68, P:91, P:119)


def test_p68_non_lws_pod_untouched():
    """A pod without the LWS name label passes through unmutated."""
    p = Pod(meta=ObjectMeta(name="loner-3", namespace="default"),
            spec=PodSpec(containers=[Container(name="c")]))
    PodWebhook().default(p, None)
    assert p.meta.labels == {} and p.spec.containers[0].env == []
    assert p.spec.affinity is None


def test_p119_p91_index_labels_populated():
    cp = run_cp(LWSBuilder().replicas(2).size(3).build())
    leader = pod(cp, "sample-1")
    assert leader.meta.labels[contract.GROUP_INDEX_LABEL_KEY] == "1"
    assert leader.meta.labels[contract.WORKER_INDEX_LABEL_KEY] == "0"
    worker = pod(cp, "sample-1-2")
    assert worker.meta.labels[contract.WORKER_INDEX_LABEL_KEY] == "2"
    assert worker.meta.labels[contract.GROUP_INDEX_LABEL_KEY] == "1"
    # Group key: sha1(namespace/leaderName), identical across the group.
    key = gen_group_unique_key("default", "sample-1")
    assert leader.meta.labels[contract.GROUP_UNIQUE_HASH_LABEL_KEY] == key


# ---------------------------------------------------------------------------
# Subgroup labels (P:152, P:192, P:229)


def test_p152_leader_subgroup_labels():
    cp = run_cp(LWSBuilder().size(4).replicas(1).tpu_chips(4)
                .leader_template(tpu_chips=4).subgroup(2).build())
    leader = pod(cp, "sample-0")
    assert leader.meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert leader.meta.labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY] == (
        gen_group_unique_key("sample-0", "0")
    )


def test_p192_worker_subgroup_labels_leader_has_tpus():
    """size=4, sgs=2, leader holds TPUs: size%sgs==0 -> worker w's subgroup
    is w//sgs (P:192's table)."""
    cp = run_cp(LWSBuilder().size(4).replicas(1).tpu_chips(4)
                .leader_template(tpu_chips=4).subgroup(2).build())
    assert pod(cp, "sample-0-1").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert pod(cp, "sample-0-2").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    assert pod(cp, "sample-0-3").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    assert pod(cp, "sample-0-2").meta.labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY] == (
        gen_group_unique_key("sample-0", "1")
    )


def test_p229_worker_subgroup_labels_leader_without_tpus():
    """size=5, sgs=2, leader WITHOUT TPUs: (size-1)%sgs==0 -> the leader is
    the folded extra pod, workers shift down: subgroup=(w-1)//sgs."""
    cp = run_cp(LWSBuilder().size(5).replicas(1).tpu_chips(4)
                .leader_template(tpu_chips=0).subgroup(2).build())
    assert pod(cp, "sample-0-1").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert pod(cp, "sample-0-2").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "0"
    assert pod(cp, "sample-0-3").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    assert pod(cp, "sample-0-4").meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"


# ---------------------------------------------------------------------------
# TPU env negative cases (P:265, P:282, P:306)


def test_p265_tpu_pod_outside_lws_gets_no_tpu_env():
    p = Pod(meta=ObjectMeta(name="solo-0", namespace="default"),
            spec=PodSpec(containers=[
                Container(name="c", resources={contract.TPU_RESOURCE_NAME: 4})
            ]))
    PodWebhook().default(p, None)
    assert contract.TPU_WORKER_HOSTNAMES not in env_of(p)


def test_p282_p306_no_tpu_request_no_tpu_env():
    cp = run_cp(LWSBuilder().replicas(1).size(2).build())  # no chips anywhere
    for name in ("sample-0", "sample-0-1"):
        env = env_of(pod(cp, name))
        assert contract.TPU_WORKER_HOSTNAMES not in env
        assert contract.TPU_WORKER_ID not in env
        # ...but the generic LWS vars are still there.
        assert env[contract.LWS_GROUP_SIZE] == "2"


# ---------------------------------------------------------------------------
# TPU env values, whole group (P:330, P:423, P:482, P:539, P:568)


def test_p330_size5_leader_tpu_env_values():
    cp = run_cp(LWSBuilder().replicas(1).size(5).tpu_chips(4)
                .leader_template(tpu_chips=4).build())
    leader = pod(cp, "sample-0")
    env = env_of(leader)
    assert hostnames(leader) == [
        "sample-0.sample", "sample-0-1.sample", "sample-0-2.sample",
        "sample-0-3.sample", "sample-0-4.sample",
    ]
    assert env[contract.TPU_WORKER_ID] == "0"
    assert env[contract.TPU_NAME] == "sample-0"
    assert env[contract.TPU_PROCESS_PORT] == TPU_PORT
    assert env[contract.TPU_PROCESS_ADDRESSES] == ",".join(
        f"{h}:{TPU_PORT}" for h in hostnames(leader)
    )


def test_p423_worker_tpu_env_leader_too():
    cp = run_cp(LWSBuilder().replicas(1).size(3).tpu_chips(4)
                .leader_template(tpu_chips=4).build())
    w2 = pod(cp, "sample-0-2")
    env = env_of(w2)
    assert env[contract.TPU_WORKER_ID] == "2"  # leader holds id 0
    assert hostnames(w2)[0] == "sample-0.sample"
    assert len(hostnames(w2)) == 3
    assert w2.meta.annotations[contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY] == "true"


def test_p482_worker_tpu_env_leader_doesnt():
    """Leader without TPUs is not a TPU worker: ids shift down by one and the
    leader's hostname leaves the list (tpu.go:201-299 shift rule)."""
    cp = run_cp(LWSBuilder().replicas(1).size(3).tpu_chips(4)
                .leader_template(tpu_chips=0).build())
    w2 = pod(cp, "sample-0-2")
    env = env_of(w2)
    assert env[contract.TPU_WORKER_ID] == "1"  # shifted: worker 1 had id 0
    assert hostnames(w2) == ["sample-0-1.sample", "sample-0-2.sample"]
    assert contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY not in w2.meta.annotations


def test_p539_size2_worker_env():
    cp = run_cp(LWSBuilder().replicas(1).size(2).tpu_chips(4)
                .leader_template(tpu_chips=4).build())
    env = env_of(pod(cp, "sample-0-1"))
    assert env[contract.TPU_WORKER_ID] == "1"
    assert env[contract.TPU_WORKER_HOSTNAMES] == "sample-0.sample,sample-0-1.sample"


def test_p568_size1_leader_env():
    cp = run_cp(LWSBuilder().replicas(1).size(1).tpu_chips(4).build())
    leader = pod(cp, "sample-0")
    env = env_of(leader)
    assert env[contract.TPU_WORKER_ID] == "0"
    assert env[contract.TPU_WORKER_HOSTNAMES] == "sample-0.sample"


# ---------------------------------------------------------------------------
# TPU env values, subgroups (P:395, P:452, P:510)


def test_p395_leader_subgroup_tpu_env():
    """size=10 sgs=5 leader with TPUs: subgroup 0's window includes the
    leader and shifts right-edge left by one."""
    cp = run_cp(LWSBuilder().replicas(1).size(10).tpu_chips(4)
                .leader_template(tpu_chips=4).subgroup(5).build())
    leader = pod(cp, "sample-0")
    env = env_of(leader)
    assert env[contract.TPU_WORKER_ID] == "0"
    assert hostnames(leader) == [
        "sample-0.sample", "sample-0-1.sample", "sample-0-2.sample",
        "sample-0-3.sample", "sample-0-4.sample",
    ]


def test_p452_worker_subgroup_tpu_env_leader_too():
    cp = run_cp(LWSBuilder().replicas(1).size(10).tpu_chips(4)
                .leader_template(tpu_chips=4).subgroup(5).build())
    # Worker 7 -> subgroup 1 (10%5==0, w//sgs) with window [5..9] unshifted?
    # Leader requests TPUs and sub_index>0: window shifts left by one: [4..9-1].
    w7 = pod(cp, "sample-0-7")
    env = env_of(w7)
    assert w7.meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    assert env[contract.TPU_WORKER_ID] == str(7 % 5)
    assert hostnames(w7) == [
        "sample-0-5.sample", "sample-0-6.sample", "sample-0-7.sample",
        "sample-0-8.sample", "sample-0-9.sample",
    ]


def test_p510_worker_subgroup_tpu_env_leader_doesnt():
    """size=5 sgs=2 leader without TPUs: worker ids are (w-1)%sgs and the
    windows are the plain [sgs*i+1, sgs*(i+1)] spans."""
    cp = run_cp(LWSBuilder().replicas(1).size(5).tpu_chips(4)
                .leader_template(tpu_chips=0).subgroup(2).build())
    w3 = pod(cp, "sample-0-3")
    env = env_of(w3)
    assert w3.meta.labels[contract.SUBGROUP_INDEX_LABEL_KEY] == "1"
    assert env[contract.TPU_WORKER_ID] == "0"  # (3-1)%2
    assert hostnames(w3) == ["sample-0-3.sample", "sample-0-4.sample"]


# ---------------------------------------------------------------------------
# Multi-container interleave (P:595)


def test_p595_multi_container_some_requesting_tpus():
    """Two TPU containers interleave worker ids (pod j container i ->
    j*n + i) and get per-container ports; non-TPU containers untouched."""
    tmpl = make_worker_template(tpu_chips=4)
    tmpl.spec.containers.append(Container(name="tpu2", resources={contract.TPU_RESOURCE_NAME: 4}))
    tmpl.spec.containers.append(Container(name="sidecar"))
    lws = LWSBuilder().replicas(1).size(2).build()
    lws.spec.leader_worker_template.worker_template = tmpl
    lws.spec.leader_worker_template.leader_template = None
    cp = run_cp(lws)
    w1 = pod(cp, "sample-0-1")
    env0, env1 = env_of(w1, 0), env_of(w1, 1)
    assert env0[contract.TPU_WORKER_ID] == "2"  # pod 1, container 0: 1*2+0
    assert env1[contract.TPU_WORKER_ID] == "3"  # pod 1, container 1: 1*2+1
    assert env0[contract.TPU_PROCESS_PORT] == TPU_PORT
    assert env1[contract.TPU_PROCESS_PORT] == str(contract.TPU_PROCESS_DEFAULT_PORT + 1)
    # Each host appears once per TPU container (interleaved hostname list).
    assert len(hostnames(w1)) == 4
    sidecar_env = env_of(w1, 2)
    assert contract.TPU_WORKER_HOSTNAMES not in sidecar_env
    assert sidecar_env[contract.LWS_GROUP_SIZE] == "2"  # generic vars: all containers


# ---------------------------------------------------------------------------
# Subdomain (P:357)


def test_p357_unique_per_replica_subdomain():
    cp = run_cp(LWSBuilder().replicas(2).size(2).tpu_chips(4)
                .subdomain_policy(SubdomainPolicy.UNIQUE_PER_REPLICA).build())
    leader = pod(cp, "sample-1")
    assert leader.spec.subdomain == "sample-1"
    # TPU hostnames ride the per-replica subdomain.
    assert hostnames(leader)[0].endswith(".sample-1")


# ---------------------------------------------------------------------------
# Exclusive placement affinities (P:622, P:645, P:671, P:698, P:720, P:745)


def exclusive_terms(p, label_key):
    aff = p.spec.affinity
    if aff is None:
        return [], []
    req = [t for t in aff.required_affinity
           if any(r.key == label_key for r in t.match_expressions)]
    anti = [t for t in aff.required_anti_affinity
            if any(r.key == label_key for r in t.match_expressions)]
    return req, anti


def test_p622_leader_exclusive_affinity():
    cp = run_cp(LWSBuilder().replicas(1).size(2).tpu_chips(4)
                .exclusive_topology("topo.k8s/rack").build())
    leader = pod(cp, "sample-0")
    req, anti = exclusive_terms(leader, contract.GROUP_UNIQUE_HASH_LABEL_KEY)
    assert len(req) == 1 and len(anti) == 1
    assert req[0].topology_key == "topo.k8s/rack"
    key = leader.meta.labels[contract.GROUP_UNIQUE_HASH_LABEL_KEY]
    assert req[0].match_expressions[0].values == [key]
    ops = [r.operator.value for r in anti[0].match_expressions]
    assert ops == ["Exists", "NotIn"]


def test_p645_leader_group_plus_subgroup_affinity():
    lws = (LWSBuilder().replicas(1).size(4).tpu_chips(4)
           .leader_template(tpu_chips=4).subgroup(2)
           .exclusive_topology("topo/slice")
           .annotation(contract.SUBGROUP_EXCLUSIVE_KEY_ANNOTATION_KEY, "topo/subslice")
           .build())
    cp = run_cp(lws)
    leader = pod(cp, "sample-0")
    g_req, g_anti = exclusive_terms(leader, contract.GROUP_UNIQUE_HASH_LABEL_KEY)
    s_req, s_anti = exclusive_terms(leader, contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY)
    assert len(g_req) == len(g_anti) == 1  # group topology
    assert len(s_req) == len(s_anti) == 1  # AND subgroup topology
    assert g_req[0].topology_key == "topo/slice"
    assert s_req[0].topology_key == "topo/subslice"


def test_p671_worker_subgroup_only_affinity():
    """Group-exclusive placement gates worker creation on the leader being
    SCHEDULED (follow-the-leader nodeSelector), so this case runs against a
    real scheduled cluster."""
    from lws_tpu.sched import make_slice_nodes

    cp = ControlPlane(auto_ready=True, enable_scheduler=True, require_binding=True)
    for s in range(2):
        nodes = make_slice_nodes(f"slice-{s}", topology="4x4")
        for i, node in enumerate(nodes):  # sub-slice domains: host pairs
            node.meta.labels["topo/subslice"] = f"slice-{s}-sub{i // 2}"
        cp.add_nodes(nodes)
    lws = (LWSBuilder().replicas(1).size(4).tpu_chips(4)
           .leader_template(tpu_chips=4).subgroup(2)
           .exclusive_topology()  # default slice topology key (schedulable)
           .annotation(contract.SUBGROUP_EXCLUSIVE_KEY_ANNOTATION_KEY, "topo/subslice")
           .build())
    cp.create(lws)
    cp.run_until_stable()
    worker = pod(cp, "sample-0-2")
    g_req, _ = exclusive_terms(worker, contract.GROUP_UNIQUE_HASH_LABEL_KEY)
    s_req, s_anti = exclusive_terms(worker, contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY)
    assert g_req == []  # workers follow the leader via nodeSelector, not affinity
    assert len(s_req) == 1 and len(s_anti) == 1


def test_p698_no_exclusive_no_affinity():
    cp = run_cp(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    assert pod(cp, "sample-0").spec.affinity is None


def test_p720_no_reapply_of_exclusive_terms():
    cp = run_cp(LWSBuilder().replicas(1).size(2).tpu_chips(4)
                .exclusive_topology("topo/rack").build())
    leader = pod(cp, "sample-0")
    before = len(leader.spec.affinity.required_affinity)
    PodWebhook().default(leader, None)  # second admission pass (retry path)
    assert len(leader.spec.affinity.required_affinity) == before


def test_p745_user_affinity_terms_preserved():
    from lws_tpu.api.pod import AffinityTerm, LabelSelectorRequirement, AffinityOperator, PodAffinity

    tmpl = make_worker_template(tpu_chips=4)
    tmpl.spec.affinity = PodAffinity(required_affinity=[
        AffinityTerm(topology_key="user/zone", match_expressions=[
            LabelSelectorRequirement("user-key", AffinityOperator.IN, ["v"])
        ])
    ])
    lws = LWSBuilder().replicas(1).size(2).exclusive_topology("topo/rack").build()
    lws.spec.leader_worker_template.worker_template = tmpl
    cp = run_cp(lws)
    leader = pod(cp, "sample-0")
    keys = [t.topology_key for t in leader.spec.affinity.required_affinity]
    assert "user/zone" in keys and "topo/rack" in keys


# ---------------------------------------------------------------------------
# Env ordering (P:801) + gang metadata (P:913)


def test_p801_leader_address_is_first_env_var():
    cp = run_cp(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    for name in ("sample-0", "sample-0-1"):
        p = pod(cp, name)
        for c in p.spec.containers:
            assert c.env[0].name == contract.LWS_LEADER_ADDRESS
            assert c.env[0].value == "sample-0.sample.default"


def test_p913_gang_pod_group_annotation():
    cp = ControlPlane(auto_ready=True, scheduler_provider="gang")
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    for p in lws_pods(cp.store, "sample"):
        gang = p.meta.annotations[contract.POD_GROUP_ANNOTATION_KEY]
        assert gang.startswith("sample-0-")  # <lws>-<groupIdx>-<revision>


# ---------------------------------------------------------------------------
# LeaderReady gate interplay: workers only exist after the leader is ready
# (P: startup-policy rows; pod_controller.go:143-146)


def test_leader_ready_gates_worker_mutation():
    cp = ControlPlane(auto_ready=False)
    cp.create(LWSBuilder().replicas(1).size(3)
              .startup_policy(StartupPolicy.LEADER_READY).build())
    cp.run_until_stable()
    names = {p.meta.name for p in lws_pods(cp.store, "sample")}
    assert names == {"sample-0"}  # leader only until ready
    from lws_tpu.testing import set_pod_ready

    set_pod_ready(cp.store, "default", "sample-0")
    cp.run_until_stable()
    names = {p.meta.name for p in lws_pods(cp.store, "sample")}
    assert names == {"sample-0", "sample-0-1", "sample-0-2"}


# ---------------------------------------------------------------------------
# LWS validation table (L:219-:562)


def reject(lws, match):
    cp = ControlPlane()
    with pytest.raises(AdmissionError, match=match):
        cp.create(lws)


def test_l219_invalid_dns1035_name_rejected():
    for bad in ("Capital", "has_underscore", "-leading-dash", "trailing-", "0digit"):
        reject(LWSBuilder(name=bad).build(), "DNS-1035")
    reject(LWSBuilder(name="x" * 64).build(), "DNS-1035")


def test_l231_l237_invalid_size_replicas():
    bad = LWSBuilder().build()
    bad.spec.leader_worker_template.size = 0
    reject(bad, "size")
    bad2 = LWSBuilder().build()
    bad2.spec.replicas = -1
    reject(bad2, "replicas")


def test_l276_replicas_times_size_overflow():
    bad = LWSBuilder().build()
    bad.spec.replicas = 2**20
    bad.spec.leader_worker_template.size = 2**12
    reject(bad, "MaxInt32")


def test_l249_l255_l261_subgroup_divisibility():
    reject(LWSBuilder().size(5).subgroup(3).build(), "divisible")
    reject(LWSBuilder().size(2).subgroup(3).build(), "greater than size")
    reject(
        LWSBuilder().size(5).subgroup(3, SubGroupPolicyType.LEADER_EXCLUDED).build(),
        "LeaderExcluded",
    )
    # size-1 divisible works for LeaderExcluded (size 7, sgs 3).
    cp = ControlPlane()
    cp.create(LWSBuilder().size(7).subgroup(3, SubGroupPolicyType.LEADER_EXCLUDED).build())


def test_l303_l322_subgroup_immutability():
    cp = ControlPlane()
    lws = cp.create(LWSBuilder().size(4).subgroup(2).build())
    lws.spec.leader_worker_template.sub_group_policy.sub_group_size = 4
    with pytest.raises(AdmissionError, match="immutable"):
        cp.store.update(lws)
    # Adding one after the fact is equally rejected.
    cp2 = ControlPlane()
    lws2 = cp2.create(LWSBuilder().size(4).build())
    from lws_tpu.api.types import SubGroupPolicy

    lws2.spec.leader_worker_template.sub_group_policy = SubGroupPolicy(sub_group_size=2)
    with pytest.raises(AdmissionError, match="immutable"):
        cp2.store.update(lws2)


def test_l410_l485_budget_combinations():
    reject(LWSBuilder().rollout(max_unavailable="150%").build(), "maxUnavailable")
    reject(LWSBuilder().rollout(max_surge="101%").build(), "maxSurge")
    reject(LWSBuilder().rollout(max_unavailable=-1).build(), "maxUnavailable")
    reject(LWSBuilder().rollout(max_surge=-2).build(), "maxSurge")
    reject(LWSBuilder().rollout(max_unavailable=0, max_surge=0).build(), "both")
    # mU=0 + mS>0 is a valid surge-only rollout (L:466).
    ControlPlane().create(LWSBuilder().rollout(max_unavailable=0, max_surge=1).build())
    # mU > replicas allowed (L:410); percentages allowed.
    ControlPlane().create(LWSBuilder().replicas(2).rollout(max_unavailable=5).build())
    ControlPlane().create(LWSBuilder().rollout(max_unavailable="50%", max_surge="25%").build())


def test_l494_l562_partition_rules():
    reject(LWSBuilder().rollout(partition=-1).build(), "partition")
    # partition >= replicas is allowed at create and update (L:502, L:544).
    cp = ControlPlane()
    lws = cp.create(LWSBuilder().replicas(2).rollout(partition=5).build())
    lws.spec.rollout_strategy.rolling_update_configuration.partition = 2
    cp.store.update(lws)
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.rollout_strategy.rolling_update_configuration.partition = -3
    with pytest.raises(AdmissionError, match="partition"):
        cp.store.update(lws)
