"""Call-graph resolution fixture (tools/vet/callgraph.py): plain calls,
self-methods, constructor-typed locals, annotation-typed params,
module-global instances, conditional receivers, and the dynamic shapes
that must produce NO edge (conservatism is the contract)."""

import threading


def helper():
    return 1


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = Other()  # attr type inference: self.peer -> Other

    def step(self):
        self._locked_inner()

    def _locked_inner(self):
        return self.peer.poke()


class Other:
    def poke(self):
        return 2


SHARED = Worker()  # module-global instance: SHARED.step() resolves


def root():
    helper()
    w = Worker()  # constructor-typed local
    w.step()
    SHARED.step()


def typed_param(w: Worker):
    w.step()


def conditional(flag: bool, a: Worker):
    # IfExp receiver: both branches the same class -> still resolves.
    target = a if flag else SHARED
    target.step()


def observer_ref():
    # Function-valued expression (resolve_callable): passing a function,
    # not calling it — an edge only through explicit registration logic.
    return helper


def dynamic(fn):
    fn()  # untyped callable param: NO edge


def duck(obj):
    obj.step()  # untyped receiver: NO edge, even though Worker.step exists
