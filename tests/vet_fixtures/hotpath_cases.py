"""Fixture: hot-path hygiene cases. hot_root is annotated; helper_sleeps
and Engine._inner are reachable through the conservative call graph;
cold() blocks freely because nothing hot reaches it."""

import socket
import time

import numpy as np


def helper_sleeps():
    time.sleep(0.01)


def hot_root():  # hot-path
    helper_sleeps()
    time.sleep(0.5)
    np.asarray([1])
    np.asarray([2])  # vet: ignore[hotpath-host-sync]: deliberate fence for the fixture
    conn = socket.create_connection(("example", 1))
    return conn


class Engine:
    def step(self):  # hot-path
        return self._inner()

    def _inner(self):
        return np.asarray([1, 2, 3])


def helper_with_closure():
    def inner():
        time.sleep(0.2)
    return inner


def hot_root2():  # hot-path
    return helper_with_closure()


def hot_root3():  # hot-path
    return lambda h: np.asarray(h)


def cold():
    time.sleep(1.0)
