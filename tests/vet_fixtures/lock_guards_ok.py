"""Fixture: lock-discipline FALSE-POSITIVE GUARDS — every access pattern
here is legitimate and the pass must stay silent.

Covers: plain `with` blocks, the acquire/try/finally-release idiom,
re-entrant RLock nesting, the `_locked`-suffix caller-holds convention,
`# holds-lock:` method annotations (also how lock-acquiring DECORATORS
are declared — the decorator body is opaque to the lexical pass), and
nested callbacks (checked at their call site's discipline, not here)."""

import threading


def synchronized(fn):
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return wrapper


class Disciplined:
    def __init__(self):
        self._lock = threading.RLock()
        self._data = {}  # guarded-by: _lock

    def with_block(self):
        with self._lock:
            return dict(self._data)

    def try_finally(self):
        self._lock.acquire()
        try:
            self._data["k"] = 1
        finally:
            self._lock.release()

    def after_release(self):
        self._lock.acquire()
        self._lock.release()
        return True  # touching _data HERE would be a finding

    def reentrant(self):
        with self._lock:
            with self._lock:
                self._data.clear()

    def _mutate_locked(self):
        self._data["x"] = 2

    def annotated(self):  # holds-lock: _lock
        return len(self._data)

    @synchronized
    def decorated(self):  # holds-lock: _lock — synchronized() acquires it
        return self._data.get("k")

    def callback_factory(self):
        def callback():
            return self._data
        return callback
