"""Interprocedural lock fixture: blocking under a declared lock (direct
and through a resolvable callee), the cross-class ABBA inversion the
call graph exposes, and the conservative shapes that must stay silent
(blocking outside locks, unresolvable callees)."""

import time
import threading


class Blocker:
    def __init__(self):
        self._lock = threading.Lock()

    def bad_direct(self):
        with self._lock:
            time.sleep(0.1)  # blocking while holding a declared lock

    def bad_transitive(self):
        with self._lock:
            self._helper()  # resolvable callee that blocks

    def _helper(self):
        time.sleep(0.1)

    def ok_outside(self):
        with self._lock:
            x = 1
        time.sleep(0.1)  # lock already released
        return x

    def ok_unresolvable(self, fn):
        with self._lock:
            fn()  # untyped callable: conservatively no propagation


class Left:
    def __init__(self):
        self._l_lock = threading.Lock()

    def fwd(self, r: "Right"):
        with self._l_lock:
            r.take()  # acquires Right._r_lock under Left._l_lock

    def take(self):
        with self._l_lock:
            return 1


class Right:
    def __init__(self):
        self._r_lock = threading.Lock()

    def take(self):
        with self._r_lock:
            return 2

    def back(self, left: Left):
        with self._r_lock:
            left.take()  # acquires Left._l_lock under Right._r_lock: ABBA
