"""Fixture: lock-discipline TRUE POSITIVES (never imported, only parsed).

`Guarded` declares `_items`/`count` guarded and touches them without the
lock in bad_read/bad_write; order_ab/order_ba acquire the class's two
locks in both orders (the ABBA deadlock shape)."""

import threading


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._other_lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock

    def bad_read(self):
        return len(self._items)

    def bad_write(self):
        self.count += 1

    def good(self):
        with self._lock:
            self._items.append(1)
            self.count += 1

    def bad_after_finally_release(self):
        self._lock.acquire()
        try:
            self._items.append(2)
        finally:
            self._lock.release()
        self.count += 1

    def order_ab(self):
        with self._lock:
            with self._other_lock:
                return None

    def order_ba(self):
        with self._other_lock:
            with self._lock:
                return None
