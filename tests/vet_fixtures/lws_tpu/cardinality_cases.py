"""Cardinality fixture (rooted under lws_tpu/): derived label values
against the REAL committed catalogue — an uncatalogued metric with an
identity-derived label is flagged, `lws_rollout_progress`'s `lws` label
(declared `capped`) is the sanctioned escape hatch, bounded/opaque
values stay silent, and one site carries a suppression."""


def bad_identity_fstring(metrics, pod):
    metrics.inc(
        "fixture_requests_total",
        {"pod": f"{pod.meta.namespace}/{pod.meta.name}"},
    )


def bad_str_of_object(metrics, req):
    metrics.observe(
        "fixture_latency_seconds", 0.1, {"request": str(req.request_id)}
    )


def bad_via_binding(metrics, pod):
    who = f"{pod.meta.namespace}/{pod.meta.name}"
    metrics.inc("fixture_requests_total", {"pod": who})


def ok_declared_capped(metrics, lws):
    # The real catalogue declares `lws`: capped on this metric — riding
    # the registry's max_label_sets cap is the sanctioned design.
    metrics.set(
        "lws_rollout_progress", 0.5,
        {"lws": f"{lws.meta.namespace}/{lws.meta.name}", "revision": "r1"},
    )


def ok_enum_literal(metrics):
    metrics.inc("fixture_requests_total", {"outcome": "success"})


def ok_opaque_value(metrics, label):
    metrics.inc("fixture_requests_total", {"pod": label})  # unknown: silent


def ok_suppressed(metrics, pod):
    metrics.inc("fixture_requests_total", {"pod": str(pod.meta.uid)})  # vet: ignore[cardinality-unbounded]: fixture — suppression semantics under test
