"""Hazard-pass fixtures: true positives AND false-positive guards. Lives
under vet_fixtures/lws_tpu/ because the pass is scoped to lws_tpu/ paths.
Never imported — only parsed by the analyzer self-tests."""

import socket
import urllib.request


def swallow_broad():
    try:
        risky()
    except Exception:  # true positive: hazard-exception-swallow
        pass


def swallow_base():
    try:
        risky()
    except (ValueError, BaseException):  # true positive: tuple with a broad member
        pass


def swallow_suppressed():
    try:
        risky()
    except Exception:  # vet: ignore[hazard-exception-swallow]: fixture keep-alive loop
        pass


def narrow_swallow_ok():
    try:
        risky()
    except ValueError:  # narrow handler: NOT flagged
        pass


def broad_but_handled_ok():
    try:
        risky()
    except Exception as e:  # broad but handled: NOT flagged
        print(e)


def dial_no_timeout():
    sock = socket.create_connection(("h", 1))  # true positive: hazard-no-timeout
    sock.close()


def fetch_no_timeout():
    return urllib.request.urlopen("http://h/metrics")  # true positive


def dial_kw_timeout_ok():
    sock = socket.create_connection(("h", 1), timeout=2.0)
    sock.close()


def dial_positional_timeout_ok():
    sock = socket.create_connection(("h", 1), 2.0)
    sock.close()


def fetch_timeout_ok():
    return urllib.request.urlopen("http://h/metrics", None, 5.0)


def risky():
    raise ValueError("boom")
