"""Fixture: span/metric hygiene inside the loadgen package. Lives under a
fake lws_tpu/loadgen/ root (the self-tests pass root=tests/vet_fixtures)
because scenario-emitted metric/span names must stay catalogue-checkable —
a harness that measured the fleet through uncatalogued names would be the
one observability surface nobody can audit."""

from lws_tpu.core import metrics, trace

SCENARIO = "burst"


def bad_scenario_metric():
    # Building the metric name from the scenario would fragment the
    # catalogue: every scenario run would mint a new, ungreppable family.
    metrics.inc("loadgen_" + SCENARIO + "_requests_total")


def bad_scenario_span(name):
    with trace.span(name):
        return None


def bad_unentered_span():
    leak = trace.span("loadgen.run")
    return leak is not None


def ok_scenario_metric():
    metrics.inc("loadgen_requests_total", {"scenario": SCENARIO})


def ok_scenario_span():
    with trace.span("loadgen.run", scenario=SCENARIO):
        return None
