"""Fixture: span/metric hygiene inside the device-runtime plane. Lives
under a fake lws_tpu/obs/ root (the self-tests pass
root=tests/vet_fixtures) because the compile ledger and HBM attribution
emit the forensics surface (`serving_compiles_total{kind}`,
`serving_hbm_pool_bytes{pool}`) that recompile-storm runbooks key on — a
ledger minting per-kind or per-pool metric names dynamically would make
the one surface that explains compile stalls itself unauditable by the
catalogue checker."""

from lws_tpu.core import metrics, trace

KIND = "recompile"
POOL = "kv"


def bad_kind_metric():
    # Building the counter name from the compile kind would fragment the
    # catalogue: first/recompile would mint separate ungreppable families
    # instead of riding the `kind` label.
    metrics.inc("serving_compiles_" + KIND)


def bad_pool_span(name):
    with trace.span(name):
        return None


def bad_unentered_span():
    leak = trace.span("fleet.compile_scrape")
    return leak is not None


def ok_kind_metric():
    metrics.inc("serving_compiles_total", {"engine": "batch", "kind": KIND})


def ok_pool_metric():
    metrics.set("serving_hbm_pool_bytes", 4.2e9, {"pool": POOL})


def ok_entered_span():
    with trace.span("fleet.compile_scrape", instances=2):
        return None
