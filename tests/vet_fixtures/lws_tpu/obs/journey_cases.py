"""Fixture: span/metric hygiene inside the journey vault. Lives under a
fake lws_tpu/obs/ root (the self-tests pass root=tests/vet_fixtures)
because the vault emits the retention-accounting metrics the tail-latency
runbook is built on (`serving_journeys_retained_total`,
`serving_journeys_dropped_total`) — a vault minting per-outcome or
per-reason names dynamically would make the one surface that explains
losses itself unauditable by the catalogue checker."""

from lws_tpu.core import metrics, trace

OUTCOME = "breached"
REASON = "budget"


def bad_outcome_metric():
    # Building the counter name from the retention outcome would fragment
    # the catalogue: every outcome would mint its own ungreppable family
    # instead of riding the `outcome` label.
    metrics.inc("serving_journeys_retained_" + OUTCOME)


def bad_reason_span(name):
    with trace.span(name):
        return None


def bad_unentered_span():
    leak = trace.span("journey.join")
    return leak is not None


def ok_outcome_metric():
    metrics.inc("serving_journeys_retained_total", {"outcome": OUTCOME})


def ok_reason_metric():
    metrics.inc("serving_journeys_dropped_total", {"reason": REASON})


def ok_entered_span():
    with trace.span("journey.join", outcome=OUTCOME):
        return None
