"""Fixture: span/metric hygiene inside the rollout plane. Lives under a
fake lws_tpu/obs/ root (the self-tests pass root=tests/vet_fixtures)
because the canary analyzer emits the rollout decision surface
(`lws_rollout_canary_verdict`, `serving_slo_burn_rate_by_revision`,
`lws_rollout_ledger_events_total`) — an analyzer minting per-revision or
per-verdict names dynamically would make the one surface rollback
automation keys on uncatalogueable."""

from lws_tpu.core import metrics, trace

REVISION = "64d5ae4edd"
VERDICT = "rollback"


def bad_revision_metric():
    # Building the gauge name from the revision hash would mint one
    # ungreppable family per rollout instead of riding the `revision`
    # label — dashboards and the actuation seam key on the literal name.
    metrics.set("lws_rollout_canary_verdict_" + REVISION, -1.0)


def bad_verdict_span(name):
    with trace.span(name):
        return None


def bad_unentered_span():
    leak = trace.span("rollout.evaluate")
    return leak is not None


def ok_verdict_metric():
    metrics.set("lws_rollout_canary_verdict", -1.0,
                {"lws": "default/sample", "revision": REVISION})


def ok_burn_metric():
    metrics.set("serving_slo_burn_rate_by_revision", 55.0,
                {"engine": "paged", "revision": REVISION, "window": "fast"})


def ok_ledger_metric():
    metrics.inc("lws_rollout_ledger_events_total", {"kind": "revision_flip"})


def ok_entered_span():
    with trace.span("rollout.evaluate", verdict=VERDICT):
        return None
