"""Fixture: span/metric hygiene inside the obs package. Lives under a fake
lws_tpu/obs/ root (the self-tests pass root=tests/vet_fixtures) because the
history/recommendation plane emits the decision metrics dashboards page on
(`serving_scale_recommendation`, `serving_slo_burn_rate`) — a recommender
that minted per-role or per-window names dynamically would be the one
decision surface the catalogue checker can't audit."""

from lws_tpu.core import metrics, trace

ROLE = "decode"
WINDOW = "fast"


def bad_role_metric():
    # Building the gauge name from the role would fragment the catalogue:
    # every role would mint its own ungreppable family instead of riding
    # the `role` label.
    metrics.set("serving_scale_recommendation_" + ROLE, 2.0)


def bad_window_span(name):
    with trace.span(name):
        return None


def bad_unentered_span():
    leak = trace.span("obs.evaluate")
    return leak is not None


def ok_role_metric():
    metrics.set("serving_scale_recommendation", 2.0, {"role": ROLE})


def ok_window_metric():
    metrics.set("serving_slo_burn_rate", 1.5,
                {"engine": "paged", "window": WINDOW})


def ok_entered_span():
    with trace.span("obs.evaluate", window=WINDOW):
        return None
