"""Purity fixture (rooted under lws_tpu/ so the scoped pass sees it):
observer registrations with and without exception containment, a marked
reconcile path doing whole-fleet and per-item store scans, the filtered/
suppressed shapes that stay silent, and a suppressed registration."""

import threading


class Recorder:
    def __init__(self):
        self._observers = []

    def add_observer(self, fn):
        self._observers.append(fn)


class Store:  # receiver typing keys on the class NAME (exactly "Store")
    def list(self, kind, namespace=None, labels=None):
        return []


def do_thing(event):
    raise ValueError(event)


def bad_observer(event):
    do_thing(event)  # can raise on the committing writer's thread


def good_observer(event):
    try:
        do_thing(event)
    except Exception:  # broad containment: the observer contract
        pass


def wire(rec: Recorder):
    rec.add_observer(bad_observer)
    rec.add_observer(good_observer)


def wire_suppressed(rec: Recorder):
    rec.add_observer(bad_observer)  # vet: ignore[purity-observer-raise]: fixture — suppression semantics under test


def untyped_helper(store):  # reconcile-path
    # Name-fallback receiver: an unannotated param literally named `store`.
    return store.list("Pod")


class Ctl:
    def __init__(self):
        self.store = Store()

    def reconcile(self, key):  # reconcile-path
        pods = self.store.list("Pod")  # whole-fleet scan
        for p in pods:
            self.store.list("Node")  # per-item fan-out
        return None

    def ok_filtered(self, key):  # reconcile-path
        self.store.list("Pod", "default", labels={"app": "x"})
        return None

    def ok_suppressed(self, key):  # reconcile-path
        self.store.list("Pod")  # vet: ignore[purity-fleet-scan]: fixture — suppression semantics under test
        return None

    def cold_scan(self):
        # NOT a reconcile root and unreachable from one: scans are fine.
        return self.store.list("Pod")
