"""hotpath-serialize-copy fixtures: true positives AND false-positive
guards. Lives under vet_fixtures/lws_tpu/serving/ because the rule is
scoped to lws_tpu/serving/ paths (lexically — no hot-root reachability
required). Never imported — only parsed by the analyzer self-tests."""

import io

import numpy as np


def npz_round_trip(arrays):
    bio = io.BytesIO()  # true positive: hotpath-serialize-copy
    np.savez(bio, **arrays)  # true positive: hotpath-serialize-copy
    return bio.getvalue()


def compressed_variant(bio, arrays):
    np.savez_compressed(bio, **arrays)  # true positive


def suppressed_copy():
    return io.BytesIO()  # vet: ignore[hotpath-serialize-copy]: fixture — a deliberate buffered debug dump


def raw_framing_ok(arrays):
    # The sanctioned shape: raw contiguous views, no intermediate buffer.
    return [memoryview(np.asarray(v).reshape(-1)).cast("B")
            for v in arrays.values()]


def bytes_join_ok(views):
    # b"".join is not BytesIO — the single-copy convenience path is
    # accounted by metrics, not banned by the analyzer.
    return b"".join(bytes(v) for v in views)
