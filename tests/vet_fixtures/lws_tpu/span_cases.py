"""Fixture: span/metric hygiene. Lives under a fake lws_tpu/ root (the
self-tests pass root=tests/vet_fixtures) because the name-literal rules
are scoped to the catalogue checker's source tree."""

from lws_tpu.core import metrics, profile, trace

NAME = "dyn_metric"


def bad_span():
    orphan = trace.span("never.entered")
    return orphan is not None


def ok_span():
    with trace.span("ok.span"):
        return None


def ok_assigned_then_entered():
    dispatch_span = trace.span("ok.assigned")
    with dispatch_span:
        return None


def bad_metric_name():
    metrics.inc(NAME)


def bad_span_name(suffix):
    with trace.span("prefix." + suffix):
        return None


def ok_metric():
    metrics.inc("fixture_total")


def bad_span_shared_name():
    sp = trace.span("leak.shared-name")
    return sp


def ok_other_function_enters_same_name():
    sp = trace.span("ok.shared-name")
    with sp:
        return None


def bad_phase_name(suffix):
    with profile.phase("phase." + suffix):
        return None


def bad_phase_name_direct(suffix):
    from lws_tpu.core.profile import phase

    with phase("phase." + suffix):  # bare-Name call shape must be caught too
        return None


def ok_phase():
    with profile.phase("ok.phase"):
        return None
