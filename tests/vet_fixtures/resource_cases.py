"""Fixture: resource-hygiene cases — leaks flagged, every legitimate
ownership shape (with, finally-close, return, handoff, pytest.raises)
left alone, plus the constructor error-path leak and its fixed twin."""

import socket
import urllib.request

import pytest


def leaky_local():
    sock = socket.socket()
    return None if sock else None


def discarded():
    urllib.request.urlopen("http://example/")


def ok_with():
    with socket.create_connection(("example", 1)) as sock:
        return sock.recv(1)


def ok_closed():
    sock = socket.socket()
    try:
        sock.bind(("", 0))
    finally:
        sock.close()


def ok_returned():
    sock = socket.socket()
    return sock


def ok_handoff(registry):
    sock = socket.socket()
    registry.append(sock)


def ok_expected_raise():
    with pytest.raises(OSError):
        urllib.request.urlopen("http://127.0.0.1:1/")


class LeakyServer:
    def __init__(self):
        self._sock = socket.socket()
        self._sock.bind(("", 0))
        self._sock.listen(1)


class SafeServer:
    def __init__(self):
        self._sock = socket.socket()
        try:
            self._sock.bind(("", 0))
            self._sock.listen(1)
        except OSError:
            self._sock.close()
            raise
