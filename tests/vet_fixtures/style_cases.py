"""Fixture: style pass cases (the folded-in tools/lint.py checks)."""

import json
import os  # noqa: intentional — suppressed unused import
import sys


def bad_default(items=[]):
    return items


def bad_compare(x):
    if x == None:
        return "f-string with no placeholder: f-literal below"
    return f"static"


def bad_except():
    try:
        return json.dumps({})
    except:
        return None


def uses_sys():
    return sys.platform
