X = 1  # vet: ignore -- forgot the rule id
Y = 2  # vet: ignore[style-eq-none]: well-formed marker, nothing to suppress here
R = 5  # vet: ignore[style-eq-none] missing the colon-reason
Z = 3   


def tabbed():
	return Y
