#!/usr/bin/env python
"""Catalogue drift check: every metric and span name used in lws_tpu/ must
be documented in docs/observability.md.

Walks the source AST for the two observability call shapes:

  * metrics writes — `metrics.inc/observe/set("name", ...)` or
    `self.metrics.inc/observe/set("name", ...)` (any attribute chain ending
    in `metrics`);
  * spans — `<anything>.span("name", ...)`.

Only string-literal first arguments count (a dynamic name can't be
catalogued). Fails with the missing names and their call sites, so adding a
metric without documenting it breaks `make check` — the catalogue is the
contract that dashboards and scrape configs are built against.

Run: `make metrics-catalogue` or `python tools/check_metrics_catalogue.py`.
"""

from __future__ import annotations

import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SOURCE_DIR = ROOT / "lws_tpu"
CATALOGUE = ROOT / "docs" / "observability.md"

METRIC_METHODS = {"inc", "observe", "set"}


def _is_metrics_receiver(node: ast.expr) -> bool:
    """True for `metrics`, `self.metrics`, `cp.metrics`, `metricsmod`, ...:
    a Name or attribute chain whose final segment names a metrics object."""
    if isinstance(node, ast.Name):
        return node.id in ("metrics", "metricsmod", "REGISTRY")
    if isinstance(node, ast.Attribute):
        return node.attr in ("metrics", "REGISTRY")
    return False


def collect(path: Path) -> list[tuple[str, str, int]]:
    """[(kind, name, lineno)] for one file; kind in {metric, span}."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out: list[tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        name = node.args[0].value
        if node.func.attr == "span":
            out.append(("span", name, node.lineno))
        elif node.func.attr in METRIC_METHODS and _is_metrics_receiver(node.func.value):
            out.append(("metric", name, node.lineno))
    return out


def main() -> int:
    catalogue = CATALOGUE.read_text()
    missing: list[str] = []
    seen: set[tuple[str, str]] = set()
    for path in sorted(SOURCE_DIR.rglob("*.py")):
        for kind, name, lineno in collect(path):
            # Exact backticked mention only: a bare-substring fallback would
            # let `serving_requests` pass inside `serving_requests_total`.
            if f"`{name}`" in catalogue:
                seen.add((kind, name))
                continue
            missing.append(
                f"{path.relative_to(ROOT)}:{lineno}: {kind} {name!r} "
                f"not documented in docs/observability.md"
            )
    if missing:
        print("\n".join(missing))
        print(f"\n{len(missing)} undocumented observability name(s); "
              f"add them to {CATALOGUE.relative_to(ROOT)}")
        return 1
    metrics_n = len({n for k, n in seen if k == "metric"})
    spans_n = len({n for k, n in seen if k == "span"})
    print(f"catalogue ok: {metrics_n} metric names, {spans_n} span names "
          f"all documented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
