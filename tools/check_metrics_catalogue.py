#!/usr/bin/env python
"""Catalogue drift check, BOTH directions: every metric and span name used
in lws_tpu/ must be documented in docs/observability.md, and every name the
catalogue's Metrics/Spans tables list must have an emitting call site
(orphaned docs rows rot into dashboards built on metrics that never come).

Walks the source AST for the observability call shapes:

  * metrics writes — `metrics.inc/observe/set("name", ...)` or
    `self.metrics.inc/observe/set("name", ...)` (any attribute chain ending
    in `metrics`);
  * spans — `<anything>.span("name", ...)`;
  * declarations — `describe("name", ...)`, which anchor metrics emitted
    through indirection (e.g. the registry's own cardinality-drop counter,
    incremented under its lock rather than through inc()).

Only string-literal first arguments count (a dynamic name can't be
catalogued). Fails with the missing names and their call sites (forward)
or the orphaned table rows (reverse), so drift in either direction breaks
`make check` — the catalogue is the contract that dashboards and scrape
configs are built against.

The Metrics table also carries the **Bound** column — the per-label
cardinality contract (`label: enum|config|capped`, grammar owned by
tools/vet/cardinality.py, which enforces its MEANING against traced
label values). This checker enforces its SHAPE: every row's Bound cell
parses, the Bound and Labels columns name the same label set, and every
label key used at an emitting call site (a dict-literal labels argument)
is declared for its metric.

Run: `make metrics-catalogue` or `python tools/check_metrics_catalogue.py`.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))  # tools.vet import when run as a script

from tools.vet.cardinality import parse_bound_cell  # noqa: E402

SOURCE_DIR = ROOT / "lws_tpu"
CATALOGUE = ROOT / "docs" / "observability.md"

METRIC_METHODS = {"inc", "observe", "set"}
# Labels-arg position per method (lws_tpu.core.metrics signatures);
# a `labels=` keyword wins. Mirrors tools/vet/cardinality.py.
LABELS_ARG_INDEX = {"inc": 1, "observe": 2, "set": 2}


def _is_metrics_receiver(node: ast.expr) -> bool:
    """True for `metrics`, `self.metrics`, `cp.metrics`, `metricsmod`, ...:
    a Name or attribute chain whose final segment names a metrics object."""
    if isinstance(node, ast.Name):
        return node.id in ("metrics", "metricsmod", "REGISTRY")
    if isinstance(node, ast.Attribute):
        return node.attr in ("metrics", "REGISTRY")
    return False


def _label_keys(node: ast.Call) -> set[str]:
    """Literal label KEYS of one metric call's dict-literal labels
    argument; empty for dynamic/absent labels (can't be checked)."""
    labels = None
    for kw in node.keywords:
        if kw.arg == "labels":
            labels = kw.value
    if labels is None:
        idx = LABELS_ARG_INDEX[node.func.attr]
        if len(node.args) > idx:
            labels = node.args[idx]
    if not isinstance(labels, ast.Dict):
        return set()
    return {
        k.value for k in labels.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    }


def collect(path: Path) -> list[tuple[str, str, int, set[str]]]:
    """[(kind, name, lineno, label_keys)] for one file; kind in {metric,
    span, declared}. `declared` rows are describe() declarations — they
    anchor the reverse (orphan) check but are not themselves emissions.
    `label_keys` is non-empty only for metric calls with dict-literal
    labels."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out: list[tuple[str, str, int, set[str]]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        name = node.args[0].value
        if isinstance(node.func, ast.Name) and node.func.id == "describe":
            out.append(("declared", name, node.lineno, set()))
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr == "describe":
            out.append(("declared", name, node.lineno, set()))
        elif node.func.attr == "span":
            out.append(("span", name, node.lineno, set()))
        elif node.func.attr in METRIC_METHODS and _is_metrics_receiver(node.func.value):
            out.append(("metric", name, node.lineno, _label_keys(node)))
    return out


# Catalogue table rows: `| `name` | ...` under the ## Metrics / ## Spans
# headings — the set the reverse check validates against the source.
_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")

# Inside a Labels cell, parenthetical asides carry enum values/examples
# (`result` (`success`/`conflict`/`error`)); strip them before reading the
# backticked label NAMES.
_PAREN_RE = re.compile(r"\([^)]*\)")


def metrics_rows(text: str) -> list[tuple[str, set[str], str]]:
    """[(metric name, labels-cell label names, raw Bound cell)] from the
    catalogue's ## Metrics table, by header-column position."""
    out: list[tuple[str, set[str], str]] = []
    section = None
    columns: list[str] = []
    for line in text.splitlines():
        if line.startswith("## "):
            section = line[3:].strip().lower()
            columns = []
            continue
        if section != "metrics" or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not columns:
            columns = [c.lower() for c in cells]
            continue
        if cells and set(cells[0]) <= {"-", " ", ":"}:
            continue  # the |---|---| separator row
        m = re.match(r"`([^`]+)`", cells[0])
        if m is None or "labels" not in columns or "bound" not in columns:
            continue

        def cell(col: str) -> str:
            idx = columns.index(col)
            return cells[idx] if idx < len(cells) else ""

        labels = set(re.findall(r"`([A-Za-z_]\w*)`", _PAREN_RE.sub("", cell("labels"))))
        out.append((m.group(1), labels, cell("bound")))
    return out


def catalogue_tables(text: str) -> dict[str, set[str]]:
    """{"metric": names, "span": names} from the catalogue's two tables."""
    out: dict[str, set[str]] = {"metric": set(), "span": set()}
    section = None
    for line in text.splitlines():
        if line.startswith("## "):
            heading = line[3:].strip().lower()
            section = {"metrics": "metric", "spans": "span"}.get(heading)
            continue
        if section is None:
            continue
        m = _ROW_RE.match(line)
        if m and m.group(1) not in ("Name", "name"):
            out[section].add(m.group(1))
    return out


def main() -> int:
    catalogue = CATALOGUE.read_text()
    missing: list[str] = []
    seen: set[tuple[str, str]] = set()
    emitted: dict[str, set[str]] = {"metric": set(), "span": set()}
    # metric name -> (label key, first call site) from dict-literal labels
    # arguments — the source side of the Bound declaration check.
    used_labels: dict[str, dict[str, str]] = {}
    for path in sorted(SOURCE_DIR.rglob("*.py")):
        for kind, name, lineno, label_keys in collect(path):
            if kind == "declared":
                # describe() anchors the orphan check (metrics emitted
                # through indirection) but needs no catalogue row itself.
                emitted["metric"].add(name)
                continue
            emitted[kind].add(name)
            for key in label_keys:
                used_labels.setdefault(name, {}).setdefault(
                    key, f"{path.relative_to(ROOT)}:{lineno}")
            # Exact backticked mention only: a bare-substring fallback would
            # let `serving_requests` pass inside `serving_requests_total`.
            if f"`{name}`" in catalogue:
                seen.add((kind, name))
                continue
            missing.append(
                f"{path.relative_to(ROOT)}:{lineno}: {kind} {name!r} "
                f"not documented in docs/observability.md"
            )
    if missing:
        print("\n".join(missing))
        print(f"\n{len(missing)} undocumented observability name(s); "
              f"add them to {CATALOGUE.relative_to(ROOT)}")
        return 1
    # Reverse direction: catalogue rows with no emitting call site are
    # orphaned docs — dashboards built on them watch metrics that never
    # arrive. A row must match a call site OR a describe() declaration.
    orphans = [
        f"docs/observability.md: {kind} {name!r} has no emitting call site "
        f"in lws_tpu/ (orphaned catalogue row)"
        for kind, names in catalogue_tables(catalogue).items()
        for name in sorted(names - emitted[kind])
    ]
    if orphans:
        print("\n".join(orphans))
        print(f"\n{len(orphans)} orphaned catalogue row(s); delete them or "
              f"restore the emitting code")
        return 1
    # Bound contract SHAPE (the meaning — traced label VALUES — lives in
    # `python -m tools.vet --only cardinality`): every Metrics row's Bound
    # cell parses, names exactly the row's Labels, and covers every label
    # key the source actually passes for that metric.
    bound_errors: list[str] = []
    for name, labels, bound_cell in metrics_rows(catalogue):
        bound = parse_bound_cell(bound_cell)
        if bound is None:
            bound_errors.append(
                f"docs/observability.md: metric {name!r} has a malformed "
                f"Bound cell {bound_cell!r} (grammar: `label`: "
                f"enum|config|capped, comma-separated, or `—`)")
            continue
        if set(bound) != labels:
            bound_errors.append(
                f"docs/observability.md: metric {name!r} Bound column "
                f"declares {sorted(bound)} but the Labels column names "
                f"{sorted(labels)} — the two must cover the same label set")
        for key, site in sorted(used_labels.get(name, {}).items()):
            if key not in bound:
                bound_errors.append(
                    f"{site}: metric {name!r} is emitted with label {key!r} "
                    f"but the catalogue's Bound column does not declare it")
    if bound_errors:
        print("\n".join(bound_errors))
        print(f"\n{len(bound_errors)} Bound-contract violation(s); every "
              f"label needs a cardinality class in {CATALOGUE.relative_to(ROOT)}")
        return 1
    metrics_n = len({n for k, n in seen if k == "metric"})
    spans_n = len({n for k, n in seen if k == "span"})
    print(f"catalogue ok: {metrics_n} metric names, {spans_n} span names "
          f"all documented, no orphaned rows")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
