#!/usr/bin/env python
"""Catalogue drift check, BOTH directions: every metric and span name used
in lws_tpu/ must be documented in docs/observability.md, and every name the
catalogue's Metrics/Spans tables list must have an emitting call site
(orphaned docs rows rot into dashboards built on metrics that never come).

Walks the source AST for the observability call shapes:

  * metrics writes — `metrics.inc/observe/set("name", ...)` or
    `self.metrics.inc/observe/set("name", ...)` (any attribute chain ending
    in `metrics`);
  * spans — `<anything>.span("name", ...)`;
  * declarations — `describe("name", ...)`, which anchor metrics emitted
    through indirection (e.g. the registry's own cardinality-drop counter,
    incremented under its lock rather than through inc()).

Only string-literal first arguments count (a dynamic name can't be
catalogued). Fails with the missing names and their call sites (forward)
or the orphaned table rows (reverse), so drift in either direction breaks
`make check` — the catalogue is the contract that dashboards and scrape
configs are built against.

Run: `make metrics-catalogue` or `python tools/check_metrics_catalogue.py`.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SOURCE_DIR = ROOT / "lws_tpu"
CATALOGUE = ROOT / "docs" / "observability.md"

METRIC_METHODS = {"inc", "observe", "set"}


def _is_metrics_receiver(node: ast.expr) -> bool:
    """True for `metrics`, `self.metrics`, `cp.metrics`, `metricsmod`, ...:
    a Name or attribute chain whose final segment names a metrics object."""
    if isinstance(node, ast.Name):
        return node.id in ("metrics", "metricsmod", "REGISTRY")
    if isinstance(node, ast.Attribute):
        return node.attr in ("metrics", "REGISTRY")
    return False


def collect(path: Path) -> list[tuple[str, str, int]]:
    """[(kind, name, lineno)] for one file; kind in {metric, span,
    declared}. `declared` rows are describe() declarations — they anchor
    the reverse (orphan) check but are not themselves emissions."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out: list[tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        name = node.args[0].value
        if isinstance(node.func, ast.Name) and node.func.id == "describe":
            out.append(("declared", name, node.lineno))
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr == "describe":
            out.append(("declared", name, node.lineno))
        elif node.func.attr == "span":
            out.append(("span", name, node.lineno))
        elif node.func.attr in METRIC_METHODS and _is_metrics_receiver(node.func.value):
            out.append(("metric", name, node.lineno))
    return out


# Catalogue table rows: `| `name` | ...` under the ## Metrics / ## Spans
# headings — the set the reverse check validates against the source.
_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")


def catalogue_tables(text: str) -> dict[str, set[str]]:
    """{"metric": names, "span": names} from the catalogue's two tables."""
    out: dict[str, set[str]] = {"metric": set(), "span": set()}
    section = None
    for line in text.splitlines():
        if line.startswith("## "):
            heading = line[3:].strip().lower()
            section = {"metrics": "metric", "spans": "span"}.get(heading)
            continue
        if section is None:
            continue
        m = _ROW_RE.match(line)
        if m and m.group(1) not in ("Name", "name"):
            out[section].add(m.group(1))
    return out


def main() -> int:
    catalogue = CATALOGUE.read_text()
    missing: list[str] = []
    seen: set[tuple[str, str]] = set()
    emitted: dict[str, set[str]] = {"metric": set(), "span": set()}
    for path in sorted(SOURCE_DIR.rglob("*.py")):
        for kind, name, lineno in collect(path):
            if kind == "declared":
                # describe() anchors the orphan check (metrics emitted
                # through indirection) but needs no catalogue row itself.
                emitted["metric"].add(name)
                continue
            emitted[kind].add(name)
            # Exact backticked mention only: a bare-substring fallback would
            # let `serving_requests` pass inside `serving_requests_total`.
            if f"`{name}`" in catalogue:
                seen.add((kind, name))
                continue
            missing.append(
                f"{path.relative_to(ROOT)}:{lineno}: {kind} {name!r} "
                f"not documented in docs/observability.md"
            )
    if missing:
        print("\n".join(missing))
        print(f"\n{len(missing)} undocumented observability name(s); "
              f"add them to {CATALOGUE.relative_to(ROOT)}")
        return 1
    # Reverse direction: catalogue rows with no emitting call site are
    # orphaned docs — dashboards built on them watch metrics that never
    # arrive. A row must match a call site OR a describe() declaration.
    orphans = [
        f"docs/observability.md: {kind} {name!r} has no emitting call site "
        f"in lws_tpu/ (orphaned catalogue row)"
        for kind, names in catalogue_tables(catalogue).items()
        for name in sorted(names - emitted[kind])
    ]
    if orphans:
        print("\n".join(orphans))
        print(f"\n{len(orphans)} orphaned catalogue row(s); delete them or "
              f"restore the emitting code")
        return 1
    metrics_n = len({n for k, n in seen if k == "metric"})
    spans_n = len({n for k, n in seen if k == "span"})
    print(f"catalogue ok: {metrics_n} metric names, {spans_n} span names "
          f"all documented, no orphaned rows")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
