"""Generate docs/reference/ from the API dataclasses + contract constants.

The analog of the reference's genref pipeline (/root/reference/hack/genref ->
site/content/en/docs/reference): instead of parsing Go doc-comments, this
walks the Python modules' dataclasses/enums/constants and lifts each field's
preceding `#` source comments as its description — the comments in
api/*.py ARE the field docs, so the generated reference stays in lockstep
with the code by construction.

Run:  python tools/gen_api_reference.py       (writes docs/reference/*.md)
Check: python tools/gen_api_reference.py --check   (CI-style drift check)
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import importlib
import inspect
import os
import sys
import typing

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

OUT_DIR = os.path.join(_ROOT, "docs", "reference")


# --------------------------------------------------------------------------
# Source-comment extraction: the description of a field/constant is the run
# of '#' lines immediately above it (plus any trailing comment on its line).


def _line_comments(source_lines: list[str]) -> dict[int, str]:
    """lineno (1-based) of each assignment -> joined preceding comment."""
    out = {}
    pending: list[str] = []
    for i, raw in enumerate(source_lines, start=1):
        stripped = raw.strip()
        if stripped.startswith("#"):
            text = stripped.lstrip("#").strip()
            if not text.startswith("----"):  # section rules aren't field docs
                pending.append(text)
            continue
        if stripped:
            if pending:
                out[i] = " ".join(pending)
            pending = []
            if "#" in raw and not stripped.startswith(("'", '"')):
                trailing = raw.split("#", 1)[1].strip()
                if trailing and i not in out:
                    out[i] = trailing
        else:
            pending = []
    return out


def _field_linenos(cls) -> dict[str, int]:
    """field/member name -> source lineno of its assignment in the class."""
    try:
        src = inspect.getsource(cls)
        tree = ast.parse(src)
        base = inspect.getsourcelines(cls)[1] - 1
    except (OSError, TypeError):
        return {}
    out = {}
    cls_node = tree.body[0]
    for node in getattr(cls_node, "body", []):
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            out[node.target.id] = base + node.lineno
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = base + node.lineno
    return out


def _comments_for(cls) -> dict[str, str]:
    try:
        module_lines = inspect.getsource(sys.modules[cls.__module__]).splitlines()
    except OSError:
        return {}
    by_line = _line_comments(module_lines)
    return {
        name: by_line.get(lineno, "")
        for name, lineno in _field_linenos(cls).items()
    }


def _type_str(t) -> str:
    s = typing.get_type_hints  # noqa: F841 — resolved below, fall back to raw
    if isinstance(t, str):
        return t
    # Parameterized generics BEFORE the bare-type branch: on Python 3.10
    # `isinstance(dict[str, str], type)` is True (fixed in 3.11), and the
    # __name__ path would strip the parameters — the generated reference
    # must not depend on which interpreter regenerated it.
    if typing.get_origin(t) is not None:
        return str(t).replace("typing.", "").replace(
            "lws_tpu.api.", "").replace("lws_tpu.", "")
    if isinstance(t, type):
        return t.__name__
    return str(t).replace("typing.", "").replace("lws_tpu.api.", "").replace(
        "lws_tpu.", ""
    )


def _default_str(f: dataclasses.Field) -> str:
    if f.default is not dataclasses.MISSING:
        v = f.default
        if isinstance(v, enum.Enum):
            return f"`{v.value}`"
        return f"`{v!r}`"
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        try:
            v = f.default_factory()  # type: ignore[misc]
            if v in ({}, [], ()):  # noqa: PLR6201
                return f"`{v!r}`"
            return f"`{type(v).__name__}()`"
        except Exception:  # noqa: BLE001
            return "factory"
    return "required"


def _real_doc(cls) -> str | None:
    """The class's OWN docstring, unless it's just the synthesized signature.

    Must not use inspect.getdoc: it walks the MRO, so a docstring-less
    str-enum would render `str.__doc__` builtin noise into the reference.
    """
    doc = cls.__dict__.get("__doc__")
    # "An enumeration." is Python <=3.10's synthesized enum docstring
    # (removed in 3.11) — boilerplate, and interpreter-version-dependent
    # output would churn the generated files on every regeneration.
    if doc and doc.strip() == "An enumeration.":
        return None
    if doc and not doc.startswith(cls.__name__ + "("):
        return inspect.cleandoc(doc)
    return None


def render_dataclass(cls) -> list[str]:
    lines = [f"### `{cls.__name__}`", ""]
    doc = _real_doc(cls)
    if doc:
        lines += [doc, ""]
    comments = _comments_for(cls)
    hints = typing.get_type_hints(cls)
    lines += ["| field | type | default | description |",
              "|---|---|---|---|"]
    for f in dataclasses.fields(cls):
        lines.append(
            f"| `{f.name}` | `{_type_str(hints.get(f.name, f.type))}` "
            f"| {_default_str(f)} | {comments.get(f.name, '')} |"
        )
    lines.append("")
    return lines


def render_enum(cls) -> list[str]:
    lines = [f"### `{cls.__name__}`", ""]
    doc = _real_doc(cls)
    if doc:
        lines += [doc, ""]
    comments = _comments_for(cls)
    lines += ["| value | description |", "|---|---|"]
    for member in cls:
        lines.append(f"| `{member.value}` | {comments.get(member.name, '')} |")
    lines.append("")
    return lines


def render_module_types(module_name: str, title: str, note: str = "") -> str:
    mod = importlib.import_module(module_name)
    lines = [f"# {title}", ""]
    if mod.__doc__:
        lines += [inspect.cleandoc(mod.__doc__), ""]
    if note:
        lines += [note, ""]
    classes = [
        cls for _, cls in inspect.getmembers(mod, inspect.isclass)
        if cls.__module__ == module_name
    ]
    # Definition order (getmembers sorts alphabetically) — references read
    # top-down the way the source does.
    classes.sort(key=lambda c: inspect.getsourcelines(c)[1])
    for cls in classes:
        if isinstance(cls, type) and issubclass(cls, enum.Enum):
            lines += render_enum(cls)
        elif dataclasses.is_dataclass(cls):
            lines += render_dataclass(cls)
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Constants (contract): sectioned tables from module-level assignments.


def render_module_consts(module_name: str, title: str) -> str:
    mod = importlib.import_module(module_name)
    src_lines = inspect.getsource(mod).splitlines()
    by_line = _line_comments(src_lines)
    tree = ast.parse("\n".join(src_lines))

    lines = [f"# {title}", ""]
    if mod.__doc__:
        lines += [inspect.cleandoc(mod.__doc__), ""]

    section = None

    def start_section(name: str):
        nonlocal section
        section = name
        lines.extend([f"## {name}", "", "| constant | value | description |",
                      "|---|---|---|"])

    # Section markers are the `# ---- name ----` ruled comments.
    sections_by_line = {}
    for i, raw in enumerate(src_lines, start=1):
        s = raw.strip()
        if s.startswith("# ----"):
            sections_by_line[i] = s.strip("# -").strip()

    marker_lines = sorted(sections_by_line)

    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) or target.id.startswith("_"):
            continue
        value = getattr(mod, target.id, None)
        if not isinstance(value, (str, int)):
            continue
        latest_marker = [m for m in marker_lines if m < node.lineno]
        sec = sections_by_line[latest_marker[-1]] if latest_marker else "constants"
        if sec != section:
            start_section(sec)
        desc = by_line.get(node.lineno, "")
        lines.append(f"| `{target.id}` | `{value}` | {desc} |")
    lines.append("")
    return "\n".join(lines)


# --------------------------------------------------------------------------


GENERATED_HEADER = (
    "<!-- Generated by tools/gen_api_reference.py — DO NOT EDIT BY HAND.\n"
    "     Regenerate: python tools/gen_api_reference.py -->\n\n"
)

PAGES = {
    "leaderworkerset.v1.md": lambda: render_module_types(
        "lws_tpu.api.types", "LeaderWorkerSet v1 API",
        "Reference parity: `api/leaderworkerset/v1/leaderworkerset_types.go`.",
    ),
    "disaggregatedset.v1.md": lambda: render_module_types(
        "lws_tpu.api.disagg", "DisaggregatedSet v1 API",
        "Reference parity: `api/disaggregatedset/v1/disaggregatedset_types.go`.",
    ) + "\n" + render_module_consts(
        "lws_tpu.api.disagg", "DisaggregatedSet labels and bounds"
    ),
    "core.v1.md": lambda: "\n".join([
        render_module_types("lws_tpu.api.meta", "Object metadata"),
        render_module_types("lws_tpu.api.pod", "Pod / PodTemplate"),
        render_module_types("lws_tpu.api.groupset", "GroupSet (native StatefulSet analog)"),
        render_module_types("lws_tpu.api.node", "Node"),
        render_module_types("lws_tpu.api.service", "Service"),
        render_module_types("lws_tpu.api.pvc", "PersistentVolumeClaim templates"),
        render_module_types("lws_tpu.api.autoscaler", "Autoscaler"),
        render_module_types("lws_tpu.api.podgroup", "PodGroup (gang scheduling)"),
        render_module_types("lws_tpu.api.lease", "Lease (leader election)"),
        render_module_types("lws_tpu.api.revision", "ControllerRevision"),
        render_module_types("lws_tpu.api.intstr", "IntOrPercent"),
    ]),
    "configuration.v1alpha1.md": lambda: render_module_types(
        "lws_tpu.config", "Component configuration",
        "Reference parity: `api/config/v1alpha1/configuration_types.go` + "
        "`defaults.go` (strict decode: unknown fields are rejected).",
    ),
    "labels-annotations-and-environment-variables.md": lambda: render_module_consts(
        "lws_tpu.api.contract", "Labels, annotations and environment variables"
    ),
}


INDEX = """# API reference

Generated from the source dataclasses and contract constants by
`tools/gen_api_reference.py` (the analog of the reference's `hack/genref`
pipeline). Regenerate after any API change; CI-style drift check:
`python tools/gen_api_reference.py --check`.

- [LeaderWorkerSet v1](leaderworkerset.v1.md) — the core group-of-pods API
- [DisaggregatedSet v1](disaggregatedset.v1.md) — multi-role coordinated rollouts
- [Core types](core.v1.md) — pods, groupsets, nodes, services, autoscaler, gang, leases
- [Component configuration](configuration.v1alpha1.md) — the `--config` file schema
- [Labels, annotations and environment variables](labels-annotations-and-environment-variables.md) — the wire contract controllers, webhooks and workloads share
"""


def generate() -> dict[str, str]:
    out = {"_index.md": INDEX}
    for name, fn in PAGES.items():
        out[name] = GENERATED_HEADER + fn().rstrip() + "\n"
    return out


def main() -> int:
    check = "--check" in sys.argv
    pages = generate()
    os.makedirs(OUT_DIR, exist_ok=True)
    drift = []
    for name, content in pages.items():
        path = os.path.join(OUT_DIR, name)
        if check:
            try:
                with open(path) as f:
                    if f.read() != content:
                        drift.append(name)
            except OSError:
                drift.append(name)
        else:
            with open(path, "w") as f:
                f.write(content)
            print(f"wrote {os.path.relpath(path, _ROOT)} ({len(content)} bytes)")
    if check and drift:
        print(f"DRIFT: {drift} — run python tools/gen_api_reference.py", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
