#!/usr/bin/env python
"""Self-contained linter (no third-party deps; ref parity: the reference
wires golangci-lint + go vet into its Makefile, Makefile:152-198).

Checks: syntax, unused imports, bare except, mutable default args,
`== None` comparisons, tabs in indentation, trailing whitespace, and
f-strings with no placeholders. Run: `make lint` or `python tools/lint.py`.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
TARGETS = ["lws_tpu", "tests", "benchmarks", "tools", "bench.py", "__graft_entry__.py"]

# Names whose import is intentional re-export or side-effect.
REEXPORT_OK = {"__init__.py", "conftest.py"}


class Checker(ast.NodeVisitor):
    def __init__(self, path: Path, tree: ast.AST):
        self.path = path
        self.problems: list[tuple[int, str]] = []
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()
        self.visit(tree)

    def problem(self, lineno: int, msg: str) -> None:
        self.problems.append((lineno, msg))

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # effective by existing, never "used"
        for a in node.names:
            if a.name == "*":
                continue
            self.imported.setdefault(a.asname or a.name, node.lineno)

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)

    # -- other checks ------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.problem(node.lineno, "bare `except:` (catch something specific)")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        for default in list(node.args.defaults) + list(node.args.kw_defaults):
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.problem(default.lineno, "mutable default argument")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                (isinstance(comp, ast.Constant) and comp.value is None)
            ):
                self.problem(node.lineno, "`== None` (use `is None`)")
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.problem(node.lineno, "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # Visit the value only: a format spec like {x:.1f} parses as a
        # nested JoinedStr with no placeholders — not a lint problem.
        self.visit(node.value)

    def unused_imports(self, source: str) -> list[tuple[int, str]]:
        out = []
        for name, lineno in self.imported.items():
            if name in self.used or name == "_":
                continue
            # `# noqa` on the import line suppresses (matches existing style).
            line = source.splitlines()[lineno - 1]
            if "noqa" in line:
                continue
            # __all__ mention counts as use.
            if f'"{name}"' in source or f"'{name}'" in source:
                continue
            out.append((lineno, f"unused import `{name}`"))
        return out


def lint_file(path: Path) -> list[str]:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    checker = Checker(path, tree)
    problems = list(checker.problems)
    if path.name not in REEXPORT_OK:
        problems += checker.unused_imports(source)
    for i, line in enumerate(source.splitlines(), 1):
        if line.rstrip() != line:
            problems.append((i, "trailing whitespace"))
        stripped = line.lstrip("\t ")
        if "\t" in line[: len(line) - len(stripped)]:
            problems.append((i, "tab in indentation"))
    rel = path.relative_to(ROOT)
    return [f"{rel}:{lineno}: {msg}" for lineno, msg in sorted(problems)]


def main() -> int:
    files: list[Path] = []
    for target in TARGETS:
        p = ROOT / target
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.exists():
            files.append(p)
    all_problems = []
    for f in files:
        all_problems.extend(lint_file(f))
    for p in all_problems:
        print(p)
    print(f"lint: {len(files)} files, {len(all_problems)} problem(s)", file=sys.stderr)
    return 1 if all_problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
