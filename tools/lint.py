#!/usr/bin/env python
"""Back-compat shim: the linter grew into the `tools/vet` analyzer
package, and the old checks live on unchanged as its `style` pass
(tools/vet/style.py). `python tools/lint.py` and `make lint` both run
exactly `python -m tools.vet --only style`; run `python -m tools.vet`
for the full suite (lock discipline, hot-path hygiene, resource
hygiene, span/metric hygiene — see docs/static-analysis.md)."""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.vet import run_vet  # noqa: E402


def main() -> int:
    return run_vet(only=["style"])


if __name__ == "__main__":
    raise SystemExit(main())
