#!/bin/bash
# Watch the TPU relay; the moment backend init succeeds, run the full bench
# orchestrator (headline -> density -> int8w -> kernel validation -> bf16
# pipeline probe) so one relay window of any length captures a prefix of the
# artifact list (VERDICT r3 next #1). Exits after one full successful run.
# Usage: nohup bash tools/relay_watch.sh >> relay_watch.log 2>&1 &
cd "$(dirname "$0")/.." || exit 1
while true; do
  echo "[watch] $(date -u +%FT%TZ) probing relay..."
  if timeout 300 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch] $(date -u +%FT%TZ) RELAY UP — running bench orchestrator"
    # Outer timeout must exceed the sum of bench.py's internal stage budgets
    # (probe 1500 + flagship 2400 + density 1500 + int8w 900 + kernel 600 + pipeline 600 +
    # headline measure time) or a slow-but-succeeding run gets killed.
    LWS_TPU_ROUND=${LWS_TPU_ROUND:-r05} timeout 12000 python bench.py > .bench_watch_out.json 2> .bench_watch_err.log
    rc=$?
    echo "[watch] bench rc=$rc; stdout:"; cat .bench_watch_out.json
    # Complete = rc 0, fresh (not degraded), and no stage-level "error"
    # records — a partial capture must leave the watcher alive to retry.
    # Pattern is '"error":' exactly: a "kernel_error" attribution on an
    # otherwise-complete capture (kernel fell back on chip) must NOT match.
    if [ $rc -eq 0 ] && grep -q '"value"' .bench_watch_out.json \
        && ! grep -q '"degraded"' .bench_watch_out.json \
        && ! grep -q '"error":' .bench_watch_out.json; then
      echo "[watch] $(date -u +%FT%TZ) capture complete"
      exit 0
    fi
    echo "[watch] bench did not complete cleanly; will retry next window"
  else
    echo "[watch] $(date -u +%FT%TZ) relay still down"
  fi
  sleep 300
done
