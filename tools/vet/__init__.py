"""`lws-tpu vet`: project-aware static analysis suite.

Eight passes over the repo (see docs/static-analysis.md for the rule
catalogue): `style` (the old tools/lint.py, folded in), `locks` (guarded
attributes + lock acquisition order + interprocedural lock-held-blocking
and cross-class lock-order via the shared call graph), `hotpath` (no
blocking or host-sync calls on the decode dispatch path), `resources`
(sockets/files/executors must be closed, including on error paths),
`spans` (spans entered via context manager, metric/span names literal),
`hazards` (no silent `except Exception: pass` swallows, no socket or
urlopen calls without an explicit timeout in lws_tpu/), `purity`
(observer callbacks contain their exceptions; reconcile paths avoid
unfiltered fleet scans), and `cardinality` (metric label values traced
against the catalogue's per-label Bound contract).

The interprocedural passes share ONE conservative call graph
(tools/vet/callgraph.py), built once per run and cached.

Entry points: `make vet`, `python -m tools.vet`, or programmatically
`run_vet(...)` (the analyzer self-tests drive passes through
`run_pass`). Findings not in tools/vet/baseline.json fail the run;
baseline entries that no longer match any finding are orphans and fail
it too (the file may only shrink).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Optional

from tools.vet import core as _core
from tools.vet import (
    cardinality,
    hazards,
    hotpath,
    locks,
    purity,
    resources,
    spans,
    style,
)
from tools.vet.core import (  # noqa: F401 — re-exported for tests
    BASELINE_PATH,
    Finding,
    Module,
    apply_baseline,
    iter_source_files,
    load_baseline,
    load_modules,
    malformed_suppressions,
    write_baseline,
)

PASSES = {
    style.PASS_NAME: style.run,
    locks.PASS_NAME: locks.run,
    hotpath.PASS_NAME: hotpath.run,
    resources.PASS_NAME: resources.run,
    spans.PASS_NAME: spans.run,
    hazards.PASS_NAME: hazards.run,
    purity.PASS_NAME: purity.run,
    cardinality.PASS_NAME: cardinality.run,
}


def run_pass(name: str, paths: list[Path], root: Optional[Path] = None) -> list[Finding]:
    """Run ONE pass over explicit files, suppressions applied, no baseline
    — the shape the analyzer self-tests (tests/test_vet.py) drive."""
    modules = load_modules(paths, root or _core.ROOT)
    by_rel = {m.rel: m for m in modules}
    out = []
    for f in PASSES[name](modules):
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f):
            continue
        out.append(f)
    return out


def collect_findings(
    modules: list[Module], pass_names: Optional[list[str]] = None
) -> tuple[list[Finding], int]:
    """Run passes + the malformed-suppression check over parsed modules,
    dropping suppressed findings: -> (findings, suppressed_count). The ONE
    collection loop run_vet, --write-baseline, and the self-tests share."""
    by_rel = {m.rel: m for m in modules}
    findings: list[Finding] = []
    suppressed = 0
    for name in pass_names or list(PASSES):
        for f in PASSES[name](modules):
            mod = by_rel.get(f.path)
            if mod is not None and mod.suppressed(f):
                suppressed += 1
                continue
            findings.append(f)
    for mod in modules:
        findings.extend(malformed_suppressions(mod))
    return findings, suppressed


def _render_json(findings: list[Finding]) -> str:
    """Machine-readable findings. The four keys `file`/`line`/`rule`/
    `reason` are a STABLE contract (CI annotators parse them); additions
    are allowed, renames are not."""
    return json.dumps(
        [
            {
                "file": f.path,
                "line": f.line,
                "rule": f.rule,
                "reason": f.message,
                "qual": f.qual,
                "detail": f.detail,
            }
            for f in findings
        ],
        indent=2,
    )


def _render_sarif(findings: list[Finding]) -> str:
    """SARIF 2.1.0 (the format code-review UIs ingest). Same stability
    contract as the json format: ruleId/uri/startLine/message map 1:1 to
    rule/file/line/reason."""
    return json.dumps(
        {
            "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
            "version": "2.1.0",
            "runs": [
                {
                    "tool": {
                        "driver": {
                            "name": "lws-tpu-vet",
                            "informationUri": "docs/static-analysis.md",
                            "rules": [
                                {"id": rule}
                                for rule in sorted({f.rule for f in findings})
                            ],
                        }
                    },
                    "results": [
                        {
                            "ruleId": f.rule,
                            "level": "error",
                            "message": {"text": f.message},
                            "locations": [
                                {
                                    "physicalLocation": {
                                        "artifactLocation": {"uri": f.path},
                                        "region": {"startLine": f.line},
                                    }
                                }
                            ],
                        }
                        for f in findings
                    ],
                }
            ],
        },
        indent=2,
    )


def run_vet(
    only: Optional[list[str]] = None,
    paths: Optional[list[Path]] = None,
    use_baseline: bool = True,
    out=sys.stdout,
    fmt: str = "text",
) -> int:
    """Full vet run. Returns the process exit code: 0 clean, 1 findings
    outside the baseline, 2 orphaned baseline entries (the baseline may
    only shrink — mirroring check_metrics_catalogue.py's orphan rule).

    `fmt`: "text" (one render() line per finding), "json", or "sarif" —
    the machine formats write ONE document to `out` (orphan complaints go
    to stderr so the document stays parseable); exit codes are identical
    across formats."""
    pass_names = list(PASSES) if not only else only
    unknown = [p for p in pass_names if p not in PASSES]
    if unknown:
        print(
            f"vet: unknown pass(es): {', '.join(unknown)} "
            f"(valid: {', '.join(PASSES)})",
            file=sys.stderr,
        )
        return 2
    files = paths if paths is not None else iter_source_files()
    modules = load_modules(files)
    findings, suppressed = collect_findings(modules, pass_names)

    # The per-key allowance applies to any full-repo run — `--only
    # hotpath` must not re-report baselined findings as new. The ORPHAN
    # check alone needs every pass: a partial run can't distinguish an
    # orphaned entry from an unexercised pass.
    baseline = load_baseline() if (use_baseline and paths is None) else {}
    new, old, orphans = apply_baseline(findings, baseline)
    if set(pass_names) != set(PASSES):
        orphans = []

    ordered = sorted(new, key=lambda f: (f.path, f.line, f.rule))
    if fmt == "json":
        print(_render_json(ordered), file=out)
    elif fmt == "sarif":
        print(_render_sarif(ordered), file=out)
    else:
        for f in ordered:
            print(f.render(), file=out)
    for key in orphans:
        print(
            f"tools/vet/baseline.json: orphaned entry `{key}` — the finding "
            "(or its full allowed count) no longer exists; shrink the file "
            "(python -m tools.vet --write-baseline)",
            file=(sys.stderr if fmt in ("json", "sarif") else out),
        )
    print(
        f"vet: {len(modules)} files, {len(pass_names)} pass(es), "
        f"{len(new)} finding(s), {len(old)} baselined, "
        f"{suppressed} suppressed, {len(orphans)} orphaned baseline "
        "entr(ies)",
        file=sys.stderr,
    )
    if orphans:
        return 2
    return 1 if new else 0
