"""`lws-tpu vet`: project-aware static analysis suite.

Six passes over the repo (see docs/static-analysis.md for the rule
catalogue): `style` (the old tools/lint.py, folded in), `locks` (guarded
attributes + lock acquisition order), `hotpath` (no blocking or
host-sync calls on the decode dispatch path), `resources` (sockets/
files/executors must be closed, including on error paths), `spans`
(spans entered via context manager, metric/span names literal), and
`hazards` (no silent `except Exception: pass` swallows, no socket or
urlopen calls without an explicit timeout in lws_tpu/).

Entry points: `make vet`, `python -m tools.vet`, or programmatically
`run_vet(...)` (the analyzer self-tests drive passes through
`run_pass`). Findings not in tools/vet/baseline.json fail the run;
baseline entries that no longer match any finding are orphans and fail
it too (the file may only shrink).
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Optional

from tools.vet import core as _core
from tools.vet import hazards, hotpath, locks, resources, spans, style
from tools.vet.core import (  # noqa: F401 — re-exported for tests
    BASELINE_PATH,
    Finding,
    Module,
    apply_baseline,
    iter_source_files,
    load_baseline,
    load_modules,
    malformed_suppressions,
    write_baseline,
)

PASSES = {
    style.PASS_NAME: style.run,
    locks.PASS_NAME: locks.run,
    hotpath.PASS_NAME: hotpath.run,
    resources.PASS_NAME: resources.run,
    spans.PASS_NAME: spans.run,
    hazards.PASS_NAME: hazards.run,
}


def run_pass(name: str, paths: list[Path], root: Optional[Path] = None) -> list[Finding]:
    """Run ONE pass over explicit files, suppressions applied, no baseline
    — the shape the analyzer self-tests (tests/test_vet.py) drive."""
    modules = load_modules(paths, root or _core.ROOT)
    by_rel = {m.rel: m for m in modules}
    out = []
    for f in PASSES[name](modules):
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f):
            continue
        out.append(f)
    return out


def collect_findings(
    modules: list[Module], pass_names: Optional[list[str]] = None
) -> tuple[list[Finding], int]:
    """Run passes + the malformed-suppression check over parsed modules,
    dropping suppressed findings: -> (findings, suppressed_count). The ONE
    collection loop run_vet, --write-baseline, and the self-tests share."""
    by_rel = {m.rel: m for m in modules}
    findings: list[Finding] = []
    suppressed = 0
    for name in pass_names or list(PASSES):
        for f in PASSES[name](modules):
            mod = by_rel.get(f.path)
            if mod is not None and mod.suppressed(f):
                suppressed += 1
                continue
            findings.append(f)
    for mod in modules:
        findings.extend(malformed_suppressions(mod))
    return findings, suppressed


def run_vet(
    only: Optional[list[str]] = None,
    paths: Optional[list[Path]] = None,
    use_baseline: bool = True,
    out=sys.stdout,
) -> int:
    """Full vet run. Returns the process exit code: 0 clean, 1 findings
    outside the baseline, 2 orphaned baseline entries (the baseline may
    only shrink — mirroring check_metrics_catalogue.py's orphan rule)."""
    pass_names = list(PASSES) if not only else only
    unknown = [p for p in pass_names if p not in PASSES]
    if unknown:
        print(f"vet: unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
        return 2
    files = paths if paths is not None else iter_source_files()
    modules = load_modules(files)
    findings, suppressed = collect_findings(modules, pass_names)

    # The per-key allowance applies to any full-repo run — `--only
    # hotpath` must not re-report baselined findings as new. The ORPHAN
    # check alone needs every pass: a partial run can't distinguish an
    # orphaned entry from an unexercised pass.
    baseline = load_baseline() if (use_baseline and paths is None) else {}
    new, old, orphans = apply_baseline(findings, baseline)
    if set(pass_names) != set(PASSES):
        orphans = []

    for f in sorted(new, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render(), file=out)
    for key in orphans:
        print(
            f"tools/vet/baseline.json: orphaned entry `{key}` — the finding "
            "(or its full allowed count) no longer exists; shrink the file "
            "(python -m tools.vet --write-baseline)", file=out,
        )
    print(
        f"vet: {len(modules)} files, {len(pass_names)} pass(es), "
        f"{len(new)} finding(s), {len(old)} baselined, "
        f"{suppressed} suppressed, {len(orphans)} orphaned baseline "
        "entr(ies)",
        file=sys.stderr,
    )
    if orphans:
        return 2
    return 1 if new else 0
