"""CLI for the vet suite: `python -m tools.vet [--only a,b] [--format
json|sarif] [--write-baseline] [paths...]`. See tools/vet/__init__.py and
docs/static-analysis.md."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.vet import PASSES, collect_findings, run_vet
from tools.vet.core import (
    BASELINE_PATH,
    iter_source_files,
    load_modules,
    write_baseline,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.vet",
        description="Project-aware static analysis (docs/static-analysis.md).",
    )
    parser.add_argument(
        "--only",
        help=f"comma-separated pass subset (of: {', '.join(PASSES)})",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="finding output format; json/sarif emit one machine-readable "
             "document with stable file/line/rule/reason keys (default: text)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring tools/vet/baseline.json",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate tools/vet/baseline.json from the current findings "
             "(use ONLY to drop fixed entries — the file may not grow)",
    )
    parser.add_argument("paths", nargs="*", help="explicit files (default: repo targets)")
    args = parser.parse_args(argv)

    only = [p.strip() for p in args.only.split(",")] if args.only else None
    paths = [Path(p).resolve() for p in args.paths] or None

    if args.write_baseline:
        findings, _ = collect_findings(load_modules(iter_source_files()))
        keys = [f.key() for f in findings]
        write_baseline(keys)
        print(f"vet: wrote {len(set(keys))} entries ({len(keys)} findings) "
              f"to {BASELINE_PATH}", file=sys.stderr)
        return 0

    return run_vet(
        only=only, paths=paths, use_baseline=not args.no_baseline,
        fmt=args.format,
    )


if __name__ == "__main__":
    raise SystemExit(main())
