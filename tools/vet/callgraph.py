"""Shared whole-program call-graph engine for the vet passes.

Extracted and generalized from the resolver that used to live privately
in hotpath.py, so the interprocedural passes (hotpath reachability,
lock-hold propagation, reconcile purity, label-source tracing) analyze
the SAME graph instead of four divergent approximations.

The resolver is deliberately conservative — it never guesses a call
target into a false positive. An edge exists only when the target is
provable from local syntax:

  * `f(...)`             -> a function of the same module (nested defs of
    the caller shadow module-level names), or a symbol imported via
    `from lws_tpu.x.y import f`;
  * `Class(...)`         -> `Class.__init__`, when `Class` is a project
    class of the same module or imported by name;
  * `self.m(...)`        -> a method of the enclosing class (single-level
    resolvable project bases included);
  * `alias.f(...)`       -> a module-level function (or class ctor) of a
    module imported as `from lws_tpu.x import alias` / `import
    lws_tpu.x.alias`;
  * `<recv>.m(...)`      -> a method, when the receiver's class is
    inferred: `self.attr` assigned `ClassName(...)` in any method (or
    annotated), a module-level `NAME = ClassName(...)` global (same
    module or via alias), a local `x = ClassName(...)` assignment, or a
    parameter annotation (`Optional[X]`/`X | None` unwrap to `X`).

Anything else — callables passed as values, ambiguous names, attributes
on untyped receivers — has NO outgoing edge by design. Containment is a
separate edge kind: nested defs belong to their enclosing function
(pipeline commit callbacks run inside the consume path), lambdas are not
graph nodes at all and are scanned inline by the passes.

`resolve_callable` additionally resolves a *function-valued expression*
(`self.on_span`, `target.on_event`, a bare name) to its graph node — the
purity pass uses it on `add_observer(...)` arguments.

Scope/limits contract (docs/static-analysis.md#call-graph): one level of
base-class lookup, one level of import indirection, no flow through
containers, dicts, or re-bound callables. When the engine cannot prove a
target it stays silent, so every interprocedural finding downstream is
anchored on provable edges only.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.vet.core import Module

# (module rel path, qualname) — the identity of every graph node.
Key = tuple[str, str]


class FuncInfo:
    """One function/method definition — a call-graph node."""

    def __init__(self, mod: Module, qual: str, cls: Optional[str],
                 node: ast.FunctionDef) -> None:
        self.mod = mod
        self.qual = qual  # e.g. "DecodePipeline.push" or "beat"
        self.cls = cls    # enclosing class qualname, if any
        self.node = node

    @property
    def key(self) -> Key:
        return (self.mod.rel, self.qual)

    @property
    def name(self) -> str:
        return self.node.name


class ClassInfo:
    """One class definition plus its inferred attribute types."""

    def __init__(self, mod: Module, qual: str, node: ast.ClassDef) -> None:
        self.mod = mod
        self.qual = qual
        self.node = node
        self.methods: dict[str, str] = {}  # method name -> qualname
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = f"{qual}.{child.name}"
        # attr -> class Key, filled by CallGraph._infer_attr_types once
        # every class is known (self.attr = ClassName(...) / annotations).
        self.attr_types: dict[str, Key] = {}

    @property
    def key(self) -> Key:
        return (self.mod.rel, self.qual)


class _ImportEntry:
    """One resolved project import: a whole module or one of its symbols."""

    __slots__ = ("module_rel", "symbol")

    def __init__(self, module_rel: str, symbol: Optional[str] = None) -> None:
        self.module_rel = module_rel  # repo-relative .py path
        self.symbol = symbol          # None for whole-module aliases


class CallGraph:
    """The project graph: functions, classes, imports, inferred types."""

    def __init__(self, modules: list[Module]) -> None:
        self.modules = modules
        self.funcs: dict[Key, FuncInfo] = {}
        self.classes: dict[Key, ClassInfo] = {}
        self._known_rels = {m.rel for m in modules}
        for mod in modules:
            self._collect_defs(mod)
        self.imports: dict[str, dict[str, _ImportEntry]] = {
            mod.rel: self._module_imports(mod) for mod in modules
        }
        # Module-level globals holding a class instance: rel -> name -> Key.
        self.globals: dict[str, dict[str, Key]] = {}
        for mod in modules:
            self.globals[mod.rel] = self._module_globals(mod)
        for cls in self.classes.values():
            self._infer_attr_types(cls)
        # Containment: nested defs of a function (qualname-prefix children).
        self.children: dict[Key, list[Key]] = {}
        by_mod: dict[str, list[FuncInfo]] = {}
        for f in self.funcs.values():
            by_mod.setdefault(f.mod.rel, []).append(f)
        for peers in by_mod.values():
            for f in peers:
                prefix = f.qual + "."
                kids = [g.key for g in peers if g.qual.startswith(prefix)]
                if kids:
                    self.children[f.key] = kids
        self._locals_cache: dict[Key, dict[str, Key]] = {}
        self._callees_cache: dict[Key, list[tuple[Key, ast.Call]]] = {}

    # ---- collection -------------------------------------------------------
    def _collect_defs(self, mod: Module) -> None:
        def walk(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    self.funcs[(mod.rel, qual)] = FuncInfo(mod, qual, cls, child)
                    walk(child, qual, cls)
                elif isinstance(child, ast.ClassDef):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    self.classes[(mod.rel, qual)] = ClassInfo(mod, qual, child)
                    walk(child, qual, qual)
                else:
                    walk(child, prefix, cls)

        if mod.tree is not None:
            walk(mod.tree, "", None)

    def _module_imports(self, mod: Module) -> dict[str, _ImportEntry]:
        """alias -> project import entry. `from lws_tpu.x import y` is a
        MODULE import when lws_tpu/x/y.py exists, else a SYMBOL of
        lws_tpu/x.py (or the package __init__)."""
        out: dict[str, _ImportEntry] = {}
        if mod.tree is None:
            return out
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith("lws_tpu"):
                base = node.module.replace(".", "/")
                for a in node.names:
                    alias = a.asname or a.name
                    as_mod = f"{base}/{a.name}.py"
                    if as_mod in self._known_rels:
                        out[alias] = _ImportEntry(as_mod)
                    elif f"{base}.py" in self._known_rels:
                        out[alias] = _ImportEntry(f"{base}.py", a.name)
                    elif f"{base}/__init__.py" in self._known_rels:
                        out[alias] = _ImportEntry(f"{base}/__init__.py", a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if not a.name.startswith("lws_tpu."):
                        continue
                    rel = a.name.replace(".", "/") + ".py"
                    if rel in self._known_rels:
                        out[a.asname or a.name.split(".")[-1]] = _ImportEntry(rel)
        return out

    def _module_globals(self, mod: Module) -> dict[str, Key]:
        """Top-level `NAME = ClassName(...)` instances (the obs planes'
        VAULT/LEDGER/RECORDER singletons)."""
        out: dict[str, Key] = {}
        if mod.tree is None:
            return out
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                cls = self._ctor_class(mod.rel, stmt.value)
                if cls is not None:
                    out[stmt.targets[0].id] = cls
        return out

    # ---- type resolution --------------------------------------------------
    def lookup_class(self, mod_rel: str, name: str) -> Optional[Key]:
        """A class name visible in `mod_rel`: same module or imported."""
        if (mod_rel, name) in self.classes:
            return (mod_rel, name)
        entry = self.imports.get(mod_rel, {}).get(name)
        if entry is not None:
            symbol = entry.symbol or name
            if (entry.module_rel, symbol) in self.classes:
                return (entry.module_rel, symbol)
        return None

    def _ctor_class(self, mod_rel: str, value: ast.expr) -> Optional[Key]:
        """`ClassName(...)` / `alias.ClassName(...)` -> the class Key.
        An IfExp whose branches construct the SAME class keeps the type."""
        if isinstance(value, ast.IfExp):
            a = self._ctor_class(mod_rel, value.body)
            b = self._ctor_class(mod_rel, value.orelse)
            return a if a is not None and a == b else None
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        if isinstance(fn, ast.Name):
            return self.lookup_class(mod_rel, fn.id)
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            entry = self.imports.get(mod_rel, {}).get(fn.value.id)
            if entry is not None and entry.symbol is None \
                    and (entry.module_rel, fn.attr) in self.classes:
                return (entry.module_rel, fn.attr)
        return None

    def _annotation_class(self, mod_rel: str, ann: Optional[ast.expr]) -> Optional[Key]:
        """`X` / `"X"` / `Optional[X]` / `X | None` -> X's class Key."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip()
            return self.lookup_class(mod_rel, name) if name.isidentifier() else None
        if isinstance(ann, ast.Name):
            return self.lookup_class(mod_rel, ann.id)
        if isinstance(ann, ast.Subscript):  # Optional[X] — unwrap one level
            base = ann.value
            if isinstance(base, ast.Name) and base.id == "Optional":
                return self._annotation_class(mod_rel, ann.slice)
            if isinstance(base, ast.Attribute) and base.attr == "Optional":
                return self._annotation_class(mod_rel, ann.slice)
            return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            for side in (ann.left, ann.right):
                if isinstance(side, ast.Constant) and side.value is None:
                    continue
                return self._annotation_class(mod_rel, side)
        return None

    def _infer_attr_types(self, cls: ClassInfo) -> None:
        """`self.attr = ClassName(...)` (or annotated) anywhere in the
        class's methods -> attr type. Conflicting assignments erase the
        entry — an attr rebound to two classes has no single type."""
        conflicted: set[str] = set()
        for fn in cls.node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                for tgt in targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    attr = tgt.attr
                    typ = None
                    if stmt.value is not None:
                        typ = self._ctor_class(cls.mod.rel, stmt.value)
                    if typ is None and isinstance(stmt, ast.AnnAssign):
                        typ = self._annotation_class(cls.mod.rel, stmt.annotation)
                    if typ is None:
                        # Unknown re-assignment poisons a previously inferred
                        # type only if it's a Call (could be anything); plain
                        # None/flag writes don't.
                        if isinstance(stmt.value, ast.Call) and attr in cls.attr_types:
                            conflicted.add(attr)
                        continue
                    if attr in cls.attr_types and cls.attr_types[attr] != typ:
                        conflicted.add(attr)
                    else:
                        cls.attr_types[attr] = typ
        for attr in conflicted:
            cls.attr_types.pop(attr, None)

    def _fn_locals(self, info: FuncInfo) -> dict[str, Key]:
        """name -> class Key for a function's provably-typed locals:
        annotated parameters and `x = ClassName(...)` assignments (nested
        defs excluded). A re-binding to an unknown type erases the name."""
        cached = self._locals_cache.get(info.key)
        if cached is not None:
            return cached
        env: dict[str, Key] = {}
        args = info.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            typ = self._annotation_class(info.mod.rel, a.annotation)
            if typ is not None:
                env[a.arg] = typ

        def scan(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name):
                    name = child.targets[0].id
                    typ = self._ctor_class(info.mod.rel, child.value)
                    if typ is None:
                        typ = self.resolve_receiver_type(info, child.value, env)
                    if typ is not None:
                        env[name] = typ
                    else:
                        env.pop(name, None)
                elif isinstance(child, ast.AnnAssign) \
                        and isinstance(child.target, ast.Name):
                    typ = self._annotation_class(info.mod.rel, child.annotation)
                    if typ is not None:
                        env[child.target.id] = typ
                scan(child)

        scan(info.node)
        self._locals_cache[info.key] = env
        return env

    def resolve_receiver_type(
        self, info: FuncInfo, expr: ast.expr,
        env: Optional[dict[str, Key]] = None,
    ) -> Optional[Key]:
        """The class of a receiver expression, when provable: a typed
        local/param, `self.attr` with an inferred type, a module global
        (same module or `alias.NAME`). An IfExp whose branches resolve to
        the SAME class keeps the type (`vault if vault else VAULT`)."""
        if isinstance(expr, ast.IfExp):
            a = self.resolve_receiver_type(info, expr.body, env)
            b = self.resolve_receiver_type(info, expr.orelse, env)
            return a if a is not None and a == b else None
        if isinstance(expr, ast.Name):
            if env is not None and expr.id in env:
                return env[expr.id]
            g = self.globals.get(info.mod.rel, {}).get(expr.id)
            if g is not None:
                return g
            entry = self.imports.get(info.mod.rel, {}).get(expr.id)
            if entry is not None and entry.symbol is not None:
                return self.globals.get(entry.module_rel, {}).get(entry.symbol)
            return None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and info.cls:
                cls = self.classes.get((info.mod.rel, info.cls))
                if cls is not None:
                    return cls.attr_types.get(expr.attr)
                return None
            entry = self.imports.get(info.mod.rel, {}).get(expr.value.id)
            if entry is not None and entry.symbol is None:
                return self.globals.get(entry.module_rel, {}).get(expr.attr)
        return None

    def method_of(self, cls_key: Key, name: str) -> Optional[Key]:
        """`name` on `cls_key`, checking one level of resolvable bases."""
        cls = self.classes.get(cls_key)
        if cls is None:
            return None
        if name in cls.methods:
            return (cls_key[0], cls.methods[name])
        for base in cls.node.bases:
            base_key = None
            if isinstance(base, ast.Name):
                base_key = self.lookup_class(cls.mod.rel, base.id)
            elif isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name):
                entry = self.imports.get(cls.mod.rel, {}).get(base.value.id)
                if entry is not None and entry.symbol is None \
                        and (entry.module_rel, base.attr) in self.classes:
                    base_key = (entry.module_rel, base.attr)
            if base_key is not None:
                parent = self.classes.get(base_key)
                if parent is not None and name in parent.methods:
                    return (base_key[0], parent.methods[name])
        return None

    # ---- call resolution --------------------------------------------------
    def resolve_call(self, info: FuncInfo, call: ast.Call) -> Optional[Key]:
        """The single provable target of one call expression, or None."""
        return self.resolve_callable(info, call.func)

    def resolve_callable(self, info: FuncInfo, fn: ast.expr) -> Optional[Key]:
        """A function-valued expression -> its graph node. Used both for
        call sites and for callables passed by value (observer args)."""
        mod_rel = info.mod.rel
        if isinstance(fn, ast.Name):
            # Nested def of this function (or an enclosing one) shadows
            # module scope.
            qual = info.qual
            while qual:
                key = (mod_rel, f"{qual}.{fn.id}")
                if key in self.funcs:
                    return key
                qual = qual.rpartition(".")[0]
            if (mod_rel, fn.id) in self.funcs:
                return (mod_rel, fn.id)
            cls_key = self.lookup_class(mod_rel, fn.id)
            if cls_key is not None:
                return self.method_of(cls_key, "__init__")
            entry = self.imports.get(mod_rel, {}).get(fn.id)
            if entry is not None and entry.symbol is not None \
                    and (entry.module_rel, entry.symbol) in self.funcs:
                return (entry.module_rel, entry.symbol)
            return None
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name):
                if recv.id == "self" and info.cls:
                    target = self.method_of((mod_rel, info.cls), fn.attr)
                    if target is not None:
                        return target
                entry = self.imports.get(mod_rel, {}).get(recv.id)
                if entry is not None and entry.symbol is None:
                    if (entry.module_rel, fn.attr) in self.funcs:
                        return (entry.module_rel, fn.attr)
                    if (entry.module_rel, fn.attr) in self.classes:
                        return self.method_of((entry.module_rel, fn.attr), "__init__")
            recv_type = self.resolve_receiver_type(info, recv, self._fn_locals(info))
            if recv_type is not None:
                return self.method_of(recv_type, fn.attr)
        return None

    def callees(self, info: FuncInfo) -> list[tuple[Key, ast.Call]]:
        """Every resolvable (callee key, call node) in one function body,
        nested defs excluded (they are containment children)."""
        cached = self._callees_cache.get(info.key)
        if cached is not None:
            return cached
        out: list[tuple[Key, ast.Call]] = []

        def scan(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # containment edge; lambdas stay inline
                if isinstance(child, ast.Call):
                    target = self.resolve_call(info, child)
                    if target is not None and target != info.key:
                        out.append((target, child))
                scan(child)

        scan(info.node)
        self._callees_cache[info.key] = out
        return out

    def reachable(self, roots: Iterable[Key]) -> set[Key]:
        """BFS closure over call + containment edges."""
        seen: set[Key] = set()
        frontier = [k for k in roots]
        while frontier:
            key = frontier.pop()
            if key in seen:
                continue
            seen.add(key)
            info = self.funcs.get(key)
            if info is None:
                continue
            for kid in self.children.get(key, ()):
                if kid not in seen:
                    frontier.append(kid)
            for callee, _ in self.callees(info):
                if callee not in seen:
                    frontier.append(callee)
        return seen


# One vet run parses the repo once and hands the SAME module list to every
# pass (tools/vet/__init__.collect_findings) — four interprocedural passes
# must not build four graphs. Identity-keyed, tiny, and dropped with the
# list: exactly the shape the wallclock bench budgets.
_GRAPH_CACHE: list[tuple[int, list[Module], CallGraph]] = []
_GRAPH_CACHE_MAX = 4


def build(modules: list[Module]) -> CallGraph:
    for ident, held, graph in _GRAPH_CACHE:
        if ident == id(modules) and held is modules:
            return graph
    graph = CallGraph(modules)
    _GRAPH_CACHE.append((id(modules), modules, graph))
    del _GRAPH_CACHE[:-_GRAPH_CACHE_MAX]
    return graph
