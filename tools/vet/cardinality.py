"""Metric label-cardinality pass + the catalogue's bound contract.

The 1,000-instance fleet surface dies first by label cardinality: one
per-request label value turns a bounded metric into an allocator of
label sets, the registry's `max_label_sets` cap starts eating samples
(`lws_metric_label_sets_dropped_total`), and the dashboards built on the
metric silently go blind. The runtime cap bounds the damage; THIS pass
bounds the cause, statically, before the series ever exist.

Contract (docs/observability.md, the Metrics table's **Bound** column):
every label of every metric declares a cardinality class —

  * `enum`   — a closed literal set in code (`engine`, `role`, `state`);
  * `config` — bounded by registered components/configuration, not by
    workload (`controller`, `watchdog`, `site`, `endpoint`);
  * `capped` — legitimately workload- or fleet-derived (`instance`,
    `lws`, `revision`, `device`): the series population rides the
    registry's `max_label_sets` cap BY DESIGN, and the emitting site
    owns a retirement story (clear_gauge on supersede, scrape-cache
    eviction, ...).

`tools/check_metrics_catalogue.py` enforces the contract's SHAPE (every
catalogued metric has a well-formed Bound cell; every label key used at
an emitting call site is declared). This pass enforces its MEANING:

  * `cardinality-unbounded` — a label VALUE at an `inc`/`set`/`observe`
    site traces back to per-request/per-object identity (an f-string
    embedding non-literal data, `str(...)` of a non-literal, an
    attribute chain ending in `.name`/`.uid`/`.namespace`/`.id`/
    `request_id`/`trace_id`, or a local assigned from one of those) while
    the catalogue declares the label `enum`/`config` — or does not
    declare it at all. Declaring the label `capped` is the sanctioned
    escape hatch, and it is a DOCS change reviewers see, not a source
    suppression.

Value tracing is conservative: literals and literal-conditional locals
are bounded, the identity patterns above are derived, and everything
else (opaque names, parameters, dict lookups) is UNKNOWN and stays
silent — the pass never guesses a finding.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from tools.vet.core import ROOT, Finding, Module

PASS_NAME = "cardinality"

CATALOGUE_PATH = ROOT / "docs" / "observability.md"

BOUND_CLASSES = ("enum", "config", "capped")

# Identity patterns. `<x>.meta.name/uid/namespace` is the store's object
# identity (TypedObject metadata — bare `.name` alone also names REGISTERED
# components, a closed config set, so it does NOT count); `request_id`/
# `trace_id`/`span_id`/`.id`/`.uid` are request/object identity anywhere.
META_IDENTITY_ATTRS = {"name", "uid", "namespace"}
IDENTITY_ATTRS = {"request_id", "trace_id", "span_id", "id", "uid"}

METRIC_METHODS = {"inc", "observe", "set"}
# Positional index of the labels argument per method, mirroring
# lws_tpu.core.metrics: inc(name, labels, value), observe(name, value,
# labels), set(name, value, labels). A `labels=` keyword always wins.
LABELS_ARG_INDEX = {"inc": 1, "observe": 2, "set": 2}

_BOUND_ENTRY_RE = re.compile(r"`?([A-Za-z_][\w]*)`?\s*:\s*([a-z]+)")


def _is_metrics_receiver(node: ast.expr) -> bool:
    """Same receiver shapes tools/check_metrics_catalogue.py accepts:
    `metrics`, `self.metrics`, `cp.metrics`, a registry object."""
    if isinstance(node, ast.Name):
        return node.id in ("metrics", "metricsmod", "REGISTRY")
    if isinstance(node, ast.Attribute):
        return node.attr in ("metrics", "REGISTRY")
    return False


def parse_bound_cell(cell: str) -> Optional[dict[str, str]]:
    """One Bound-column cell -> {label: class}, {} for `—`/empty, or None
    when malformed (unparseable entries or an unknown class). Shared with
    tools/check_metrics_catalogue.py — the contract has ONE grammar."""
    text = cell.strip()
    if text in ("", "—", "-", "–"):
        return {}
    out: dict[str, str] = {}
    for part in text.split(","):
        m = _BOUND_ENTRY_RE.fullmatch(part.strip())
        if m is None or m.group(2) not in BOUND_CLASSES:
            return None
        out[m.group(1)] = m.group(2)
    return out


def catalogue_bounds(text: str) -> dict[str, dict[str, str]]:
    """metric name -> {label: bound class}, parsed from the ## Metrics
    table's Bound column. Malformed cells parse as {} here — the shape
    check (check_metrics_catalogue.py) owns rejecting them loudly; this
    pass then treats the metric's labels as undeclared."""
    bounds: dict[str, dict[str, str]] = {}
    section = None
    columns: list[str] = []
    for line in text.splitlines():
        if line.startswith("## "):
            section = line[3:].strip().lower()
            columns = []
            continue
        if section != "metrics" or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not columns:
            columns = [c.lower() for c in cells]
            continue
        if cells and set(cells[0]) <= {"-", " ", ":"}:
            continue  # the |---|---| separator row
        m = re.match(r"`([^`]+)`", cells[0])
        if m is None or "bound" not in columns:
            continue
        idx = columns.index("bound")
        cell = cells[idx] if idx < len(cells) else ""
        bounds[m.group(1)] = parse_bound_cell(cell) or {}
    return bounds


class _ValueTracer:
    """Classifies a label-value expression as 'bounded' (a closed literal
    set), 'derived' (per-request/object identity), or 'unknown'."""

    def __init__(self, fn_node: ast.AST) -> None:
        # name -> every expression assigned to it in this function; a name
        # is derived if ANY of its bindings is.
        self.bindings: dict[str, list[ast.expr]] = {}
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and node.value is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.bindings.setdefault(tgt.id, []).append(node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name):
                self.bindings.setdefault(node.target.id, []).append(node.value)

    def classify(self, expr: ast.expr, depth: int = 0) -> str:
        if depth > 4:  # binding chains deeper than this are not provable
            return "unknown"
        if isinstance(expr, ast.Constant):
            return "bounded"
        if isinstance(expr, ast.IfExp):
            a = self.classify(expr.body, depth + 1)
            b = self.classify(expr.orelse, depth + 1)
            if "derived" in (a, b):
                return "derived"
            return "bounded" if a == b == "bounded" else "unknown"
        if isinstance(expr, ast.JoinedStr):
            # An f-string embedding anything non-literal mints a new label
            # value per distinct datum — the classic cardinality leak.
            for v in expr.values:
                if isinstance(v, ast.FormattedValue) \
                        and not isinstance(v.value, ast.Constant):
                    return "derived"
            return "bounded"
        if isinstance(expr, ast.Call):
            fn = expr.func
            if isinstance(fn, ast.Name) and fn.id in ("str", "repr", "format") \
                    and expr.args and not isinstance(expr.args[0], ast.Constant):
                return "derived"
            if isinstance(fn, ast.Attribute) and fn.attr == "format":
                return "derived"
            return "unknown"
        if isinstance(expr, ast.Attribute):
            if expr.attr in IDENTITY_ATTRS:
                return "derived"
            if expr.attr in META_IDENTITY_ATTRS \
                    and isinstance(expr.value, ast.Attribute) \
                    and expr.value.attr == "meta":
                return "derived"
            return "unknown"
        if isinstance(expr, ast.Name):
            if expr.id in IDENTITY_ATTRS:
                return "derived"
            values = self.bindings.get(expr.id)
            if not values:
                return "unknown"
            classes = {self.classify(v, depth + 1) for v in values}
            if "derived" in classes:
                return "derived"
            return "bounded" if classes == {"bounded"} else "unknown"
        if isinstance(expr, ast.BinOp):  # "a" + x, "%"-format
            a = self.classify(expr.left, depth + 1)
            b = self.classify(expr.right, depth + 1)
            if "derived" in (a, b):
                return "derived"
            return "bounded" if a == b == "bounded" else "unknown"
        return "unknown"


def _labels_arg(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    method = call.func.attr  # caller guarantees Attribute
    idx = LABELS_ARG_INDEX[method]
    if len(call.args) > idx:
        return call.args[idx]
    return None


def metric_sites(mod: Module):
    """Yield (call, metric name, enclosing function node) for every
    literal-named inc/set/observe in one module."""
    if mod.tree is None:
        return
    # Enclosing function for each call, so the tracer sees its bindings.
    def walk(node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            inner = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr in METRIC_METHODS \
                    and _is_metrics_receiver(child.func.value) \
                    and child.args \
                    and isinstance(child.args[0], ast.Constant) \
                    and isinstance(child.args[0].value, str):
                yield_sites.append((child, child.args[0].value, inner))
            walk(child, inner)

    yield_sites: list = []
    walk(mod.tree, mod.tree)
    return yield_sites


def load_bounds(path: Path = CATALOGUE_PATH) -> dict[str, dict[str, str]]:
    if not path.exists():
        return {}
    return catalogue_bounds(path.read_text())


def run(modules: list[Module]) -> list[Finding]:
    bounds = load_bounds()
    findings: list[Finding] = []
    for mod in modules:
        if not mod.rel.startswith("lws_tpu/"):
            continue  # the contract governs the shipped control plane
        for call, metric, fn_node in metric_sites(mod) or []:
            labels = _labels_arg(call)
            if not isinstance(labels, ast.Dict):
                continue  # opaque labels object: unknown, stay silent
            tracer = _ValueTracer(fn_node)
            declared = bounds.get(metric, {})
            for key_node, value_node in zip(labels.keys, labels.values):
                if not (isinstance(key_node, ast.Constant)
                        and isinstance(key_node.value, str)):
                    continue
                label = key_node.value
                if tracer.classify(value_node) != "derived":
                    continue
                klass = declared.get(label)
                if klass == "capped":
                    continue  # sanctioned: rides max_label_sets by design
                where = (
                    f"declared `{klass}` in the catalogue" if klass
                    else "not declared in the catalogue's Bound column"
                )
                findings.append(mod.finding(
                    "cardinality-unbounded", call.lineno,
                    f"{metric}:{label}",
                    f"label {label!r} of metric {metric!r} takes a "
                    f"per-request/object-derived value but is {where} — "
                    "bound the value to a closed set, or declare the label "
                    "`capped` in docs/observability.md with a retirement "
                    "story",
                ))
    return findings
