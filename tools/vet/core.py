"""Shared infrastructure for the `lws-tpu vet` analyzer passes.

The vet suite is the Python analog of the reference control plane's
`go vet` + golangci-lint + `-race` toolchain: project-aware AST passes
over a concurrent codebase, wired into `make check`. This module owns
everything the passes share:

  * file discovery (same target set as the old tools/lint.py, minus
    tests/vet_fixtures/ — those files are deliberate rule violations);
  * the `Finding` model and its stable baseline key (path + enclosing
    scope + rule + detail, NO line number — line drift must not churn
    tools/vet/baseline.json);
  * source-comment annotations: `# guarded-by: <lock>` on attribute
    initializers, `# hot-path` on def lines, `# holds-lock: <lock>` on
    methods whose CALLER owns the lock;
  * inline suppressions: `# vet: ignore[rule-id]: reason` on the finding
    line. A rule id is mandatory; a bare ignore marker is itself a
    finding (vet-malformed-suppression) so suppressions stay auditable;
  * the committed baseline (tools/vet/baseline.json): pre-existing
    findings burn down without blocking CI, and — mirroring
    tools/check_metrics_catalogue.py's orphaned-row rule — a baseline
    entry no current finding matches is an ERROR, so the file can only
    shrink.

Run: `make vet`, `python -m tools.vet`, or `python -m tools.vet --only
style,locks`. Rules are catalogued in docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Optional

ROOT = Path(__file__).resolve().parent.parent.parent
TARGETS = ["lws_tpu", "tests", "benchmarks", "tools", "bench.py", "__graft_entry__.py"]
# Directories whose files are never vetted: fixture snippets are
# deliberate violations the analyzer self-tests assert on.
EXCLUDED_DIRS = {"vet_fixtures", "__pycache__"}

BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

# A suppression needs BOTH the bracketed rule id(s) AND a `: reason` —
# ISSUE acceptance: zero inline suppressions without a rule-id and comment.
SUPPRESS_RE = re.compile(r"#\s*vet:\s*ignore\[([A-Za-z0-9_,\- ]+)\]\s*:\s*\S")
MALFORMED_SUPPRESS_RE = re.compile(r"#\s*vet:\s*ignore\b")
# The annotation markers may share a comment (`# hot-path — holds-lock:
# _lock`), so they match anywhere after the `#`, not only right behind it.
GUARDED_BY_RE = re.compile(r"#.*?\bguarded-by:\s*([A-Za-z_]\w*)")
HOT_PATH_RE = re.compile(r"#.*?\bhot-path\b")
RECONCILE_PATH_RE = re.compile(r"#.*?\breconcile-path\b")
HOLDS_LOCK_RE = re.compile(r"#.*?\bholds-lock:\s*([A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    qual: str  # enclosing function/class qualname, or "<module>"
    detail: str  # stable short detail (attr name, call name, ...)
    message: str

    def key(self) -> str:
        """Baseline identity: everything except the line number, which
        drifts with unrelated edits above the finding."""
        return f"{self.path}::{self.qual}::{self.rule}::{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Module:
    """One parsed source file plus the comment-annotation side tables."""

    def __init__(self, path: Path, root: Path = ROOT) -> None:
        self.path = path
        try:
            self.rel = path.relative_to(root).as_posix()
        except ValueError:
            self.rel = path.as_posix()
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.source, filename=str(path))
        except SyntaxError as e:
            self.syntax_error = e
        # (start, end, qualname) spans for enclosing-scope lookup, innermost
        # match wins. Populated lazily — style-only runs never need it.
        self._scopes: Optional[list[tuple[int, int, str]]] = None

    # ---- lines + annotations ---------------------------------------------
    def line(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def has_hot_path_mark(self, node: ast.AST) -> bool:
        """`# hot-path` on the def line or the line directly above it."""
        lineno = getattr(node, "lineno", 0)
        return bool(
            HOT_PATH_RE.search(self.line(lineno))
            or HOT_PATH_RE.search(self.line(lineno - 1))
        )

    def has_reconcile_mark(self, node: ast.AST) -> bool:
        """`# reconcile-path` on the def line or the line directly above —
        an explicit purity-pass root where register()-discovery can't see
        the loop (the manager's own dispatch bodies)."""
        lineno = getattr(node, "lineno", 0)
        return bool(
            RECONCILE_PATH_RE.search(self.line(lineno))
            or RECONCILE_PATH_RE.search(self.line(lineno - 1))
        )

    def holds_locks(self, node: ast.AST) -> set[str]:
        """Locks a `# holds-lock: a, b` annotation declares held on entry."""
        lineno = getattr(node, "lineno", 0)
        for text in (self.line(lineno), self.line(lineno - 1)):
            m = HOLDS_LOCK_RE.search(text)
            if m:
                return {part.strip() for part in m.group(1).split(",")}
        return set()

    def guarded_by(self, lineno: int) -> Optional[str]:
        m = GUARDED_BY_RE.search(self.line(lineno))
        return m.group(1) if m else None

    def suppressed(self, finding: Finding) -> bool:
        m = SUPPRESS_RE.search(self.line(finding.line))
        if not m:
            return False
        rules = {part.strip() for part in m.group(1).split(",")}
        return finding.rule in rules

    # ---- scopes -----------------------------------------------------------
    def qualname_at(self, lineno: int) -> str:
        if self._scopes is None:
            self._scopes = []
            if self.tree is not None:
                self._collect_scopes(self.tree, "")
        best = "<module>"
        best_span = None
        for start, end, qual in self._scopes:
            if start <= lineno <= end:
                if best_span is None or (end - start) < best_span:
                    best, best_span = qual, end - start
        return best

    def _collect_scopes(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                end = getattr(child, "end_lineno", child.lineno)
                assert self._scopes is not None
                self._scopes.append((child.lineno, end, qual))
                self._collect_scopes(child, qual)
            else:
                self._collect_scopes(child, prefix)

    def finding(self, rule: str, lineno: int, detail: str, message: str) -> Finding:
        return Finding(rule, self.rel, lineno, self.qualname_at(lineno), detail, message)


def dotted_name(node: ast.expr) -> Optional[str]:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_source_files(root: Path = ROOT, targets: Optional[list[str]] = None) -> list[Path]:
    files: list[Path] = []
    for target in targets or TARGETS:
        p = root / target
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if EXCLUDED_DIRS.isdisjoint(part for part in f.parts):
                    files.append(f)
        elif p.exists():
            files.append(p)
    return files


def load_modules(paths: Iterable[Path], root: Path = ROOT) -> list[Module]:
    return [Module(p, root) for p in paths]


def malformed_suppressions(mod: Module) -> list[Finding]:
    """A vet-ignore marker without a [rule-id] or without a `: reason` —
    unauditable, so itself a finding (and it suppresses NOTHING). Applies
    everywhere, including on otherwise-clean lines."""
    out = []
    for i, text in enumerate(mod.lines, 1):
        if MALFORMED_SUPPRESS_RE.search(text) and not SUPPRESS_RE.search(text):
            out.append(mod.finding(
                "vet-malformed-suppression", i, "marker",
                "suppression without a [rule-id] and `: reason` — write "
                "`# vet: ignore[rule-id]: reason`",
            ))
    return out


# ---------------------------------------------------------------------------
# Baseline: committed findings burn down without blocking CI; orphans error.


def load_baseline(path: Path = BASELINE_PATH) -> dict[str, int]:
    """key -> allowed occurrence count. Counts keep the key line-stable
    while still bounding it: a baselined key must not silently absorb NEW
    findings of the same shape."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    entries = data.get("entries", {})
    if isinstance(entries, list):  # legacy shape: each entry allows one
        counted: dict[str, int] = {}
        for key in entries:
            counted[key] = counted.get(key, 0) + 1
        return counted
    return {key: int(n) for key, n in entries.items()}


def write_baseline(keys: Iterable[str], path: Path = BASELINE_PATH) -> None:
    """`keys` is one entry PER FINDING — repetition sets the allowed count."""
    counts: dict[str, int] = {}
    for key in keys:
        counts[key] = counts.get(key, 0) + 1
    payload = {
        "_comment": (
            "Pre-existing vet findings allowed to persist while they burn "
            "down, as key -> occurrence count. NO new entries and no count "
            "may grow: fix the finding or suppress inline with a rule-id "
            "and reason. An entry whose count exceeds the current findings "
            "is an error (orphan rule, like check_metrics_catalogue.py) — "
            "regenerate with `python -m tools.vet --write-baseline` only "
            "when removing fixed entries."
        ),
        "entries": dict(sorted(counts.items())),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")


def apply_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> tuple[list[Finding], list[Finding], list[str]]:
    """-> (new findings, baseline-allowed findings, orphaned entries).

    Per key, the first `count` findings (by file order) are allowed; any
    beyond that are NEW — a 6th host-sync added to a function whose 5 are
    baselined fails the run. A key with FEWER current findings than its
    count is stale and reported as an orphan (the file may only shrink)."""
    remaining = dict(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    orphans = sorted(key for key, n in remaining.items() if n > 0)
    return new, old, orphans
