"""Hazard pass: failure-handling hygiene in lws_tpu/ source.

Two rules, both scoped to `lws_tpu/` (tests and tools legitimately
swallow and block):

`hazard-exception-swallow` — an `except Exception:` (or BaseException,
or a tuple containing either) whose entire body is `pass`: the failure
vanishes — no log line, no metric, no ring event — which is exactly how
a partially-broken fleet degrades silently instead of visibly. Narrow
handlers (`except queue.Empty: pass`) are fine; broad ones must handle,
count, or at least log. Keep-alive loops that genuinely must outlive
anything carry `# vet: ignore[hazard-exception-swallow]: reason`.

`hazard-no-timeout` — a `socket.create_connection(...)` or
`urllib.request.urlopen(...)` call without an explicit timeout: the
OS-default is effectively infinite, so one dead peer hangs the caller
forever — the hang class the resilience layer (deadlines, breakers)
exists to eliminate cannot be allowed back in at the socket layer.
Positional timeouts count (`create_connection(addr, 5.0)`,
`urlopen(url, data, 5.0)`).
"""

from __future__ import annotations

import ast

from tools.vet.core import Finding, Module, dotted_name

PASS_NAME = "hazards"

BROAD_EXCEPTIONS = {"Exception", "BaseException"}

# dotted call name -> index of the positional timeout argument.
_TIMEOUT_CALLS = {
    "socket.create_connection": 1,
    "urllib.request.urlopen": 2,
    "request.urlopen": 2,
    "urlopen": 2,
}


def _names_in(node: ast.expr) -> set[str]:
    """Exception-class names a handler type mentions (Name, dotted tail,
    or any member of a tuple)."""
    if isinstance(node, ast.Tuple):
        out: set[str] = set()
        for elt in node.elts:
            out |= _names_in(elt)
        return out
    if isinstance(node, ast.Name):
        return {node.id}
    if isinstance(node, ast.Attribute):
        return {node.attr}
    return set()


def _body_is_pass(body: list[ast.stmt]) -> bool:
    return bool(body) and all(isinstance(stmt, ast.Pass) for stmt in body)


def run(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.rel.startswith("lws_tpu/") or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler):
                # Bare `except:` is the style pass's problem; here only the
                # explicitly-broad swallow shape.
                if node.type is not None \
                        and _names_in(node.type) & BROAD_EXCEPTIONS \
                        and _body_is_pass(node.body):
                    broad = sorted(_names_in(node.type) & BROAD_EXCEPTIONS)[0]
                    findings.append(mod.finding(
                        "hazard-exception-swallow", node.lineno,
                        f"except-{broad}-pass",
                        f"`except {broad}: pass` swallows every failure "
                        "silently — handle, count, or log it (or suppress "
                        "with a reason if the loop truly must outlive "
                        "anything)",
                    ))
            elif isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if dotted not in _TIMEOUT_CALLS:
                    continue
                timeout_idx = _TIMEOUT_CALLS[dotted]
                has_timeout = (
                    any(kw.arg == "timeout" for kw in node.keywords)
                    or len(node.args) > timeout_idx
                )
                if not has_timeout:
                    findings.append(mod.finding(
                        "hazard-no-timeout", node.lineno, dotted,
                        f"{dotted}() without an explicit timeout hangs "
                        "forever on a dead peer — pass timeout=",
                    ))
    return findings
