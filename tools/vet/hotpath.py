"""Hot-path hygiene pass: no blocking or host-syncing calls on the
decode dispatch path.

Roots are functions whose def line carries `# hot-path` (the dispatch
bodies of `DecodePipeline` and `PagedBatchEngine.step_n` are annotated
in source). Reachability closes over the roots through a conservative
intra-project call graph:

  * `self.m(...)`        -> a method of the same class, when it exists;
  * `f(...)`             -> a top-level function of the same module;
  * `alias.f(...)`       -> a top-level function of another lws_tpu
    module imported as `from lws_tpu.x import alias` / `import
    lws_tpu.x.alias`;
  * nested defs of a hot function are hot (pipeline commit callbacks
    run inside the consume path).

Anything the resolver can't see (callables passed as values, methods on
other objects) is out of scope by design — the pass must never guess a
call target into a false positive.

Rules:

  * `hotpath-blocking-call` — `time.sleep`, socket construction or
    `socket.create_connection`, `urllib.request.urlopen`,
    `subprocess.*`, builtin `open()`: host latency injected straight
    into the device dispatch window.
  * `hotpath-host-sync`     — `np.asarray(...)`, `jax.device_get`,
    `jax.block_until_ready` or any `.block_until_ready()` method call:
    a forced device->host fence that serializes the pipeline (exactly
    what PR 3 removed from `step_n`). Intentional fences — the
    pipeline's consume is one — carry an inline
    `# vet: ignore[hotpath-host-sync]: reason`.
  * `hotpath-serialize-copy` — `np.savez*` / `io.BytesIO` ANYWHERE in
    `lws_tpu/serving/` (lexical, no reachability needed): the npz round
    trip copies every payload at least twice on the KV wire path, which
    ISSUE 10 replaced with zero-copy raw-buffer framing
    (`kv_transport.pack_payload`). A serving-side serialization that
    genuinely needs a buffered copy carries a
    `# vet: ignore[hotpath-serialize-copy]: reason`.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.vet.core import Finding, Module, dotted_name

PASS_NAME = "hotpath"

BLOCKING_DOTTED = {
    "time.sleep", "sleep",
    "socket.socket", "socket.create_connection",
    "urllib.request.urlopen", "urlopen",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
}
# np.asarray is this repo's documented completion fence (engine.host_sync);
# np.array is NOT banned — building a host array from host lists is host
# work, not a device sync (e.g. the paged engine's dirty-tracked inputs).
HOST_SYNC_DOTTED = {
    "np.asarray", "numpy.asarray",
    "jax.device_get", "jax.block_until_ready",
}
HOST_SYNC_METHODS = {"block_until_ready"}
# Buffered-serialization shapes banned across lws_tpu/serving/ (lexically —
# a copy-heavy serializer is a hazard anywhere near the KV wire, reachable
# from a hot root or not): the npz/BytesIO round trip ISSUE 10 deleted.
SERIALIZE_COPY_DOTTED = {
    "np.savez", "np.savez_compressed", "numpy.savez",
    "numpy.savez_compressed", "io.BytesIO", "BytesIO",
}
SERVING_PREFIX = "lws_tpu/serving/"


class _FuncInfo:
    def __init__(self, mod: Module, qual: str, cls: Optional[str],
                 node: ast.FunctionDef) -> None:
        self.mod = mod
        self.qual = qual  # e.g. "DecodePipeline.push" or "beat"
        self.cls = cls    # enclosing class qualname, if any
        self.node = node
        self.hot_mark = mod.has_hot_path_mark(node)

    @property
    def key(self) -> tuple[str, str]:
        return (self.mod.rel, self.qual)


def _collect_functions(mod: Module) -> list[_FuncInfo]:
    out: list[_FuncInfo] = []

    def walk(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                out.append(_FuncInfo(mod, qual, cls, child))
                walk(child, qual, cls)
            elif isinstance(child, ast.ClassDef):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                walk(child, qual, qual)
            else:
                walk(child, prefix, cls)

    if mod.tree is not None:
        walk(mod.tree, "", None)
    return out


def _module_imports(mod: Module) -> dict[str, str]:
    """alias -> repo-relative module path, for lws_tpu imports only."""
    aliases: dict[str, str] = {}
    if mod.tree is None:
        return aliases
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith("lws_tpu"):
            base = node.module.replace(".", "/")
            for a in node.names:
                aliases[a.asname or a.name] = f"{base}/{a.name}.py"
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("lws_tpu."):
                    aliases[a.asname or a.name.split(".")[-1]] = \
                        a.name.replace(".", "/") + ".py"
    return aliases


def _direct_calls(info: _FuncInfo, funcs_by_key: dict, aliases: dict[str, str]) -> list[tuple[str, str]]:
    """Resolvable callee keys of one function (excluding nested defs —
    those are separate graph nodes marked hot by containment)."""
    out: list[tuple[str, str]] = []
    mod_rel = info.mod.rel

    def scan(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs resolve via containment edges; lambdas stay inline
            if isinstance(child, ast.Call):
                fn = child.func
                if isinstance(fn, ast.Name):
                    key = (mod_rel, fn.id)
                    if key in funcs_by_key:
                        out.append(key)
                elif isinstance(fn, ast.Attribute):
                    if isinstance(fn.value, ast.Name):
                        if fn.value.id == "self" and info.cls:
                            key = (mod_rel, f"{info.cls}.{fn.attr}")
                            if key in funcs_by_key:
                                out.append(key)
                        elif fn.value.id in aliases:
                            key = (aliases[fn.value.id], fn.attr)
                            if key in funcs_by_key:
                                out.append(key)
            scan(child)

    scan(info.node)
    return out


def _banned(call: ast.Call) -> Optional[tuple[str, str, str]]:
    """-> (rule, detail, description) when the call is banned on a hot path."""
    fn = call.func
    dotted = dotted_name(fn)
    if isinstance(fn, ast.Name) and fn.id == "open":
        return ("hotpath-blocking-call", "open", "file I/O via open()")
    if dotted in BLOCKING_DOTTED:
        return ("hotpath-blocking-call", dotted, f"blocking call {dotted}()")
    if dotted in HOST_SYNC_DOTTED:
        return ("hotpath-host-sync", dotted, f"host sync {dotted}()")
    if isinstance(fn, ast.Attribute) and fn.attr in HOST_SYNC_METHODS:
        return ("hotpath-host-sync", fn.attr, f".{fn.attr}() device fence")
    return None


def run(modules: list[Module]) -> list[Finding]:
    funcs: list[_FuncInfo] = []
    for mod in modules:
        funcs.extend(_collect_functions(mod))
    funcs_by_key = {f.key: f for f in funcs}
    aliases_by_mod = {mod.rel: _module_imports(mod) for mod in modules}

    # Containment: nested defs of a hot function are hot (qualname prefix
    # == containment here). Applied to every function entering the hot set
    # — BFS-reached callees included, not just annotated roots — so a
    # blocking call hidden in a helper's closure is still found.
    by_mod: dict[str, list[_FuncInfo]] = {}
    for f in funcs:
        by_mod.setdefault(f.mod.rel, []).append(f)
    children: dict[tuple[str, str], list[tuple[str, str]]] = {}
    for peers in by_mod.values():
        for f in peers:
            prefix = f.qual + "."
            kids = [g.key for g in peers if g.qual.startswith(prefix)]
            if kids:
                children[f.key] = kids

    # BFS over the conservative call graph + containment edges.
    hot: set[tuple[str, str]] = {f.key for f in funcs if f.hot_mark}
    frontier = list(hot)
    while frontier:
        key = frontier.pop()
        info = funcs_by_key.get(key)
        if info is None:
            continue
        edges = list(children.get(key, ()))
        edges += _direct_calls(info, funcs_by_key, aliases_by_mod[info.mod.rel])
        for callee in edges:
            if callee not in hot:
                hot.add(callee)
                frontier.append(callee)

    findings: list[Finding] = []
    for key in sorted(hot):
        info = funcs_by_key.get(key)
        if info is None:
            continue

        def scan(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # separate hot node (containment edge); scanned on its own
                # Lambdas are NOT separate nodes — a commit callback like
                # `lambda h: np.asarray(h)` is scanned as part of its
                # containing hot function.
                if isinstance(child, ast.Call):
                    hit = _banned(child)
                    if hit is not None:
                        rule, detail, desc = hit
                        findings.append(info.mod.finding(
                            rule, child.lineno, f"{info.qual}:{detail}",
                            f"{desc} on the hot path (in {info.qual})",
                        ))
                scan(child)

        for stmt in info.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan(stmt)

    # Serving-wide serialization-copy sweep: lexical, independent of the
    # hot-root reachability above — `np.savez`/`BytesIO` in
    # lws_tpu/serving/ is a finding wherever it hides.
    for mod in modules:
        if not mod.rel.startswith(SERVING_PREFIX) or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted in SERIALIZE_COPY_DOTTED:
                findings.append(mod.finding(
                    "hotpath-serialize-copy", node.lineno,
                    f"{mod.qualname_at(node.lineno)}:{dotted}",
                    f"buffered serialization {dotted}() in lws_tpu/serving/ "
                    "— use kv_transport's zero-copy raw framing "
                    "(pack_payload/bytes_to_arrays)",
                ))
    return findings
