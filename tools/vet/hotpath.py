"""Hot-path hygiene pass: no blocking or host-syncing calls on the
decode dispatch path.

Roots are functions whose def line carries `# hot-path` (the dispatch
bodies of `DecodePipeline` and `PagedBatchEngine.step_n` are annotated
in source). Reachability closes over the roots through the shared
conservative call graph (tools/vet/callgraph.py): self-methods, module
functions, cross-module aliases, typed receivers, plus containment —
nested defs of a hot function are hot (pipeline commit callbacks run
inside the consume path).

Anything the resolver can't prove (callables passed as values, methods
on untyped objects) is out of scope by design — the pass must never
guess a call target into a false positive.

Rules:

  * `hotpath-blocking-call` — `time.sleep`, socket construction or
    `socket.create_connection`, `urllib.request.urlopen`,
    `subprocess.*`, builtin `open()`: host latency injected straight
    into the device dispatch window.
  * `hotpath-host-sync`     — `np.asarray(...)`, `jax.device_get`,
    `jax.block_until_ready` or any `.block_until_ready()` method call:
    a forced device->host fence that serializes the pipeline (exactly
    what PR 3 removed from `step_n`). Intentional fences — the
    pipeline's consume is one — carry an inline
    `# vet: ignore[hotpath-host-sync]: reason`.
  * `hotpath-serialize-copy` — `np.savez*` / `io.BytesIO` ANYWHERE in
    `lws_tpu/serving/` (lexical, no reachability needed): the npz round
    trip copies every payload at least twice on the KV wire path, which
    ISSUE 10 replaced with zero-copy raw-buffer framing
    (`kv_transport.pack_payload`). A serving-side serialization that
    genuinely needs a buffered copy carries a
    `# vet: ignore[hotpath-serialize-copy]: reason`.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.vet import callgraph
from tools.vet.core import Finding, Module, dotted_name

PASS_NAME = "hotpath"

BLOCKING_DOTTED = {
    "time.sleep", "sleep",
    "socket.socket", "socket.create_connection",
    "urllib.request.urlopen", "urlopen",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
}
# np.asarray is this repo's documented completion fence (engine.host_sync);
# np.array is NOT banned — building a host array from host lists is host
# work, not a device sync (e.g. the paged engine's dirty-tracked inputs).
HOST_SYNC_DOTTED = {
    "np.asarray", "numpy.asarray",
    "jax.device_get", "jax.block_until_ready",
}
HOST_SYNC_METHODS = {"block_until_ready"}
# Buffered-serialization shapes banned across lws_tpu/serving/ (lexically —
# a copy-heavy serializer is a hazard anywhere near the KV wire, reachable
# from a hot root or not): the npz/BytesIO round trip ISSUE 10 deleted.
SERIALIZE_COPY_DOTTED = {
    "np.savez", "np.savez_compressed", "numpy.savez",
    "numpy.savez_compressed", "io.BytesIO", "BytesIO",
}
SERVING_PREFIX = "lws_tpu/serving/"


def banned(call: ast.Call) -> Optional[tuple[str, str, str]]:
    """-> (rule, detail, description) when the call is banned on a hot
    path. Shared with locks.py's interprocedural lock-held-blocking rule:
    the SAME deny-list applies under a held lock."""
    fn = call.func
    dotted = dotted_name(fn)
    if isinstance(fn, ast.Name) and fn.id == "open":
        return ("hotpath-blocking-call", "open", "file I/O via open()")
    if dotted in BLOCKING_DOTTED:
        return ("hotpath-blocking-call", dotted, f"blocking call {dotted}()")
    if dotted in HOST_SYNC_DOTTED:
        return ("hotpath-host-sync", dotted, f"host sync {dotted}()")
    if isinstance(fn, ast.Attribute) and fn.attr in HOST_SYNC_METHODS:
        return ("hotpath-host-sync", fn.attr, f".{fn.attr}() device fence")
    return None


def scan_banned(info: callgraph.FuncInfo) -> list[tuple[ast.Call, tuple[str, str, str]]]:
    """Banned calls lexically inside one function body, nested defs
    excluded (each is its own graph node), lambdas scanned inline."""
    hits: list[tuple[ast.Call, tuple[str, str, str]]] = []

    def scan(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate node (containment edge); scanned on its own
            # Lambdas are NOT separate nodes — a commit callback like
            # `lambda h: np.asarray(h)` is scanned as part of its
            # containing function.
            if isinstance(child, ast.Call):
                hit = banned(child)
                if hit is not None:
                    hits.append((child, hit))
            scan(child)

    for stmt in info.node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scan(stmt)
    return hits


def run(modules: list[Module]) -> list[Finding]:
    graph = callgraph.build(modules)
    roots = [
        key for key, info in graph.funcs.items()
        if info.mod.has_hot_path_mark(info.node)
    ]
    hot = graph.reachable(roots)

    findings: list[Finding] = []
    for key in sorted(hot):
        info = graph.funcs.get(key)
        if info is None:
            continue
        for call, (rule, detail, desc) in scan_banned(info):
            findings.append(info.mod.finding(
                rule, call.lineno, f"{info.qual}:{detail}",
                f"{desc} on the hot path (in {info.qual})",
            ))

    # Serving-wide serialization-copy sweep: lexical, independent of the
    # hot-root reachability above — `np.savez`/`BytesIO` in
    # lws_tpu/serving/ is a finding wherever it hides.
    for mod in modules:
        if not mod.rel.startswith(SERVING_PREFIX) or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted in SERIALIZE_COPY_DOTTED:
                findings.append(mod.finding(
                    "hotpath-serialize-copy", node.lineno,
                    f"{mod.qualname_at(node.lineno)}:{dotted}",
                    f"buffered serialization {dotted}() in lws_tpu/serving/ "
                    "— use kv_transport's zero-copy raw framing "
                    "(pack_payload/bytes_to_arrays)",
                ))
    return findings
