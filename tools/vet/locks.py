"""Lock-discipline pass: guarded attributes, lock acquisition order, and
interprocedural blocking-under-lock — on the shared call graph
(tools/vet/callgraph.py).

`lock-guarded-attr` — an instance attribute whose initializer carries a
`# guarded-by: _lock` annotation may only be touched (read OR written)
through `self.<attr>` while `self.<lock>` is held. Held means:

  * lexically inside `with self.<lock>:` (multiple items and nesting
    compose; re-entrant RLocks are naturally fine — the lock stays in
    the held set);
  * after a `self.<lock>.acquire()` statement in the same block, until
    the matching `.release()` — the try/finally-release idiom keeps the
    lock held through the try body and handlers;
  * for the whole method when its def line carries `# holds-lock:
    <lock>` (the caller owns the lock — the `_locked`-suffix method
    convention from core/store.py is honored the same way);
  * `__init__`/`__del__` are exempt (construction and teardown are
    single-threaded by contract).

The check FOLLOWS calls into `_locked` helpers: calling a same-class
`*_locked` method (or one annotated `# holds-lock:`) without holding the
locks its body needs — the guards of the guarded attrs it touches, plus
its declared holds-locks — is flagged at the CALL SITE.

Accesses inside nested function defs and lambdas are NOT checked: those
bodies run later, under whatever discipline their call site owns (the
engines' pipeline commit callbacks run under the pipeline's consume
lock, which this pass cannot see lexically).

`lock-held-blocking` — a blocking or host-syncing call (the hotpath
pass's deny-lists: `time.sleep`, sockets, `subprocess`, `open()`,
`np.asarray`, `.block_until_ready()`) executed while a DECLARED lock is
held — directly, or transitively through any resolvable call chain.
Declared means the class constructs the lock (`threading.Lock()` etc.),
a `# guarded-by:` annotation names it, or it is a module-level lock
global. Blocking under a store or registry lock convoys every other
thread behind host latency — the exact shape `go vet`-era reviews catch
by hand. A deny-listed call that is itself suppressed at source (e.g.
the fault injector's deliberate delay-mode sleep) is not propagated.

`lock-order` — every nested acquisition `A then B` is recorded as an
edge between GLOBAL lock identities (module, class, attr — two
same-named classes in different files never merge), including
acquisitions reached through resolvable calls while a lock is held.
Observing both `A->B` and `B->A` anywhere in the project — now across
classes and modules, not only within one class — flags both sites; a
longer cycle (A->B->C->A) is reported once per strongly-connected
component.

The annotations this pass consumes live in core/store.py,
core/metrics.py, core/flightrecorder.py, core/slo.py,
serving/pipeline.py, serving/kv_transport.py and runtime/fleet.py.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.vet import callgraph, hotpath
from tools.vet.core import SUPPRESS_RE, Finding, Module

PASS_NAME = "locks"

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
EXEMPT_METHODS = {"__init__", "__del__", "__post_init__"}

# A held lock inside a function body: ("self", attr) or ("mod", global name).
LockRef = tuple[str, str]


def _self_attr(node: ast.expr) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lock_call_target(node: ast.expr, op: str) -> Optional[ast.expr]:
    """`<target>.acquire()` / `.release()` (as an expression) -> target."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == op:
        return node.func.value
    return None


class _ClassInfo:
    def __init__(self, mod: Module, qual: str, node: ast.ClassDef) -> None:
        self.mod = mod
        self.qual = qual
        self.node = node
        self.locks: set[str] = set()
        self.guarded: dict[str, str] = {}  # attr -> lock name
        self._collect()

    def _collect(self) -> None:
        for fn in self.node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    value = stmt.value
                    if isinstance(value, ast.Call):
                        ctor = value.func
                        name = ctor.attr if isinstance(ctor, ast.Attribute) else (
                            ctor.id if isinstance(ctor, ast.Name) else None
                        )
                        if name in LOCK_CTORS:
                            self.locks.add(attr)
                    guard = self.mod.guarded_by(stmt.lineno)
                    if guard:
                        self.guarded[attr] = guard

    def declared(self, name: str) -> bool:
        return name in self.locks or name in set(self.guarded.values())


def _module_locks(mod: Module) -> set[str]:
    """Top-level `NAME = threading.Lock()` (etc.) globals."""
    out: set[str] = set()
    if mod.tree is None:
        return out
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            ctor = stmt.value.func
            name = ctor.attr if isinstance(ctor, ast.Attribute) else (
                ctor.id if isinstance(ctor, ast.Name) else None
            )
            if name in LOCK_CTORS:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


class _Analysis:
    """Whole-project lock analysis state: the call graph, per-class lock
    tables, and memoized per-function summaries (transitive blocking
    calls / transitively acquired locks)."""

    def __init__(self, modules: list[Module]) -> None:
        self.graph = callgraph.build(modules)
        self.classes: dict[tuple[str, str], _ClassInfo] = {}
        for key, cg_cls in self.graph.classes.items():
            self.classes[key] = _ClassInfo(cg_cls.mod, cg_cls.qual, cg_cls.node)
        self.module_locks: dict[str, set[str]] = {
            mod.rel: _module_locks(mod) for mod in modules
        }
        self._blocking_memo: dict[callgraph.Key, Optional[tuple[str, str, str, str]]] = {}
        self._acquired_memo: dict[callgraph.Key, frozenset[tuple[str, str]]] = {}
        self._required_memo: dict[callgraph.Key, set[str]] = {}
        self._required_inprogress: set[callgraph.Key] = set()

    # ---- lock identity ----------------------------------------------------
    def class_of(self, info: callgraph.FuncInfo) -> Optional[_ClassInfo]:
        if info.cls is None:
            return None
        return self.classes.get((info.mod.rel, info.cls))

    def lock_id(self, info: callgraph.FuncInfo, ref: LockRef) -> tuple[str, str]:
        """-> (global id, short label). Keyed by (module, class): a class
        lives in exactly one module, and two same-named classes in
        different files must not merge into one phantom ABBA pair."""
        if ref[0] == "self" and info.cls is not None:
            return (f"{info.mod.rel}::{info.cls}::{ref[1]}",
                    f"{info.cls}.{ref[1]}")
        return (f"{info.mod.rel}::<module>::{ref[1]}", ref[1])

    def is_lock(self, info: callgraph.FuncInfo, ref: LockRef) -> bool:
        if ref[0] == "self":
            cls = self.class_of(info)
            if cls is None:
                return False
            return cls.declared(ref[1]) or ref[1].endswith(("lock", "mutex", "cond"))
        return ref[1] in self.module_locks.get(info.mod.rel, set())

    def is_declared(self, info: callgraph.FuncInfo, ref: LockRef) -> bool:
        """Constructed or guarded-by-named locks only — the suffix
        heuristic tracks the held set but never anchors a blocking
        finding."""
        if ref[0] == "self":
            cls = self.class_of(info)
            return cls is not None and cls.declared(ref[1])
        return ref[1] in self.module_locks.get(info.mod.rel, set())

    def as_lockref(self, info: callgraph.FuncInfo, expr: ast.expr) -> Optional[LockRef]:
        attr = _self_attr(expr)
        if attr is not None:
            ref = ("self", attr)
            return ref if self.is_lock(info, ref) else None
        if isinstance(expr, ast.Name):
            ref = ("mod", expr.id)
            return ref if self.is_lock(info, ref) else None
        return None

    # ---- summaries --------------------------------------------------------
    def _source_sanctioned(self, mod: Module, lineno: int, rule: str) -> bool:
        """A deny-listed call whose own line suppresses its hotpath rule
        (or lock-held-blocking) is sanctioned at source — don't propagate
        it into callers' lock regions."""
        m = SUPPRESS_RE.search(mod.line(lineno))
        if not m:
            return False
        rules = {part.strip() for part in m.group(1).split(",")}
        return rule in rules or "lock-held-blocking" in rules

    def blocking_summary(
        self, key: callgraph.Key, _stack: Optional[set] = None,
    ) -> Optional[tuple[str, str, str, str]]:
        """-> (rule, detail, description, qual where it happens) for the
        first deny-listed call this function transitively executes, or
        None. Nested defs and lambdas are skipped — defining a closure
        under a lock does not run it."""
        if key in self._blocking_memo:
            return self._blocking_memo[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return None  # recursion cycle
        stack.add(key)
        info = self.graph.funcs.get(key)
        result: Optional[tuple[str, str, str, str]] = None
        if info is not None:
            hits: list[tuple[ast.Call, tuple[str, str, str]]] = []

            def scan(node: ast.AST) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue
                    if isinstance(child, ast.Call):
                        hit = hotpath.banned(child)
                        if hit is not None and not self._source_sanctioned(
                                info.mod, child.lineno, hit[0]):
                            hits.append((child, hit))
                    scan(child)

            for stmt in info.node.body:
                if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan(stmt)
            if hits:
                _, (rule, detail, desc) = hits[0]
                result = (rule, detail, desc, info.qual)
            else:
                for callee, _ in self.graph.callees(info):
                    sub = self.blocking_summary(callee, stack)
                    if sub is not None:
                        result = sub
                        break
        stack.discard(key)
        self._blocking_memo[key] = result
        return result

    def acquired_summary(
        self, key: callgraph.Key, _stack: Optional[set] = None,
    ) -> frozenset[tuple[str, str]]:
        """(lock id, label) pairs this function transitively acquires."""
        cached = self._acquired_memo.get(key)
        if cached is not None:
            return cached
        stack = _stack if _stack is not None else set()
        if key in stack:
            return frozenset()
        stack.add(key)
        acc: set[tuple[str, str]] = set()
        info = self.graph.funcs.get(key)
        if info is not None:
            def scan(node: ast.AST) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        for item in child.items:
                            ref = self.as_lockref(info, item.context_expr)
                            if ref is not None:
                                acc.add(self.lock_id(info, ref))
                    target = _lock_call_target(child, "acquire") \
                        if isinstance(child, ast.Call) else None
                    if target is not None:
                        ref = self.as_lockref(info, target)
                        if ref is not None:
                            acc.add(self.lock_id(info, ref))
                    scan(child)

            # Scan from the function NODE so a `with lock:` that IS a
            # top-level body statement still registers (scan only matches
            # With nodes seen as children).
            scan(info.node)
            for callee, _ in self.graph.callees(info):
                acc |= self.acquired_summary(callee, stack)
        stack.discard(key)
        frozen = frozenset(acc)
        self._acquired_memo[key] = frozen
        return frozen

    def required_locks(self, callee: callgraph.FuncInfo) -> set[str]:
        """Locks a helper's CALLER must hold: its `# holds-lock:`
        declaration, plus — for `*_locked`-suffix helpers — the guards of
        every guarded attr its body touches OUTSIDE its own lock regions
        (a `_locked` method that takes the lock itself, like the store's
        write path, imposes nothing on callers)."""
        key = callee.key
        cached = self._required_memo.get(key)
        if cached is not None:
            return cached
        if key in self._required_inprogress:
            return set()  # mutual-recursion cycle: no extra requirement
        self._required_inprogress.add(key)
        required = set(callee.mod.holds_locks(callee.node))
        cls = self.class_of(callee)
        if cls is not None and callee.name.endswith("_locked"):
            missing: set[str] = set()
            _FuncChecker(self, callee, [], {}, collect_missing=missing)
            required |= missing
        self._required_inprogress.discard(key)
        self._required_memo[key] = required
        return required


class _FuncChecker:
    """Walks one function's statements tracking the set of held locks."""

    def __init__(self, analysis: _Analysis, info: callgraph.FuncInfo,
                 findings: list[Finding], edges: dict,
                 collect_missing: Optional[set[str]] = None) -> None:
        self.analysis = analysis
        self.info = info
        self.cls = analysis.class_of(info)
        self.fn = info.node
        self.findings = findings
        self.edges = edges  # (outer id, inner id) -> (mod, line, out lbl, in lbl)
        # Collect mode (required_locks): record which locks the body NEEDS
        # from its caller instead of reporting findings — the walk starts
        # from the annotation only, without the `_locked` assumption.
        self.collect_missing = collect_missing
        held: set[LockRef] = {("self", name)
                              for name in info.mod.holds_locks(info.node)}
        if collect_missing is None and self.cls is not None \
                and info.name.endswith("_locked"):
            # store.py convention: the caller holds every guard lock the
            # body actually needs (required_locks computes that set; the
            # body's own check assumes the convention was honored).
            held |= {("self", lock)
                     for lock in analysis.required_locks(info)}
        self.walk_block(self.fn.body, held)

    # ---- statement walk ---------------------------------------------------
    def walk_block(self, stmts: list[ast.stmt], held: set[LockRef]) -> set[LockRef]:
        """Walk statements sequentially; returns the held set at block end
        (so a release inside a try's finally ends the region for the
        statements AFTER the try)."""
        cur = set(held)
        for stmt in stmts:
            cur = self.walk_stmt(stmt, cur)
        return cur

    def walk_stmt(self, stmt: ast.stmt, held: set[LockRef]) -> set[LockRef]:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                ref = self.analysis.as_lockref(self.info, item.context_expr)
                if ref is not None:
                    acquired.append(ref)
                else:
                    self.check_expr(item.context_expr, held)
                if item.optional_vars is not None:
                    self.check_expr(item.optional_vars, held)
            for ref in acquired:
                self._record_order(held, ref, stmt.lineno)
            self.walk_block(stmt.body, held | set(acquired))
            return held
        if isinstance(stmt, ast.Expr):
            acq_target = _lock_call_target(stmt.value, "acquire")
            if acq_target is not None:
                ref = self.analysis.as_lockref(self.info, acq_target)
                if ref is not None:
                    self._record_order(held, ref, stmt.lineno)
                    return held | {ref}
            rel_target = _lock_call_target(stmt.value, "release")
            if rel_target is not None:
                ref = self.analysis.as_lockref(self.info, rel_target)
                if ref is not None:
                    return held - {ref}
            self.check_expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.Try):
            # A lock acquired before the try is held through body and
            # handlers; a release in the finally ends the region — the
            # finalbody's resulting held set is what statements AFTER the
            # try run under.
            self.walk_block(stmt.body, held)
            for handler in stmt.handlers:
                self.walk_block(handler.body, held)
            self.walk_block(stmt.orelse, held)
            return self.walk_block(stmt.finalbody, held)
        if isinstance(stmt, (ast.If,)):
            self.check_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.While,)):
            self.check_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.check_expr(stmt.iter, held)
            self.check_expr(stmt.target, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return held  # nested scope: runs later, not checked here
        # Leaf statements: scan every expression they contain.
        for child in ast.iter_child_nodes(stmt):
            self.check_expr(child, held)
        return held

    # ---- expression scan --------------------------------------------------
    def check_expr(self, node: ast.AST, held: set[LockRef]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scope
        if self.cls is not None:
            attr = _self_attr(node) if isinstance(node, ast.Attribute) else None
            if attr is not None and attr in self.cls.guarded:
                lock = self.cls.guarded[attr]
                if ("self", lock) not in held:
                    if self.collect_missing is not None:
                        self.collect_missing.add(lock)
                    else:
                        self.findings.append(self.info.mod.finding(
                            "lock-guarded-attr", node.lineno,
                            f"{self.fn.name}.{attr}",
                            f"self.{attr} is `# guarded-by: {lock}` but "
                            f"accessed in {self.cls.qual}.{self.fn.name} "
                            f"without holding self.{lock}",
                        ))
        if isinstance(node, ast.Call):
            self.check_call(node, held)
        for child in ast.iter_child_nodes(node):
            self.check_expr(child, held)

    def check_call(self, call: ast.Call, held: set[LockRef]) -> None:
        analysis = self.analysis
        if self.collect_missing is not None:
            # Collect mode: only requirement propagation — a _locked helper
            # calling another helper needs whatever that helper needs.
            target = analysis.graph.resolve_call(self.info, call)
            if target is not None and target != self.info.key:
                callee = analysis.graph.funcs.get(target)
                if callee is not None and callee.cls == self.info.cls \
                        and callee.mod.rel == self.info.mod.rel:
                    held_names = {name for kind, name in held if kind == "self"}
                    self.collect_missing |= (
                        analysis.required_locks(callee) - held_names
                    )
            return
        declared_held = [ref for ref in held
                         if self.analysis.is_declared(self.info, ref)]
        # Direct deny-listed call under a declared lock.
        if declared_held:
            hit = hotpath.banned(call)
            if hit is not None:
                rule, detail, desc = hit
                _, label = analysis.lock_id(self.info, declared_held[0])
                self.findings.append(self.info.mod.finding(
                    "lock-held-blocking", call.lineno,
                    f"{self.fn.name}:{detail}",
                    f"{desc} while holding {label} (in "
                    f"{self.info.qual}) — blocking under a lock convoys "
                    "every waiter",
                ))
        target = analysis.graph.resolve_call(self.info, call)
        if target is None or target == self.info.key:
            return
        callee = analysis.graph.funcs.get(target)
        if callee is None:
            return
        # Blocking reached through the call chain.
        if declared_held:
            summary = analysis.blocking_summary(target)
            if summary is not None:
                _, _, desc, where = summary
                _, label = analysis.lock_id(self.info, declared_held[0])
                self.findings.append(self.info.mod.finding(
                    "lock-held-blocking", call.lineno,
                    f"{self.fn.name}->{callee.qual}",
                    f"call to {callee.qual} reaches {desc} (in {where}) "
                    f"while holding {label} — blocking under a lock convoys "
                    "every waiter",
                ))
        # Lock-order edges through the call chain.
        if held:
            inner = analysis.acquired_summary(target)
            if inner:
                outer_ids = [analysis.lock_id(self.info, ref) for ref in held]
                for outer_id, outer_label in outer_ids:
                    for inner_id, inner_label in inner:
                        if inner_id == outer_id:
                            continue  # re-entrant re-acquire
                        self.edges.setdefault(
                            (outer_id, inner_id),
                            (self.info.mod, call.lineno, outer_label, inner_label),
                        )
        # Guarded-attr discipline follows calls into _locked helpers:
        # the call site must hold what the helper's body needs.
        if self.cls is not None and callee.cls == self.info.cls \
                and callee.mod.rel == self.info.mod.rel \
                and callee.name not in EXEMPT_METHODS:
            required = analysis.required_locks(callee)
            missing = sorted(required - {name for kind, name in held
                                         if kind == "self"})
            if missing:
                self.findings.append(self.info.mod.finding(
                    "lock-guarded-attr", call.lineno,
                    f"{self.fn.name}->{callee.name}",
                    f"{self.cls.qual}.{callee.name} requires the caller to "
                    f"hold self.{', self.'.join(missing)} but "
                    f"{self.fn.name} calls it without",
                ))

    # ---- helpers ----------------------------------------------------------
    def _record_order(self, held: set[LockRef], acquired: LockRef,
                      lineno: int) -> None:
        acq_id, acq_label = self.analysis.lock_id(self.info, acquired)
        for outer in held:
            out_id, out_label = self.analysis.lock_id(self.info, outer)
            if out_id == acq_id:
                continue  # re-entrant RLock re-acquire: not an order edge
            self.edges.setdefault(
                (out_id, acq_id), (self.info.mod, lineno, out_label, acq_label)
            )


def _checked_functions(analysis: _Analysis) -> list[callgraph.FuncInfo]:
    """Methods of classes that declare locks or guarded attrs, plus
    module-level functions of modules with module-level lock globals —
    NOT every function in the repo (a test's local lock is its own
    business)."""
    out: list[callgraph.FuncInfo] = []
    for info in analysis.graph.funcs.values():
        if info.name in EXEMPT_METHODS:
            continue
        if info.cls is not None:
            cls = analysis.class_of(info)
            if cls is not None and (cls.locks or cls.guarded):
                # Direct class-body methods only — nested defs run later,
                # under their call site's discipline.
                if info.qual == f"{info.cls}.{info.name}":
                    out.append(info)
        elif analysis.module_locks.get(info.mod.rel) and "." not in info.qual:
            out.append(info)
    return out


def _cycle_findings(edges: dict) -> list[Finding]:
    """ABBA pairs first (both directions observed), then longer cycles
    via strongly-connected components of the remaining order graph."""
    findings: list[Finding] = []
    reported: set[tuple[str, str]] = set()
    for (a, b), (mod, lineno, a_label, b_label) in sorted(
        edges.items(), key=lambda kv: (kv[1][0].rel, kv[1][1])
    ):
        if (b, a) in edges and (b, a) not in reported:
            reported.add((a, b))
            other_mod, other_line, _, _ = edges[(b, a)]
            same_class = a.rsplit("::", 1)[0] == b.rsplit("::", 1)[0]
            if same_class:
                cls_qual = a.split("::")[1]
                detail = f"{cls_qual}:{a.rsplit('::', 1)[1]}<->{b.rsplit('::', 1)[1]}"
                scope = f"in {cls_qual}"
            else:
                detail = f"{a_label}<->{b_label}"
                scope = "across classes"
            findings.append(mod.finding(
                "lock-order", lineno, detail,
                f"inconsistent lock order {scope}: {a_label} -> {b_label} "
                f"here but {b_label} -> {a_label} at "
                f"{other_mod.rel}:{other_line} (ABBA deadlock)",
            ))
    # Longer cycles: Tarjan SCC over edges not already part of a 2-cycle.
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        if (b, a) in edges:
            continue
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    for comp in sccs:
        # Anchor the finding at the first edge inside the component.
        site = None
        labels = []
        for (a, b), (mod, lineno, a_label, b_label) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].rel, kv[1][1])
        ):
            if a in comp and b in comp:
                if site is None:
                    site = (mod, lineno)
                for lbl in (a_label, b_label):
                    if lbl not in labels:
                        labels.append(lbl)
        if site is not None:
            mod, lineno = site
            findings.append(mod.finding(
                "lock-order", lineno, "cycle:" + "->".join(sorted(labels)),
                f"lock acquisition cycle across {len(comp)} locks: "
                f"{' -> '.join(labels)} -> {labels[0]} (deadlock shape)",
            ))
    return findings


def run(modules: list[Module]) -> list[Finding]:
    analysis = _Analysis(modules)
    findings: list[Finding] = []
    edges: dict[tuple[str, str], tuple[Module, int, str, str]] = {}
    for info in _checked_functions(analysis):
        _FuncChecker(analysis, info, findings, edges)
    findings.extend(_cycle_findings(edges))
    return findings
