"""Lock-discipline pass: guarded attributes and lock acquisition order.

`lock-guarded-attr` — an instance attribute whose initializer carries a
`# guarded-by: _lock` annotation may only be touched (read OR written)
through `self.<attr>` while `self.<lock>` is held. Held means:

  * lexically inside `with self.<lock>:` (multiple items and nesting
    compose; re-entrant RLocks are naturally fine — the lock stays in
    the held set);
  * after a `self.<lock>.acquire()` statement in the same block, until
    the matching `.release()` — the try/finally-release idiom keeps the
    lock held through the try body and handlers;
  * for the whole method when its def line carries `# holds-lock:
    <lock>` (the caller owns the lock — the `_locked`-suffix method
    convention from core/store.py is honored the same way);
  * `__init__`/`__del__` are exempt (construction and teardown are
    single-threaded by contract).

Accesses inside nested function defs and lambdas are NOT checked: those
bodies run later, under whatever discipline their call site owns (the
engines' pipeline commit callbacks run under the pipeline's consume
lock, which this pass cannot see lexically).

`lock-order` — for each class, every nested acquisition `A then B` of
two of its own locks is recorded; observing both `A->B` and `B->A`
anywhere in the project is a potential deadlock and flags both sites.

The annotations this pass consumes live in core/store.py,
core/metrics.py, core/flightrecorder.py, core/slo.py,
serving/pipeline.py, serving/kv_transport.py and runtime/fleet.py.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.vet.core import Finding, Module

PASS_NAME = "locks"

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
EXEMPT_METHODS = {"__init__", "__del__", "__post_init__"}


def _self_attr(node: ast.expr) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lock_call_attr(node: ast.expr, op: str) -> Optional[str]:
    """`self.X.acquire()` / `.release()` (as an expression) -> "X"."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == op:
        return _self_attr(node.func.value)
    return None


class _ClassInfo:
    def __init__(self, mod: Module, qual: str, node: ast.ClassDef) -> None:
        self.mod = mod
        self.qual = qual
        self.node = node
        self.locks: set[str] = set()
        self.guarded: dict[str, str] = {}  # attr -> lock name
        self._collect()

    def _collect(self) -> None:
        for fn in self.node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    value = stmt.value
                    if isinstance(value, ast.Call):
                        ctor = value.func
                        name = ctor.attr if isinstance(ctor, ast.Attribute) else (
                            ctor.id if isinstance(ctor, ast.Name) else None
                        )
                        if name in LOCK_CTORS:
                            self.locks.add(attr)
                    guard = self.mod.guarded_by(stmt.lineno)
                    if guard:
                        self.guarded[attr] = guard


class _MethodChecker:
    """Walks one method's statements tracking the set of held self-locks."""

    def __init__(self, cls: _ClassInfo, fn: ast.FunctionDef,
                 findings: list[Finding], edges: dict) -> None:
        self.cls = cls
        self.fn = fn
        self.findings = findings
        self.edges = edges  # (class_qual, lockA, lockB) -> first site
        held = set(cls.mod.holds_locks(fn))
        if fn.name.endswith("_locked"):
            # store.py convention: the caller holds every guard lock.
            held |= set(cls.guarded.values())
        self.walk_block(fn.body, held)

    # ---- statement walk ---------------------------------------------------
    def walk_block(self, stmts: list[ast.stmt], held: set[str]) -> set[str]:
        """Walk statements sequentially; returns the held set at block end
        (so a release inside a try's finally ends the region for the
        statements AFTER the try)."""
        cur = set(held)
        for stmt in stmts:
            cur = self.walk_stmt(stmt, cur)
        return cur

    def walk_stmt(self, stmt: ast.stmt, held: set[str]) -> set[str]:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and self._is_lock(attr):
                    acquired.append(attr)
                else:
                    self.check_expr(item.context_expr, held)
                if item.optional_vars is not None:
                    self.check_expr(item.optional_vars, held)
            for lock in acquired:
                self._record_order(held, lock, stmt.lineno)
            self.walk_block(stmt.body, held | set(acquired))
            return held
        if isinstance(stmt, ast.Expr):
            acq = _lock_call_attr(stmt.value, "acquire")
            if acq is not None and self._is_lock(acq):
                self._record_order(held, acq, stmt.lineno)
                return held | {acq}
            rel = _lock_call_attr(stmt.value, "release")
            if rel is not None and self._is_lock(rel):
                return held - {rel}
            self.check_expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.Try):
            # A lock acquired before the try is held through body and
            # handlers; a release in the finally ends the region — the
            # finalbody's resulting held set is what statements AFTER the
            # try run under.
            self.walk_block(stmt.body, held)
            for handler in stmt.handlers:
                self.walk_block(handler.body, held)
            self.walk_block(stmt.orelse, held)
            return self.walk_block(stmt.finalbody, held)
        if isinstance(stmt, (ast.If,)):
            self.check_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.While,)):
            self.check_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.check_expr(stmt.iter, held)
            self.check_expr(stmt.target, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return held  # nested scope: runs later, not checked here
        # Leaf statements: scan every expression they contain.
        for child in ast.iter_child_nodes(stmt):
            self.check_expr(child, held)
        return held

    # ---- expression scan --------------------------------------------------
    def check_expr(self, node: ast.AST, held: set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scope
        attr = _self_attr(node) if isinstance(node, ast.Attribute) else None
        if attr is not None and attr in self.cls.guarded:
            lock = self.cls.guarded[attr]
            if lock not in held:
                self.findings.append(self.cls.mod.finding(
                    "lock-guarded-attr", node.lineno, f"{self.fn.name}.{attr}",
                    f"self.{attr} is `# guarded-by: {lock}` but accessed in "
                    f"{self.cls.qual}.{self.fn.name} without holding "
                    f"self.{lock}",
                ))
        for child in ast.iter_child_nodes(node):
            self.check_expr(child, held)

    # ---- helpers ----------------------------------------------------------
    def _is_lock(self, attr: str) -> bool:
        return attr in self.cls.locks or attr in set(self.cls.guarded.values()) \
            or attr.endswith(("lock", "mutex", "cond"))

    def _record_order(self, held: set[str], acquired: str, lineno: int) -> None:
        for outer in held:
            if outer == acquired:
                continue  # re-entrant RLock re-acquire: not an order edge
            # Keyed by (module, class): a class lives in exactly one module,
            # and two same-named classes in different files must not merge
            # into one phantom ABBA pair.
            key = (self.cls.mod.rel, self.cls.qual, outer, acquired)
            self.edges.setdefault(key, (self.cls.mod, lineno))


def _classes(mod: Module) -> list[_ClassInfo]:
    out: list[_ClassInfo] = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                out.append(_ClassInfo(mod, qual, child))
                walk(child, qual)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, f"{prefix}.{child.name}" if prefix else child.name)
            else:
                walk(child, prefix)

    if mod.tree is not None:
        walk(mod.tree, "")
    return out


def run(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    edges: dict[tuple[str, str, str, str], tuple[Module, int]] = {}
    for mod in modules:
        for cls in _classes(mod):
            if not cls.guarded and not cls.locks:
                continue
            for fn in cls.node.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if fn.name in EXEMPT_METHODS:
                    continue
                if cls.guarded or cls.locks:
                    _MethodChecker(cls, fn, findings, edges)
    # Inconsistent acquisition order: both A->B and B->A observed for the
    # same class's locks (the classic ABBA deadlock shape).
    reported: set[tuple[str, str, str, str]] = set()
    for (rel, qual, a, b), (mod, lineno) in sorted(
        edges.items(), key=lambda kv: (kv[1][0].rel, kv[1][1])
    ):
        if (rel, qual, b, a) in edges and (rel, qual, b, a) not in reported:
            reported.add((rel, qual, a, b))
            other_mod, other_line = edges[(rel, qual, b, a)]
            findings.append(mod.finding(
                "lock-order", lineno, f"{qual}:{a}<->{b}",
                f"inconsistent lock order in {qual}: {a} -> {b} here but "
                f"{b} -> {a} at {other_mod.rel}:{other_line} (ABBA deadlock)",
            ))
    return findings
