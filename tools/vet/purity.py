"""Reconcile/observer purity pass, on the shared call graph
(tools/vet/callgraph.py).

The control plane's dispatchers run observer callbacks SYNCHRONOUSLY on
the committing thread: `Store._drain_events` calls watchers unwrapped
under the dispatch lock, `Manager._on_event` maps watch events through
user-provided key functions, and the tracer/recorder/SLO feeds fan out
to the obs planes. A raising observer therefore propagates straight into
whichever reconcile (or serving) thread committed the write — the
invariant "observers never raise into reconcile" was prose until this
pass.

Rules (scoped to lws_tpu/ — tests may register throwaway callbacks):

  * `purity-observer-raise` — the callable registered at an observer
    registration site (`add_observer(fn)`, `add_finish_listener(fn)`,
    `store.watch(fn)`, `journey_sinks.append(fn)`) must be
    EXCEPTION-CONTAINED: every statement of its body either provably
    cannot raise (constants, name/attribute reads, calls on a small
    safe-builtin allowlist, resolvable calls whose targets are
    themselves contained) or sits inside a `try` with a broad
    (`except Exception`/bare) handler whose handler body is itself safe.
    Subscript reads, unresolvable calls, `raise`, `assert`, and
    non-trivial context managers count as "can raise". Lambda observers
    are out of scope (the resolver never guesses).

  * `purity-fleet-scan` — functions reachable from the reconcile roots
    must not scan the whole fleet per reconcile: a store `.list(<kind>)`
    with no namespace and no label/field filter is an unbounded
    whole-fleet scan, and any store `.list(...)` INSIDE a for/while body
    is per-item fan-out (O(items x objects) per tick — the serial
    fraction that dominates at the 1,000-instance regime). Roots are
    functions annotated `# reconcile-path` plus the `reconcile` methods
    of every object passed to a `register(...)` call with a resolvable
    type, plus registered observers (watch callbacks run inside the
    commit path). A scan that is genuinely unavoidable (no index exists
    and the path is rare) carries an inline
    `# vet: ignore[purity-fleet-scan]: reason`.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.vet import callgraph
from tools.vet.core import Finding, Module

PASS_NAME = "purity"

LWS_PREFIX = "lws_tpu/"
REGISTRATION_METHODS = {"add_observer", "add_finish_listener", "watch"}
SINK_LIST_ATTRS = {"journey_sinks"}

# Calls assumed non-raising on well-formed inputs — kept deliberately
# small; anything outside it needs a broad try or a resolvable, contained
# target. (getattr is only safe with an explicit default.)
SAFE_BUILTINS = {
    "len", "str", "int", "float", "bool", "repr", "id", "type",
    "isinstance", "hasattr", "callable", "round", "abs",
    "sorted", "list", "dict", "set", "tuple", "frozenset",
    "min", "max", "sum", "enumerate", "zip", "range", "format",
}
SAFE_METHODS = {
    "get", "items", "keys", "values", "copy", "append", "add",
    "discard", "setdefault", "update", "clear", "strip", "split",
    "join", "startswith", "endswith", "lower", "upper", "format",
    "monotonic", "time", "perf_counter", "notify_all", "notify",
}


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    if isinstance(handler.type, ast.Name):
        names = [handler.type.id]
    elif isinstance(handler.type, ast.Tuple):
        names = [e.id for e in handler.type.elts if isinstance(e, ast.Name)]
    return bool({"Exception", "BaseException"} & set(names))


class _Containment:
    """Memoized is-this-function-exception-contained check."""

    def __init__(self, graph: callgraph.CallGraph) -> None:
        self.graph = graph
        self.memo: dict[callgraph.Key, bool] = {}
        self._stack: set[callgraph.Key] = set()

    def contained(self, key: callgraph.Key) -> bool:
        cached = self.memo.get(key)
        if cached is not None:
            return cached
        if key in self._stack:
            return True  # recursion cycle: optimistic (the outer frame decides)
        info = self.graph.funcs.get(key)
        if info is None:
            return False
        self._stack.add(key)
        ok = all(self.stmt_ok(info, s) for s in info.node.body)
        self._stack.discard(key)
        self.memo[key] = ok
        return ok

    # ---- statements -------------------------------------------------------
    def stmt_ok(self, info: callgraph.FuncInfo, stmt: ast.stmt) -> bool:
        if isinstance(stmt, ast.Try):
            handlers_ok = all(
                all(self.stmt_ok(info, s) for s in h.body) for h in stmt.handlers
            )
            broad = any(_is_broad_handler(h) for h in stmt.handlers)
            final_ok = all(self.stmt_ok(info, s) for s in stmt.finalbody)
            orelse_ok = all(self.stmt_ok(info, s) for s in stmt.orelse)
            if broad and handlers_ok and final_ok and orelse_ok:
                return True  # the wrapper pattern: body may do anything
            body_ok = all(self.stmt_ok(info, s) for s in stmt.body)
            return body_ok and handlers_ok and final_ok and orelse_ok
        if isinstance(stmt, (ast.Pass, ast.Break, ast.Continue,
                             ast.Global, ast.Nonlocal,
                             ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom)):
            return True
        if isinstance(stmt, ast.Return):
            return self.expr_ok(info, stmt.value)
        if isinstance(stmt, ast.Expr):
            return self.expr_ok(info, stmt.value)
        if isinstance(stmt, ast.Assign):
            return all(self.target_ok(info, t) for t in stmt.targets) \
                and self.expr_ok(info, stmt.value)
        if isinstance(stmt, ast.AnnAssign):
            return self.target_ok(info, stmt.target) \
                and self.expr_ok(info, stmt.value)
        if isinstance(stmt, ast.AugAssign):
            # Aug-assign READS the target first — a Subscript target is a
            # subscript read (`seq["n"] += 1` raises KeyError).
            return isinstance(stmt.target, (ast.Name, ast.Attribute)) \
                and self.expr_ok(info, stmt.value)
        if isinstance(stmt, ast.If):
            return self.expr_ok(info, stmt.test) \
                and all(self.stmt_ok(info, s) for s in stmt.body) \
                and all(self.stmt_ok(info, s) for s in stmt.orelse)
        if isinstance(stmt, ast.While):
            return self.expr_ok(info, stmt.test) \
                and all(self.stmt_ok(info, s) for s in stmt.body) \
                and all(self.stmt_ok(info, s) for s in stmt.orelse)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self.expr_ok(info, stmt.iter) \
                and self.target_ok(info, stmt.target) \
                and all(self.stmt_ok(info, s) for s in stmt.body) \
                and all(self.stmt_ok(info, s) for s in stmt.orelse)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # Lock-like context managers (a plain name/attribute, e.g.
            # `with self._lock:`) don't raise on enter; anything fancier
            # (a call returning a CM) is opaque and counts as risky.
            for item in stmt.items:
                if not isinstance(item.context_expr, (ast.Name, ast.Attribute)):
                    return False
            return all(self.stmt_ok(info, s) for s in stmt.body)
        return False  # raise, assert, delete, match, ... — can raise

    def target_ok(self, info: callgraph.FuncInfo, target: ast.expr) -> bool:
        if isinstance(target, (ast.Name, ast.Attribute)):
            return True
        if isinstance(target, ast.Subscript):
            # A subscript WRITE (`d[k] = v`) is a plain setitem; the
            # container read underneath must still be safe.
            return self.expr_ok(info, target.value) \
                and self.expr_ok(info, target.slice)
        if isinstance(target, (ast.Tuple, ast.List)):
            return all(self.target_ok(info, t) for t in target.elts)
        return False

    # ---- expressions ------------------------------------------------------
    def expr_ok(self, info: callgraph.FuncInfo, expr: Optional[ast.expr]) -> bool:
        if expr is None or isinstance(expr, (ast.Constant, ast.Name, ast.Lambda)):
            return True
        if isinstance(expr, ast.Attribute):
            return self.expr_ok(info, expr.value)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return all(self.expr_ok(info, e) for e in expr.elts)
        if isinstance(expr, ast.Dict):
            return all(self.expr_ok(info, k) for k in expr.keys if k is not None) \
                and all(self.expr_ok(info, v) for v in expr.values)
        if isinstance(expr, ast.BoolOp):
            return all(self.expr_ok(info, v) for v in expr.values)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_ok(info, expr.operand)
        if isinstance(expr, ast.BinOp):
            return self.expr_ok(info, expr.left) and self.expr_ok(info, expr.right)
        if isinstance(expr, ast.Compare):
            return self.expr_ok(info, expr.left) \
                and all(self.expr_ok(info, c) for c in expr.comparators)
        if isinstance(expr, ast.IfExp):
            return self.expr_ok(info, expr.test) and self.expr_ok(info, expr.body) \
                and self.expr_ok(info, expr.orelse)
        if isinstance(expr, ast.JoinedStr):
            return all(self.expr_ok(info, v) for v in expr.values)
        if isinstance(expr, ast.FormattedValue):
            return self.expr_ok(info, expr.value)
        if isinstance(expr, ast.Starred):
            return self.expr_ok(info, expr.value)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.expr_ok(info, expr.elt) \
                and all(self._comp_ok(info, g) for g in expr.generators)
        if isinstance(expr, ast.DictComp):
            return self.expr_ok(info, expr.key) and self.expr_ok(info, expr.value) \
                and all(self._comp_ok(info, g) for g in expr.generators)
        if isinstance(expr, ast.Call):
            return self.call_ok(info, expr)
        return False  # Subscript (Load), Await, Yield, ... — can raise

    def _comp_ok(self, info: callgraph.FuncInfo, gen: ast.comprehension) -> bool:
        return self.expr_ok(info, gen.iter) and self.target_ok(info, gen.target) \
            and all(self.expr_ok(info, c) for c in gen.ifs)

    def call_ok(self, info: callgraph.FuncInfo, call: ast.Call) -> bool:
        args_ok = all(self.expr_ok(info, a) for a in call.args) \
            and all(self.expr_ok(info, kw.value) for kw in call.keywords)
        if not args_ok:
            return False
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in SAFE_BUILTINS:
                return True
            if fn.id == "getattr" and len(call.args) == 3:
                return True
        if isinstance(fn, ast.Attribute) and fn.attr in SAFE_METHODS \
                and self.expr_ok(info, fn.value):
            return True
        target = self.graph.resolve_call(info, call)
        if target is not None:
            return self.contained(target)
        return False


# ---------------------------------------------------------------------------
# Registration-site + root discovery


def _function_calls(info: callgraph.FuncInfo) -> list[ast.Call]:
    """Calls lexically in one function body (nested defs excluded — each
    is scanned as its own function)."""
    out: list[ast.Call] = []

    def scan(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Call):
                out.append(child)
            scan(child)

    for stmt in info.node.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan(stmt)
    return out


def _registration_arg(call: ast.Call) -> Optional[ast.expr]:
    """The observer callable of a registration call, or None."""
    fn = call.func
    if not isinstance(fn, ast.Attribute) or not call.args:
        return None
    if fn.attr in REGISTRATION_METHODS:
        return call.args[0]
    if fn.attr == "append" and isinstance(fn.value, ast.Attribute) \
            and fn.value.attr in SINK_LIST_ATTRS:
        return call.args[0]
    return None


def _store_receiver(graph: callgraph.CallGraph, info: callgraph.FuncInfo,
                    recv: ast.expr) -> bool:
    """True when `recv.list(...)` targets the object store: the receiver's
    inferred class is named Store, or — fallback for unannotated params —
    the receiver is literally named `store`/`*_store`."""
    typ = graph.resolve_receiver_type(info, recv, graph._fn_locals(info))
    if typ is not None:
        return typ[1].rsplit(".", 1)[-1] == "Store"
    name = None
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    return name is not None and (name == "store" or name.endswith("_store"))


def _scan_fleet(graph: callgraph.CallGraph, info: callgraph.FuncInfo,
                findings: list[Finding]) -> None:
    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, (ast.For, ast.AsyncFor)):
                walk(child.iter, in_loop)
                for s in child.body + child.orelse:
                    walk(s, True)
                continue
            if isinstance(child, ast.While):
                walk(child.test, in_loop)
                for s in child.body + child.orelse:
                    walk(s, True)
                continue
            if isinstance(child, ast.Call) and isinstance(child.func, ast.Attribute) \
                    and child.func.attr == "list" \
                    and _store_receiver(graph, info, child.func.value):
                kind = "?"
                if child.args and isinstance(child.args[0], ast.Constant) \
                        and isinstance(child.args[0].value, str):
                    kind = child.args[0].value
                unfiltered = len(child.args) == 1 and not child.keywords \
                    and kind != "?"
                if in_loop:
                    findings.append(info.mod.finding(
                        "purity-fleet-scan", child.lineno,
                        f"{info.qual}:list({kind})@loop",
                        f"store.list({kind!r}) inside a loop on the "
                        f"reconcile path (in {info.qual}) — per-item "
                        "fan-out multiplies into O(items x objects) per "
                        "tick; hoist one scan and group locally",
                    ))
                elif unfiltered:
                    findings.append(info.mod.finding(
                        "purity-fleet-scan", child.lineno,
                        f"{info.qual}:list({kind})",
                        f"unfiltered store.list({kind!r}) on the reconcile "
                        f"path (in {info.qual}) — a whole-fleet scan per "
                        "reconcile; scope it by namespace or label, or "
                        "index what you need",
                    ))
            walk(child, in_loop)

    # Walk from the function NODE (not per body statement) so a for/while
    # at the top level of the body still marks its own body as in-loop.
    walk(info.node, False)


def run(modules: list[Module]) -> list[Finding]:
    graph = callgraph.build(modules)
    containment = _Containment(graph)
    findings: list[Finding] = []
    observer_keys: set[callgraph.Key] = set()
    roots: set[callgraph.Key] = set()

    for key, info in graph.funcs.items():
        if info.mod.has_reconcile_mark(info.node):
            roots.add(key)
        if not info.mod.rel.startswith(LWS_PREFIX):
            continue
        for call in _function_calls(info):
            arg = _registration_arg(call)
            if arg is not None:
                target = graph.resolve_callable(info, arg)
                if target is not None:
                    observer_keys.add(target)
                    if not containment.contained(target):
                        findings.append(info.mod.finding(
                            "purity-observer-raise", call.lineno,
                            f"{info.qual}:{target[1]}",
                            f"observer {target[1]} (registered in "
                            f"{info.qual}) can raise into the dispatching "
                            "reconcile/serving thread — wrap its body in a "
                            "broad try/except or make it provably "
                            "non-raising",
                        ))
            # Reconcile roots: `<manager>.register(reconciler, ...)` with a
            # resolvable reconciler type.
            fn = call.func
            if isinstance(fn, ast.Attribute) and fn.attr == "register" \
                    and call.args:
                typ = graph.resolve_receiver_type(
                    info, call.args[0], graph._fn_locals(info)
                )
                if typ is not None:
                    method = graph.method_of(typ, "reconcile")
                    if method is not None:
                        roots.add(method)

    # Watch observers run inside the commit path — their closure is part
    # of the reconcile reachability for the fleet-scan rule.
    roots |= observer_keys
    for key in sorted(graph.reachable(roots)):
        info = graph.funcs.get(key)
        if info is None or not info.mod.rel.startswith(LWS_PREFIX):
            continue
        _scan_fleet(graph, info, findings)
    return findings
