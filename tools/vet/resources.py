"""Resource-hygiene pass: sockets, files and executors must have an
owner that closes them.

`resource-unclosed` — a resource constructor (`socket.socket`,
`socket.create_connection`, `open`, `ThreadPoolExecutor`,
`urllib.request.urlopen`) whose result is bound to a LOCAL name is fine
only when the function also does one of: use it as a `with` context,
call `.close()`/`.shutdown()` on it, return it, yield it, store it on
`self`/an object (ownership transferred), or pass it to another call
(ownership escapes). A bare constructor used as an expression statement
is flagged — nothing can ever close it — unless it sits inside a
`with pytest.raises(...)` block, where the call is EXPECTED to raise
before producing a resource (the standard error-path test shape).

`resource-ctor-leak` — the error-path variant the KV transport had: a
resource stored on `self` in a constructor, followed IN THE SAME
function by fallible setup calls on it (`bind`/`listen`/`connect`/
`wrap_socket`) outside any try — if setup raises, the constructor
aborts and the already-created resource leaks until GC. The fix shape
is `try: setup() except: res.close(); raise`.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.vet.core import Finding, Module, dotted_name

PASS_NAME = "resources"

RESOURCE_CTORS = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "open": "file",
    "ThreadPoolExecutor": "executor",
    "ProcessPoolExecutor": "executor",
    "urllib.request.urlopen": "http response",
    "urlopen": "http response",
}
FALLIBLE_SETUP = {"bind", "listen", "connect", "wrap_socket", "connect_ex"}
CLOSERS = {"close", "shutdown", "detach", "terminate", "kill"}


def _ctor_kind(call: ast.Call) -> Optional[str]:
    fn = call.func
    dotted = dotted_name(fn)
    if dotted in RESOURCE_CTORS:
        return RESOURCE_CTORS[dotted]
    if isinstance(fn, ast.Name) and fn.id in RESOURCE_CTORS:
        return RESOURCE_CTORS[fn.id]
    return None


def _functions(mod: Module):
    if mod.tree is None:
        return
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_nodes(fn: ast.AST) -> list[ast.AST]:
    """Every AST node of the function EXCLUDING nested def/lambda bodies —
    those are scanned as their own functions."""
    out: list[ast.AST] = []

    def collect(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            out.append(child)
            collect(child)

    collect(fn)
    return out


def _name_escapes(nodes: list[ast.AST], name: str, after_line: int) -> bool:
    """True when `name` is closed, with-managed, returned/yielded, stored
    on an object, or passed to a call anywhere later in the function."""
    for node in nodes:
        if getattr(node, "lineno", 0) < after_line:
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Name) \
                        and item.context_expr.id == name:
                    return True
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == name \
                    and node.func.attr in CLOSERS:
                return True
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    return True
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and isinstance(getattr(node, "value", None), ast.Name) \
                and node.value.id == name:
            return True
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == name:
                    return True  # ownership transferred to an object
    return False


def _in_try(nodes: list[ast.AST], lineno: int) -> bool:
    for node in nodes:
        if isinstance(node, ast.Try):
            # Only the guarded BODY counts: a fallible call sitting in an
            # except/else/finally of some unrelated try still leaks on
            # raise — nothing there catches it to close the resource.
            start = node.body[0].lineno
            end = getattr(node.body[-1], "end_lineno", node.body[-1].lineno)
            if start <= lineno <= end:
                return True
    return False


def _raises_ranges(nodes: list[ast.AST]) -> list[tuple[int, int]]:
    """Line ranges of `with pytest.raises(...)` bodies — resource ctors
    there are expected to raise, not to produce a resource."""
    out = []
    for node in nodes:
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call) \
                    and dotted_name(expr.func) in ("pytest.raises", "raises"):
                out.append((node.lineno, getattr(node, "end_lineno", node.lineno)))
    return out


def run(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        for fn in _functions(mod):
            qual = mod.qualname_at(fn.lineno)
            nodes = _own_nodes(fn)
            raises_spans = _raises_ranges(nodes)
            for node in nodes:
                # Bare constructor as an expression statement: unclosable.
                if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                    kind = _ctor_kind(node.value)
                    if kind is not None and not any(
                        a <= node.lineno <= b for a, b in raises_spans
                    ):
                        findings.append(mod.finding(
                            "resource-unclosed", node.lineno,
                            f"{qual}:discarded-{kind}",
                            f"{kind} created and immediately discarded — "
                            "nothing can ever close it",
                        ))
                if not isinstance(node, ast.Assign) \
                        or not isinstance(node.value, ast.Call):
                    continue
                kind = _ctor_kind(node.value)
                if kind is None:
                    continue
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    if not _name_escapes(nodes, tgt.id, node.lineno):
                        findings.append(mod.finding(
                            "resource-unclosed", node.lineno,
                            f"{qual}:{tgt.id}",
                            f"{kind} `{tgt.id}` is never closed, "
                            "with-managed, or handed off in this function",
                        ))
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    # Error-path leak: fallible setup on the fresh resource,
                    # in the same function, outside any try.
                    attr = tgt.attr
                    for later in nodes:
                        if getattr(later, "lineno", 0) <= node.lineno:
                            continue
                        if isinstance(later, ast.Call) \
                                and isinstance(later.func, ast.Attribute) \
                                and later.func.attr in FALLIBLE_SETUP:
                            recv = later.func.value
                            if isinstance(recv, ast.Attribute) \
                                    and isinstance(recv.value, ast.Name) \
                                    and recv.value.id == "self" \
                                    and recv.attr == attr \
                                    and not _in_try(nodes, later.lineno):
                                findings.append(mod.finding(
                                    "resource-ctor-leak", later.lineno,
                                    f"{qual}:{attr}.{later.func.attr}",
                                    f"self.{attr}.{later.func.attr}() can "
                                    f"raise and leak the {kind} created at "
                                    f"line {node.lineno} — wrap setup in "
                                    "try/except that closes it",
                                ))
                                break
    return findings
