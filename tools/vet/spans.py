"""Span/metric hygiene pass: observability that can't leak or lie.

`span-context-manager` — every `*.span(...)` call must be entered: used
directly as a `with` item, used as a decorator, or assigned to a local
name that a later `with` in the same function enters (the paged
engine's `dispatch_span = trace.span(...)` / `with dispatch_span:`
shape). A span constructed and never entered never closes, skewing
duration attribution and leaking the thread-local span stack.

`metric-name-literal` / `span-name-literal` — in lws_tpu/ source (the
catalogue checker's scope — ALL of it, including `lws_tpu/loadgen/`:
scenario-emitted names would otherwise fragment per scenario into
families nobody can grep for), metric and span names must be string
literals at the emission site: the docs catalogue
checker (tools/check_metrics_catalogue.py) anchors on literal first
arguments, so a dynamically-built name silently escapes the catalogue
contract that dashboards are built against. Forwarding shims whose
whole job is to pass a caller-supplied name through (core/slo.py's
`_observe`) carry an inline suppression with the reason.

`profile-phase-literal` — the profiler's explicit phase tags
(`profile.phase("name")`, core/profile.py) carry the same soundness
contract: a phase name folded into collapsed stacks must be a string
literal in lws_tpu/ source, or flamegraphs and the `lws-tpu profile`
per-span tables fragment across computed names nobody can grep for.

The registry implementation itself (lws_tpu/core/metrics.py) is exempt
from `metric-name-literal`: its module-level `inc`/`observe`/`set`
helpers forward their `name` parameter by design, and every caller-side
emission is still checked.
"""

from __future__ import annotations

import ast

from tools.vet.core import Finding, Module

PASS_NAME = "spans"

METRIC_METHODS = {"inc", "observe", "set", "describe"}
METRIC_EXEMPT_FILES = {"lws_tpu/core/metrics.py"}


def _is_metrics_receiver(node: ast.expr) -> bool:
    """`metrics`, `self.metrics`, `cp.metrics`, `REGISTRY`, `_own_metrics`:
    a Name or attribute chain whose final segment names a metrics object
    (same shape the catalogue checker walks for)."""
    if isinstance(node, ast.Name):
        return node.id in ("metrics", "metricsmod", "REGISTRY") \
            or "metrics" in node.id
    if isinstance(node, ast.Attribute):
        return node.attr in ("metrics", "REGISTRY") or "metrics" in node.attr
    return False


def _is_profile_receiver(node: ast.expr) -> bool:
    """`profile`, `profmod`, `PROFILER`, `self.profiler`: a Name or
    attribute chain whose final segment names the profiler module/object —
    the receivers of `.phase(...)` tag calls."""
    if isinstance(node, ast.Name):
        return "prof" in node.id.lower()
    if isinstance(node, ast.Attribute):
        return "prof" in node.attr.lower()
    return False


def _scopes(tree: ast.Module) -> list[list[ast.AST]]:
    """Split a module into per-scope node lists: the module body and each
    function, each EXCLUDING nested def/lambda bodies. The entered-span
    check must match assigned names within ONE scope — a `with sp:` in
    another function must not launder a leaked span that shares the
    variable name."""
    scopes: list[list[ast.AST]] = []

    def collect(root: ast.AST) -> list[ast.AST]:
        own: list[ast.AST] = []

        def inner(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    # Decorators/defaults evaluate in the ENCLOSING scope.
                    for dec in getattr(child, "decorator_list", []):
                        own.append(dec)
                        inner(dec)
                    continue
                own.append(child)
                inner(child)

        inner(root)
        return own

    scopes.append(collect(tree))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(collect(node))
    return scopes


def _unentered_spans(scope: list[ast.AST], decorator_ids: set[int]) -> list[ast.Call]:
    """`.span(...)` calls in one scope that are never entered: not a with
    item, not a decorator, not assigned to a name a `with` in the SAME
    scope enters."""
    with_items: set[int] = set()
    with_names: set[str] = set()
    assigned: dict[int, str] = {}  # id(call) -> target name
    for node in scope:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_items.add(id(item.context_expr))
                if isinstance(item.context_expr, ast.Name):
                    with_names.add(item.context_expr.id)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                assigned[id(node.value)] = node.targets[0].id
    bad = []
    for node in scope:
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute) and node.func.attr == "span"):
            continue
        if id(node) in with_items or id(node) in decorator_ids:
            continue
        target = assigned.get(id(node))
        if target is not None and target in with_names:
            continue
        bad.append(node)
    return bad


def run(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if mod.tree is None:
            continue
        # Decorator expressions (`@tracer.trace(...)`-style shapes) are
        # exempt everywhere: the wrapper enters the span at call time.
        decorator_ids: set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                for dec in node.decorator_list:
                    for sub in ast.walk(dec):
                        decorator_ids.add(id(sub))
        for scope in _scopes(mod.tree):
            for node in _unentered_spans(scope, decorator_ids):
                findings.append(mod.finding(
                    "span-context-manager", node.lineno,
                    f"{mod.qualname_at(node.lineno)}:span",
                    "span created but never entered — use `with ....span(...):`"
                    " (or enter the assigned name in the same function)",
                ))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # Name-literal rules apply to lws_tpu/ only: the catalogue
            # checker's contract covers shipped source — a test building
            # span names in a loop can't leak into dashboards.
            in_catalogue_scope = mod.rel.startswith("lws_tpu/")
            # Span names: literal first argument.
            if isinstance(fn, ast.Attribute) and fn.attr == "span":
                if in_catalogue_scope and node.args and not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    findings.append(mod.finding(
                        "span-name-literal", node.lineno,
                        f"{mod.qualname_at(node.lineno)}:span-name",
                        "span name must be a string literal (the catalogue "
                        "checker can't see a computed name)",
                    ))
                continue
            # Profiler phase tags: literal first argument (same soundness
            # contract — a computed phase fragments the collapsed stacks).
            # Both shapes: `profile.phase(...)` and the directly-imported
            # bare `phase(...)` (mirrors the describe() handling).
            is_phase = (
                isinstance(fn, ast.Attribute) and fn.attr == "phase"
                and _is_profile_receiver(fn.value)
            ) or (isinstance(fn, ast.Name) and fn.id == "phase")
            if is_phase:
                if in_catalogue_scope and node.args and not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    findings.append(mod.finding(
                        "profile-phase-literal", node.lineno,
                        f"{mod.qualname_at(node.lineno)}:phase-name",
                        "profiler phase tag must be a string literal (a "
                        "computed name fragments the collapsed-stack folds)",
                    ))
                continue
            # Metric names: literal first argument on metrics receivers.
            is_describe = (
                isinstance(fn, ast.Name) and fn.id == "describe"
            ) or (isinstance(fn, ast.Attribute) and fn.attr == "describe")
            is_metric_method = (
                isinstance(fn, ast.Attribute)
                and fn.attr in METRIC_METHODS
                and _is_metrics_receiver(fn.value)
            )
            if not (is_describe or is_metric_method):
                continue
            if mod.rel in METRIC_EXEMPT_FILES or not in_catalogue_scope:
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                findings.append(mod.finding(
                    "metric-name-literal", node.lineno,
                    f"{mod.qualname_at(node.lineno)}:metric-name",
                    "metric name must be a string literal so the docs "
                    "catalogue checker stays sound",
                ))
    return findings
