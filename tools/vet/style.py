"""Style pass: the old tools/lint.py checks, folded in unchanged.

Rules (ids prefixed `style-`): syntax errors, unused imports (suppressed
by `# noqa` on the import line or an __all__/string mention, exactly as
before), bare `except:`, mutable default arguments, `== None`
comparisons, f-strings with no placeholders, trailing whitespace, and
tabs in indentation. `make lint` now aliases `python -m tools.vet --only
style`, so existing muscle memory keeps working.
"""

from __future__ import annotations

import ast

from tools.vet.core import Finding, Module

PASS_NAME = "style"

# Files whose imports are intentional re-exports or side-effects.
REEXPORT_OK = {"__init__.py", "conftest.py"}


class _StyleVisitor(ast.NodeVisitor):
    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.problems: list[tuple[int, str, str]] = []  # (line, detail, msg)
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()
        assert mod.tree is not None
        self.visit(mod.tree)

    def problem(self, rule_detail: str, lineno: int, msg: str) -> None:
        self.problems.append((lineno, rule_detail, msg))

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # effective by existing, never "used"
        for a in node.names:
            if a.name == "*":
                continue
            self.imported.setdefault(a.asname or a.name, node.lineno)

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)

    # -- other checks ------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.problem("bare-except", node.lineno,
                         "bare `except:` (catch something specific)")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        for default in list(node.args.defaults) + list(node.args.kw_defaults):
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.problem("mutable-default", default.lineno,
                             "mutable default argument")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                isinstance(comp, ast.Constant) and comp.value is None
            ):
                self.problem("eq-none", node.lineno, "`== None` (use `is None`)")
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.problem("fstring", node.lineno, "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # Visit the value only: a format spec like {x:.1f} parses as a
        # nested JoinedStr with no placeholders — not a lint problem.
        self.visit(node.value)

    def unused_imports(self) -> list[tuple[int, str, str]]:
        out = []
        source = self.mod.source
        for name, lineno in self.imported.items():
            if name in self.used or name == "_":
                continue
            # `# noqa` on the import line suppresses (matches existing style).
            if "noqa" in self.mod.line(lineno):
                continue
            # __all__ mention counts as use.
            if f'"{name}"' in source or f"'{name}'" in source:
                continue
            out.append((lineno, name, f"unused import `{name}`"))
        return out


_RULE_BY_DETAIL = {
    "bare-except": "style-bare-except",
    "mutable-default": "style-mutable-default",
    "eq-none": "style-eq-none",
    "fstring": "style-fstring",
}


def run(modules: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    for mod in modules:
        if mod.syntax_error is not None:
            e = mod.syntax_error
            out.append(mod.finding(
                "style-syntax", e.lineno or 1, "syntax",
                f"syntax error: {e.msg}",
            ))
            continue
        visitor = _StyleVisitor(mod)
        # Details are line-FREE (the key contract: unrelated edits above a
        # finding must not churn the baseline); multiple occurrences in
        # one scope are distinguished by the baseline's occurrence counts.
        for lineno, detail, msg in visitor.problems:
            rule = _RULE_BY_DETAIL[detail]
            out.append(mod.finding(rule, lineno, detail, msg))
        if mod.path.name not in REEXPORT_OK:
            for lineno, name, msg in visitor.unused_imports():
                out.append(mod.finding("style-unused-import", lineno, name, msg))
        for i, text in enumerate(mod.lines, 1):
            if text.rstrip() != text:
                out.append(mod.finding(
                    "style-trailing-ws", i, "line", "trailing whitespace"
                ))
            stripped = text.lstrip("\t ")
            if "\t" in text[: len(text) - len(stripped)]:
                out.append(mod.finding(
                    "style-tab-indent", i, "line", "tab in indentation"
                ))
    return out
